"""Pure-JAX GPT-style decoder-only language model.

TPU-native twin of reference `models/gpt.py` (`TransformerDecoderLM`,
models/gpt.py:187-231). The model is a pure function over a parameter pytree:
`init_params(rng, config)` builds the pytree, `forward(params, config, ...)`
computes logits. There are no modules, no wrappers — parallelism is applied
from the outside as sharding on the pytree (see tpukit/shardings.py) or as a
pipeline schedule over the stacked layer parameters (see tpukit/pipeline.py).

Architecture (matching the reference layer by layer):
  - Embeddings: token + learned absolute position embeddings, summed
    (models/gpt.py:169-185). The reference's `Embeddings.__init__` reads
    `self.dim` before assigning it (models/gpt.py:177, AttributeError);
    the intended behavior — embed to `dim` — is implemented here.
  - DecoderLayer, pre-LN: `x + attn(norm1(x))`, `x + ffn(norm2(x))`
    (models/gpt.py:124-135).
  - SelfAttention: separate q/k/v projections without bias
    (`qkv_bias=False` default, models/gpt.py:50,60-62), output projection
    with bias (models/gpt.py:64), scale `1/sqrt(head_dim)` (models/gpt.py:66).
    Attention math lives in tpukit/ops/attention.py.
  - FeedForward: up-proj x4 -> relu -> down-proj -> **relu again** -> dropout
    (models/gpt.py:33-41). The second activation after down_proj is unusual
    but deliberate reference behavior; twinned faithfully.
  - Final LayerNorm then untied `lm_head = Linear(dim, vocab, bias=False)`
    (models/gpt.py:217-219).
  - `forward(input_ids, position_ids, mask)` twin of models/gpt.py:221-231;
    the reference passes an undefined `x` into embeddings (models/gpt.py:227)
    — intended `input_ids`, implemented as intended.

Layer parameters are **stacked** along a leading `num_layers` axis and the
decoder trunk is a `lax.scan` — one compiled layer body regardless of depth,
and a layout that reshapes directly into `[stages, layers_per_stage, ...]`
for pipeline parallelism.

Numerics: parameters are float32; matmuls run in `config.compute_dtype`
(bfloat16 by default — the TPU-native equivalent of the reference's
`torch.autocast(dtype=bfloat16)`, main-single.py:88-90); LayerNorm/softmax/
loss run in float32, matching autocast's op policy.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from tpukit.ops.attention import causal_attention
from tpukit.ops.layers import dropout, layer_norm, linear
from tpukit.ops.moe_dispatch import moe_ffn_a2a, moe_ffn_xla

Params = Any  # nested dict pytree of jax.Array


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model hyper-parameters.

    Defaults mirror the reference CLI defaults (main-single.py:156-162):
    dim 256, head_dim 32, heads 8, num_layers 8, seq 256, GPT-2 vocab.
    """

    dim: int = 256
    head_dim: int = 32
    heads: int = 8
    num_layers: int = 8
    vocab_size: int = 50257
    max_position_embeddings: int = 256
    dropout: float = 0.0
    ffn_mult: int = 4  # reference FeedForward mult=4 (models/gpt.py:14)
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "auto" picks per shape: XLA's fused attention below 512 tokens, the
    # Pallas flash kernel (tpukit/ops/pallas_attention.py) at 512 and above.
    # "ring" runs sequence-sharded ring attention (tpukit/ring_attention.py)
    # over the `ring_axis` mesh axis — only valid inside shard_map.
    attention_impl: str = "auto"  # "auto" | "xla" | "flash" | "ring" | "ulysses"
    ring_axis: str = "seq"
    # Sequence layout of the ring shards: "contiguous" (device d holds rows
    # [d*Sl, (d+1)*Sl)) or "zigzag" (device d holds chunks d and 2P-1-d of
    # 2P — causally load-balanced; see tpukit/ring_attention.py). Only
    # meaningful with attention_impl="ring"; ContextParallel sets it and
    # permutes the batch to match.
    ring_layout: str = "contiguous"
    # TPU perf: the embedding table and lm_head are padded so the vocab
    # dimension is a multiple of this (50257 -> 50304, a 128-lane multiple —
    # the dominant matmul of the small-dim reference shape tiles cleanly
    # onto the MXU). Logits for pad columns are forced to -1e9, so softmax,
    # loss, accuracy, and argmax sampling are unchanged; pad rows/columns
    # receive zero gradient. Set to 1 to disable.
    vocab_pad_multiple: int = 128
    # Layer-stack execution. scan_layers=False unrolls the trunk into
    # num_layers inlined blocks: measured on v5e this cuts the train step
    # ~20% at the reference depth (the scan's stacked-residual saves — a
    # dynamic-update-slice plus copy per layer — were the single largest
    # item in the profile). scan_layers=True keeps one compiled layer body:
    # use it for depths where compile time or code size matters.
    scan_layers: bool = False
    # remat_layers=True checkpoints each decoder layer: backward recomputes
    # the layer forward instead of loading saved residuals — less HBM
    # traffic AND less memory (slightly faster on v5e, and required for the
    # larger ladder configs at long sequence).
    remat_layers: bool = False
    # Compute q/k/v as one fused [dim, 3*inner] matmul (bitwise-identical
    # column blocks, better MXU tiling). TensorParallel disables this: its
    # kernels are column-sharded and concatenating along the sharded axis
    # would re-lay-out the weights every step.
    fuse_qkv: bool = True
    # Mixture-of-experts FFN (beyond-reference: the cookbook has no MoE,
    # SURVEY §2.4 marks EP "not required"). num_experts > 0 replaces every
    # layer's FFN with a Switch-style top-1 routed expert bank: a linear
    # router picks one expert per token, tokens dispatch into fixed-size
    # per-expert buffers (capacity = ceil(tokens/E * capacity_factor) —
    # STATIC shapes, the TPU requirement), overflow tokens fall through the
    # residual with zero FFN output, and a load-balance aux loss
    # (Switch Transformer eq. 4: E * sum(frac_tokens_e * mean_prob_e))
    # keeps routing uniform. Each expert applies the reference FFN
    # (up -> relu -> down -> relu, the double-relu quirk preserved). See
    # tpukit/shardings.py ExpertParallel for the expert-sharded execution.
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Compute the load-balance statistics over REAL tokens only (the Switch
    # paper's convention), excluding pad positions from frac_tokens /
    # mean_prob and normalizing by each row's real-token count (ADVICE r5
    # #2). False restores the previous behavior — statistics averaged over
    # every position including pads — for comparing against pre-round-8
    # training curves. Only the aux-loss VALUE changes; routing, dispatch,
    # and the FFN outputs are identical either way, and unpadded batches
    # produce the same aux under both settings.
    moe_aux_mask_pads: bool = True
    # routed experts per token: 1 = Switch (default), 2 = GShard/Mixtral-
    # style top-2. Gates stay the RAW router probabilities (GShard
    # convention) so top_k=1 is bit-identical to the Switch path.
    router_top_k: int = 1
    # Expert dispatch dataflow (tpukit/ops/moe_dispatch.py). "xla": global
    # one-hot einsums, partitioning left to GSPMD — the right spelling on
    # one device / pure DP, and the default so the parity goldens and the
    # single-chip bench path are untouched. "a2a": explicit shard_map
    # dispatch — tokens pack into per-expert capacity buffers and move
    # through a hand-placed lax.all_to_all pair over `moe_mesh`'s `expert`
    # axis in BOTH forward and backward. "pallas" (tpukit/ops/moe_gemm.py,
    # round 11): the fused grouped-expert GEMM — sort tokens by expert and
    # run a blocked segment GEMM, no capacity buffer, dropless unless
    # moe_capacity is set; under ExpertParallel it composes after the a2a
    # exchange. ExpertParallel injects its dispatch (and the mesh) at loss
    # time; plain model calls see only what the caller configured.
    moe_dispatch: str = "xla"  # "xla" | "a2a" | "pallas"
    moe_mesh: Any = None  # jax Mesh with an 'expert' axis (a2a/pallas under EP)
    # Explicit per-row expert capacity. 0 (default) keeps the derived
    # capacity (ceil(max_position * top_k * capacity_factor / E)) for the
    # buffer dispatches and makes the "pallas" dispatch DROPLESS; > 0
    # overrides the derived value on every dispatch — the same cumsum drop
    # mask everywhere, so "pallas" capacity mode drops the bit-identical
    # token set the buffer paths drop (tests/test_moe.py).
    moe_capacity: int = 0
    # Collective payload dtype (tpukit/ops/quant_comm.py, round 12 —
    # EQuARX-style). "f32" (default): the exact pre-round-12 collectives,
    # byte-identical HLO. "bf16"/"int8": the strategies with hand-wired
    # quantized collectives (DataParallel grad psum, FSDP grad
    # reduce-scatter, ExpertParallel a2a dispatch payload) compress the
    # wire payload — int8 is block-scaled (per-256-element max-abs f32
    # scale sidecar packed into the payload) with f32 accumulation and
    # f32 master params/optimizer throughout. Strategies without wired
    # collectives reject non-f32 values at validate_config.
    comm_dtype: str = "f32"  # "f32" | "bf16" | "int8"
    # Stochastic rounding for the int8 quantizer (floor(x/scale + U[0,1)):
    # unbiased per element, the EQuARX option against long-horizon rounding
    # drift). Default OFF — round-to-nearest-even.
    quant_stochastic: bool = False
    # Overlap-scheduled gradient collectives (round 18, ROADMAP #5 —
    # tpukit/ops/quant_comm.py bucket scheduler). 0 (default): the serial
    # schedule — one flattened payload after backward completes,
    # byte-identical HLO to round 17. N >= 1: DataParallel/FSDP partition
    # the grad tree into N ~equal-byte buckets in layer-reversed
    # (backward-completion) order and issue each bucket's collective the
    # moment its grads exist, so the remaining backward compute hides the
    # wire (1 = the serial schedule expressed in the bucket machinery —
    # the bit-parity reference for the f32 tests). Composes with
    # --comm_dtype: the int8 wire cut and the overlap win stack. Under
    # ExpertParallel the a2a exchange is already per-layer, so any
    # N >= 1 declares the hlolint `overlap` gate without changing the
    # dataflow. Strategies without a hand-placed grad wire reject N > 0
    # at validate_config.
    grad_buckets: int = 0
    # Interleaved virtual pipeline stages (round 22, ROADMAP #5 —
    # tpukit/pipeline.py Pipeline1F1B + tpukit/pipeline_schedule.py).
    # 1 (default): each pipeline device owns ONE contiguous layer block —
    # the existing GPipe/1F1B schedules, byte-identical HLO. V > 1: device
    # d owns V non-contiguous chunks (global chunks d, d+S, d+2S, ... of
    # the layer stack), and the 1F1B tick machine runs a static interleaved
    # tick table (Megatron-LM's interleaved 1F1B) so the warm-up/cool-down
    # bubble shrinks toward (S-1)/(M*V) at equal micro count M. Only the
    # explicit-vjp 1f1b schedule interleaves; Pipeline (GPipe) rejects
    # V > 1 at validate_config with a named error.
    virtual_stages: int = 1
    # Fused paged decode (round 21, ROADMAP #3 — tpukit/ops/
    # paged_attention.py). False (default): the paged decode path keeps
    # its per-layer gather_view + _attend_over_cache trace byte-unchanged.
    # True: T==1 paged steps route attention through the fused Pallas
    # kernel — block tables dereferenced inside the kernel, int8 pages
    # dequantized tile-by-tile in VMEM, single-block flash softmax —
    # the gathered view's math op-for-op (~1-ULP dot reassociation only;
    # token streams exactly identical — tests/test_paged_attention.py).
    # Prefill chunks (T>1) and the pool write-back stay on the shared
    # unfused spellings either way.
    fused_decode: bool = False

    def __post_init__(self):
        if self.comm_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"comm_dtype={self.comm_dtype!r} must be 'f32', 'bf16' or "
                f"'int8'"
            )
        if self.grad_buckets < 0:
            raise ValueError(
                f"grad_buckets={self.grad_buckets} must be >= 0 (0 = the "
                f"serial schedule, N = bucket count)"
            )
        if self.num_experts > 0 and not (1 <= self.router_top_k <= self.num_experts):
            raise ValueError(
                f"router_top_k={self.router_top_k} must be in [1, "
                f"num_experts={self.num_experts}] — silently clamping would "
                f"train a different routing than the one requested"
            )
        if self.moe_dispatch not in ("xla", "a2a", "pallas"):
            raise ValueError(
                f"moe_dispatch={self.moe_dispatch!r} must be 'xla', 'a2a' "
                f"or 'pallas'"
            )
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages={self.virtual_stages} must be >= 1 (1 = "
                f"one contiguous layer block per pipeline stage, V > 1 = "
                f"interleaved chunks under the 1f1b schedule)"
            )

    @property
    def inner_dim(self) -> int:
        return self.head_dim * self.heads

    @property
    def padded_vocab_size(self) -> int:
        m = self.vocab_pad_multiple
        return -(-self.vocab_size // m) * m

    def replace(self, **kw) -> "GPTConfig":
        return dataclasses.replace(self, **kw)


# --------------------------------------------------------------------------
# Initialization.
#
# Distributions twin the torch defaults the reference inherits:
#   nn.Linear   -> kernel & bias ~ U(-1/sqrt(fan_in), 1/sqrt(fan_in))
#   nn.Embedding-> N(0, 1)
#   nn.LayerNorm-> scale 1, bias 0
# --------------------------------------------------------------------------


def _linear_params(rng, fan_in: int, fan_out: int, bias: bool, dtype) -> dict:
    bound = 1.0 / jnp.sqrt(fan_in)
    k_rng, b_rng = jax.random.split(rng)
    p = {"kernel": jax.random.uniform(k_rng, (fan_in, fan_out), dtype, -bound, bound)}
    if bias:
        p["bias"] = jax.random.uniform(b_rng, (fan_out,), dtype, -bound, bound)
    return p


def _layer_norm_params(dim: int, dtype) -> dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def _init_decoder_layer(rng, cfg: GPTConfig) -> dict:
    """One DecoderLayer (models/gpt.py:108-135): attn + ffn + two norms.
    With cfg.num_experts > 0 the ffn is a router + stacked expert bank
    (leading axis num_experts on every expert leaf)."""
    rngs = jax.random.split(rng, 7)
    dtype = cfg.param_dtype
    if cfg.num_experts > 0:
        up = partial(
            _linear_params, fan_in=cfg.dim, fan_out=cfg.dim * cfg.ffn_mult,
            bias=True, dtype=dtype,
        )
        down = partial(
            _linear_params, fan_in=cfg.dim * cfg.ffn_mult, fan_out=cfg.dim,
            bias=True, dtype=dtype,
        )
        ffn = {
            "router": _linear_params(rngs[6], cfg.dim, cfg.num_experts, bias=False, dtype=dtype),
            "experts": {
                "up": jax.vmap(up)(jax.random.split(rngs[4], cfg.num_experts)),
                "down": jax.vmap(down)(jax.random.split(rngs[5], cfg.num_experts)),
            },
        }
    else:
        ffn = {
            "up": _linear_params(rngs[4], cfg.dim, cfg.dim * cfg.ffn_mult, bias=True, dtype=dtype),
            "down": _linear_params(rngs[5], cfg.dim * cfg.ffn_mult, cfg.dim, bias=True, dtype=dtype),
        }
    return {
        "norm1": _layer_norm_params(cfg.dim, dtype),
        "attn": {
            "q": _linear_params(rngs[0], cfg.dim, cfg.inner_dim, bias=False, dtype=dtype),
            "k": _linear_params(rngs[1], cfg.dim, cfg.inner_dim, bias=False, dtype=dtype),
            "v": _linear_params(rngs[2], cfg.dim, cfg.inner_dim, bias=False, dtype=dtype),
            "out": _linear_params(rngs[3], cfg.inner_dim, cfg.dim, bias=True, dtype=dtype),
        },
        "norm2": _layer_norm_params(cfg.dim, dtype),
        "ffn": ffn,
    }


def init_params(rng: jax.Array, cfg: GPTConfig) -> Params:
    """Build the full parameter pytree. Layer params are stacked: every leaf
    under `params["layers"]` has a leading `num_layers` axis."""
    emb_rng, pos_rng, head_rng, layers_rng = jax.random.split(rng, 4)
    dtype = cfg.param_dtype
    layer_rngs = jax.random.split(layers_rng, cfg.num_layers)
    layers = jax.vmap(partial(_init_decoder_layer, cfg=cfg))(layer_rngs)
    # vocab dims are padded to the lane multiple (cfg.padded_vocab_size);
    # pad rows are never gathered and pad logits are masked in apply_head
    return {
        "embeddings": {
            "token": jax.random.normal(emb_rng, (cfg.padded_vocab_size, cfg.dim), dtype),
            "position": jax.random.normal(pos_rng, (cfg.max_position_embeddings, cfg.dim), dtype),
        },
        "layers": layers,
        "norm_out": _layer_norm_params(cfg.dim, dtype),
        "lm_head": _linear_params(head_rng, cfg.dim, cfg.padded_vocab_size, bias=False, dtype=dtype),
    }


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


# --------------------------------------------------------------------------
# Forward pass, decomposed into embed / trunk / head so the pipeline recipe
# can place the pieces on stages (reference main-pipe.py:52-68 puts
# embeddings on the first stage and norm+lm_head on the last).
# --------------------------------------------------------------------------


def apply_embeddings(params: Params, cfg: GPTConfig, input_ids, position_ids) -> jax.Array:
    """Token + position embedding sum (models/gpt.py:180-185), cast to the
    compute dtype."""
    emb = params["embeddings"]
    x = jnp.take(emb["token"], input_ids, axis=0) + jnp.take(emb["position"], position_ids, axis=0)
    return x.astype(cfg.compute_dtype)


def _apply_feed_forward(layer, cfg: GPTConfig, x, rng, deterministic):
    """FeedForward (models/gpt.py:33-41): up -> relu -> down -> relu -> drop.
    The post-down_proj activation is the reference's (unusual) behavior."""
    h = linear(x, layer["ffn"]["up"], cfg.compute_dtype)
    h = jax.nn.relu(h)
    h = linear(h, layer["ffn"]["down"], cfg.compute_dtype)
    h = jax.nn.relu(h)
    return dropout(h, cfg.dropout, rng, deterministic)


def _apply_moe_ffn(layer, cfg: GPTConfig, x, rng, deterministic, pad_mask=None):
    """Routed mixture-of-experts FFN: Switch-style top-1 by default,
    GShard/Mixtral-style top-k via cfg.router_top_k. Returns (out, aux).

    `pad_mask` (optional `[B, S]` bool, True = padding — the attention
    convention) only affects the load-balance STATISTICS: with
    cfg.moe_aux_mask_pads (default) pad positions are excluded from
    frac_tokens/mean_prob and each row normalizes by its real-token count,
    so heavily padded batches no longer dilute the balance signal toward
    how pads route (ADVICE r5 #2). Dispatch itself still routes every
    position — masking dispatch would change the FFN outputs and break
    the width-invariance contract below.

    TPU-first design: STATIC shapes throughout — tokens dispatch into
    fixed capacity buffers, each expert runs the reference FFN (up -> relu
    -> down -> relu, the double-relu quirk, models/gpt.py:33-41) as one
    batched matmul pair on the MXU, and the gated combine returns results
    to their residual positions. Capacity is PER ROW (position within an
    expert = causal cumsum of its assignment mask along the sequence), so
    rows never compete for expert slots, and it derives from the STATIC
    max_position_embeddings — not the call's sequence width — so a row's
    dispatch is identical whatever buffer padding surrounds it: eval
    losses are batch-composition-independent and the batched decode stays
    token-for-token equal to the serial one even when their buffer widths
    differ. Tokens beyond an expert's row capacity get zero FFN output
    (they ride the residual stream). Router math is f32 (softmax stability
    under bf16 compute). `aux` is the Switch load-balance loss
    E * sum(frac_tokens_e * mean_router_prob_e), averaged over rows — 1.0
    at perfect balance. The KV-cached decode routes each chunk with its
    own capacity window, so a capacity-dropped token can differ from the
    full-reforward path there — use_cache=False is exact for the buffer
    dispatches. EXCEPTION (round 14): with moe_dispatch="pallas" and no
    moe_capacity override the dataflow is DROPLESS — every routed token
    computes regardless of chunk composition, per-token routing depends
    only on that token's activations, and the cached decode is therefore
    exactly the full-reforward decode (cached==uncached equivalence in
    tests/test_serve.py); sampling's use_cache auto-resolve treats that
    case as exact (tpukit/sampling._cached_decode_exact).

    The dispatch DATAFLOW is pluggable (cfg.moe_dispatch, implementations
    in tpukit/ops/moe_dispatch.py and tpukit/ops/moe_gemm.py): "xla"
    computes global one-hot dispatch/combine einsums and leaves
    partitioning to GSPMD; "a2a" (the ExpertParallel default) hand-places
    the token exchange as a lax.all_to_all pair over the `expert` mesh
    axis inside shard_map — identical math, and the backward is also an
    all_to_all pair instead of the GSPMD replicate-repartition fallback
    the einsum transpose provokes (MULTICHIP_r05.json); "pallas" sorts
    tokens by expert and runs the fused Pallas segment GEMM — no capacity
    buffer or padding FLOPs, dropless unless cfg.moe_capacity is set, and
    under ExpertParallel it rides the same a2a exchange. Dropout applies
    to the combined output, outside every dataflow, so all three stay
    loss/grad-parity-equal.
    """
    if cfg.moe_dispatch == "pallas":
        from tpukit.ops.moe_gemm import moe_ffn_pallas

        impl = moe_ffn_pallas
    else:
        impl = moe_ffn_a2a if cfg.moe_dispatch == "a2a" else moe_ffn_xla
    out, aux = impl(layer, cfg, x, pad_mask=pad_mask)
    return dropout(out, cfg.dropout, rng, deterministic), aux


def _apply_attention(layer, cfg: GPTConfig, x, pad_mask, rng, deterministic):
    """SelfAttention (models/gpt.py:68-105).

    The q/k/v parameters stay separate (exact reference surface,
    models/gpt.py:60-62) but compute as ONE fused [dim, 3*inner] matmul:
    column blocks of a wider matmul are bitwise identical to the three
    narrow ones, and the 3x-wider N dimension tiles the MXU far better at
    the reference's small dim."""
    batch, seq_len = x.shape[0], x.shape[1]
    if cfg.fuse_qkv:
        qkv_kernel = jnp.concatenate(
            [layer["attn"][n]["kernel"] for n in ("q", "k", "v")], axis=1
        )
        qkv = linear(x, {"kernel": qkv_kernel}, cfg.compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    else:
        q = linear(x, layer["attn"]["q"], cfg.compute_dtype)
        k = linear(x, layer["attn"]["k"], cfg.compute_dtype)
        v = linear(x, layer["attn"]["v"], cfg.compute_dtype)

    split = lambda t: t.reshape(batch, seq_len, cfg.heads, cfg.head_dim).transpose(0, 2, 1, 3)
    out = causal_attention(
        split(q),
        split(k),
        split(v),
        scale=1.0 / (cfg.head_dim**0.5),
        pad_mask=pad_mask,
        impl=cfg.attention_impl,
        ring_axis=cfg.ring_axis,
        ring_layout=cfg.ring_layout,
    )
    out = out.transpose(0, 2, 1, 3).reshape(batch, seq_len, cfg.inner_dim)
    out = linear(out, layer["attn"]["out"], cfg.compute_dtype)
    return dropout(out, cfg.dropout, rng, deterministic)


def apply_decoder_layer(layer: Params, cfg: GPTConfig, x, pad_mask, rng=None, deterministic=True):
    """Pre-LN block (models/gpt.py:124-135). With cfg.num_experts > 0 the
    FFN is the routed expert bank and the return is `(x, aux)` — the
    branch is on a STATIC config field, so the dense path's signature and
    compiled graph are untouched."""
    if rng is None:
        attn_rng = ffn_rng = None
    else:
        attn_rng, ffn_rng = jax.random.split(rng)
    h = layer_norm(x, layer["norm1"]).astype(cfg.compute_dtype)
    x = x + _apply_attention(layer, cfg, h, pad_mask, attn_rng, deterministic)
    h = layer_norm(x, layer["norm2"]).astype(cfg.compute_dtype)
    if cfg.num_experts > 0:
        ffn_out, aux = _apply_moe_ffn(
            layer, cfg, h, ffn_rng, deterministic, pad_mask=pad_mask
        )
        return x + ffn_out, aux
    x = x + _apply_feed_forward(layer, cfg, h, ffn_rng, deterministic)
    return x


def apply_decoder_layers(
    stacked_layers: Params, cfg: GPTConfig, x, pad_mask, rng=None, deterministic=True,
    active=None, aux_out: list | None = None,
) -> jax.Array:
    """Sequential layer stack (models/gpt.py:161-167) over the stacked layer
    parameters. Works for any leading stack size, so pipeline stages call it
    on their `[layers_per_stage, ...]` slice.

    `active` (optional bool [num]): per-slot gate for padding layers in
    uneven pipeline layouts — an inactive slot passes `x` through unchanged
    and its parameters receive zero gradient (the `where` selects the
    residual stream, so the layer branch is dead in the backward pass).

    `aux_out` (MoE only): a list the summed per-layer load-balance aux loss
    is appended to — a trace-time side channel, appended OUTSIDE any scan
    body so no tracer leaks. Ignored for dense configs.

    Execution is controlled by cfg.scan_layers (unrolled blocks vs one
    lax.scan body) and cfg.remat_layers (checkpoint each layer); see the
    GPTConfig field docs for the measured trade-offs. Both paths are
    numerically identical (tests/test_model.py::test_scan_matches_unrolled).
    """
    num = jax.tree_util.tree_leaves(stacked_layers)[0].shape[0]
    moe = cfg.num_experts > 0

    layer_fn = apply_decoder_layer
    if cfg.remat_layers:
        layer_fn = jax.checkpoint(
            apply_decoder_layer, static_argnums=(1, 5)
        )

    if rng is None:
        rngs = jnp.zeros((num, 2), dtype=jnp.uint32)
        use_rng = False
    else:
        rngs = jax.random.split(rng, num)
        use_rng = True

    if not cfg.scan_layers:
        aux_total = jnp.float32(0)
        for i in range(num):
            layer = jax.tree_util.tree_map(lambda t: t[i], stacked_layers)
            y = layer_fn(
                layer, cfg, x, pad_mask, rngs[i] if use_rng else None, deterministic
            )
            if moe:
                y, aux = y
                aux_total = aux_total + (
                    aux if active is None else jnp.where(active[i], aux, 0.0)
                )
            x = y if active is None else jnp.where(active[i], y, x)
        if moe and aux_out is not None:
            aux_out.append(aux_total)
        return x

    if active is None:
        active = jnp.ones((num,), dtype=bool)
        gate = False
    else:
        gate = True

    def body(carry, scanned):
        layer, layer_rng, act = scanned
        x, aux_total = carry
        out = layer_fn(
            layer, cfg, x, pad_mask, layer_rng if use_rng else None, deterministic
        )
        if moe:
            out, aux = out
            aux_total = aux_total + jnp.where(act, aux.astype(jnp.float32), 0.0)
        if gate:
            out = jnp.where(act, out, x)
        return (out, aux_total), None

    (x, aux_total), _ = jax.lax.scan(
        body, (x, jnp.float32(0)), (stacked_layers, rngs, active)
    )
    if moe and aux_out is not None:
        aux_out.append(aux_total)
    return x


# --------------------------------------------------------------------------
# KV-cached decode path (no reference counterpart: the reference re-forwards
# the whole growing sequence per generated token, utils.py:63-64 — a known
# wart SURVEY §3.5 flags. Used by tpukit/sampling.py).
# --------------------------------------------------------------------------


def init_kv_cache(cfg: GPTConfig, batch: int, max_len: int) -> dict:
    """Per-layer stacked K/V buffers: `[num_layers, B, heads, max_len, d]`."""
    shape = (cfg.num_layers, batch, cfg.heads, max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
    }


def _apply_attention_cached(layer, cfg: GPTConfig, x, k_cache, v_cache, start):
    """Attention for decode: write this chunk's K/V into the cache at
    `start` and attend over all cached positions `<= query position`.
    x: [B, T, dim]; k_cache/v_cache: [B, heads, S_max, d]. Returns
    (out, k_cache, v_cache).

    `start` is a scalar (every row writes at the same offset — the
    single-sequence decode and the full-width batched prefill) or a
    `[B]` vector of PER-ROW offsets (the continuous-batching decode
    step, tpukit/serve: each slot sits at its own cursor). The scalar
    path keeps its original dynamic-update-slice trace byte-unchanged;
    the vector path vmaps the cache write over rows and offsets each
    row's query position independently — identical math per row."""
    batch, t = x.shape[0], x.shape[1]
    q = linear(x, layer["attn"]["q"], cfg.compute_dtype)
    k = linear(x, layer["attn"]["k"], cfg.compute_dtype)
    v = linear(x, layer["attn"]["v"], cfg.compute_dtype)
    split = lambda z: z.reshape(batch, t, cfg.heads, cfg.head_dim).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)

    s_max = k_cache.shape[2]
    if jnp.ndim(start) == 1:
        upd = lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (0, s, 0))
        k_cache = jax.vmap(upd)(k_cache, k, start)
        v_cache = jax.vmap(upd)(v_cache, v, start)
        q_pos = (start[:, None] + jnp.arange(t))[:, None, :, None]
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, 0, start, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, 0, start, 0))
        q_pos = (start + jnp.arange(t))[None, None, :, None]

    out = _attend_over_cache(layer, cfg, q, k_cache, v_cache, q_pos)
    return out, k_cache, v_cache


def _attend_over_cache(layer, cfg: GPTConfig, q, k_cache, v_cache, q_pos):
    """The cached-attention read: scores over every cache position, causal
    `key_pos <= q_pos` window, softmax, value mix, output projection. ONE
    spelling shared by the ring path above and the paged path below —
    masked positions softmax to exact zeros (exp underflows in f32) and
    exact zeros annihilate whatever garbage the masked cache slots hold,
    which is why the two storage layouts produce bit-identical outputs
    for the same logical K/V (the paged parity bar, tests/test_paged.py).
    """
    batch, t = q.shape[0], q.shape[2]
    s_max = k_cache.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache) * (1.0 / cfg.head_dim**0.5)
    key_pos = jnp.arange(s_max)[None, None, None, :]
    scores = jnp.where(key_pos <= q_pos, scores, jnp.asarray(-1e9, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v_cache)
    out = out.transpose(0, 2, 1, 3).reshape(batch, t, cfg.inner_dim)
    return linear(out, layer["attn"]["out"], cfg.compute_dtype)


def _apply_attention_paged(layer, cfg: GPTConfig, x, pool_k, pool_v,
                           scale_k, scale_v, bt, start, write_mask,
                           mesh=None):
    """Attention for decode over the PAGED cache (round 15, ROADMAP #2):
    the per-row-cursor indirection of the vector path above with one extra
    hop — each row's K/V comes from fixed-size pages dereferenced through
    its block-table row `bt [B, MP]` instead of a contiguous ring slice.

    The gather (`serve.paged.gather_view`) materializes exactly the
    `[B, H, MP*P, D]` per-row view the vector path writes and attends, the
    chunk's fresh K/V is written into the view with the SAME vmapped
    dynamic-update-slice, and the attend math is `_attend_over_cache`
    verbatim — so for page storage at the compute dtype the outputs are
    bit-identical to the ring path and the parity bar transfers. The only
    paged-specific math is the write-back: the fresh K/V also lands in the
    pool (single position for decode T==1, whole pages for a prefill
    chunk — `start` page-aligned and T a page multiple, the engine's
    chunking contract), with `write_mask`-False rows routed to the null
    page so inactive/prefilling slots never touch a page another slot may
    own. int8 pools dequantize after the gather and requantize written
    rows (lossy — gated by tolerance, never claimed exact).

    Under a serving mesh the pools shard heads-over-`model` and stay
    replicated across `data` (the engine enforces a model-only grid for
    paged serving): gather and scatter index only the unsharded page axis
    with replicated indices, so the paged hop adds ZERO collectives — the
    `decode_step_comm` closed form is unchanged and the compiled HLO must
    still match it exactly (tests/test_paged.py)."""
    from tpukit.serve import paged as paged_lib  # lazy: tpukit.serve imports gpt

    batch, t = x.shape[0], x.shape[1]
    q = linear(x, layer["attn"]["q"], cfg.compute_dtype)
    k = linear(x, layer["attn"]["k"], cfg.compute_dtype)
    v = linear(x, layer["attn"]["v"], cfg.compute_dtype)
    split = lambda z: z.reshape(batch, t, cfg.heads, cfg.head_dim).transpose(0, 2, 1, 3)
    q, k, v = split(q), split(k), split(v)

    if cfg.fused_decode and t == 1:
        # round 21: the decode tick skips the materialized gather — the
        # fused kernel walks the block tables itself (same math op-for-op
        # as the gathered path; ~1-ULP dot reassociation, exact token
        # parity — tests/test_paged_attention.py). [B,H,D] out == the
        # reference transpose+reshape for T==1, so the projection line
        # is shared.
        from tpukit.ops import paged_attention as paged_kernel

        attn = paged_kernel.fused_paged_attention(
            pool_k, pool_v, scale_k, scale_v, bt, start,
            q[:, :, 0, :], k[:, :, 0, :], v[:, :, 0, :], mesh=mesh,
        )
        out = linear(attn.reshape(batch, 1, cfg.inner_dim),
                     layer["attn"]["out"], cfg.compute_dtype)
    else:
        view_k = paged_lib.gather_view(pool_k, scale_k, bt, cfg.compute_dtype)
        view_v = paged_lib.gather_view(pool_v, scale_v, bt, cfg.compute_dtype)
        upd = lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (0, s, 0))
        view_k = jax.vmap(upd)(view_k, k, start)
        view_v = jax.vmap(upd)(view_v, v, start)
        q_pos = (start[:, None] + jnp.arange(t))[:, None, :, None]
        out = _attend_over_cache(layer, cfg, q, view_k, view_v, q_pos)

    if t == 1:
        pool_k, scale_k = paged_lib.write_token(
            pool_k, scale_k, bt, start, k[:, :, 0, :], write_mask
        )
        pool_v, scale_v = paged_lib.write_token(
            pool_v, scale_v, bt, start, v[:, :, 0, :], write_mask
        )
    else:
        pool_k, scale_k = paged_lib.write_pages(pool_k, scale_k, bt, start, k, write_mask)
        pool_v, scale_v = paged_lib.write_pages(pool_v, scale_v, bt, start, v, write_mask)
    return out, pool_k, pool_v, scale_k, scale_v


def forward_cached(params: Params, cfg: GPTConfig, input_ids, position_ids,
                   cache, start, write_mask=None, mesh=None):
    """Forward a chunk of tokens with the KV cache: writes K/V for positions
    `[start, start+T)` and returns `(logits [B, T, padded_vocab], cache)`.
    Prefill with the prompt chunk, then decode with T=1 per step. `start`
    is a scalar offset shared by every row, or a `[B]` vector of per-row
    offsets (the continuous-batching decode step — see
    `_apply_attention_cached`).

    `cache` is either the contiguous ring (`init_kv_cache`) or the paged
    pytree (`serve.paged.init_paged_cache`, detected by its `"bt"` block
    tables — round 15): paged caches require a vector `start` and route
    each layer through `_apply_attention_paged`, with `write_mask [B]`
    (default all-True) gating which rows' K/V reach the pool — the paged
    engine passes the live-slot mask so an inactive lane's re-forward can
    never write a page it no longer owns. The ring path ignores
    `write_mask` and keeps its original trace byte-unchanged.

    `mesh` matters only for the paged path with `cfg.fused_decode`: the
    fused kernel must run inside shard_map when heads are sharded over a
    `model` axis (GSPMD cannot partition a pallas_call) — the serve
    decode step threads its mesh through here."""
    paged = isinstance(cache, dict) and "bt" in cache
    if paged:
        bt = cache["bt"]
        if jnp.ndim(start) != 1:
            raise ValueError(
                "paged forward_cached requires a [B] vector `start` (each "
                "row sits at its own cursor through its block table)"
            )
        if write_mask is None:
            write_mask = jnp.ones((bt.shape[0],), bool)
        quant = "ks" in cache
    x = apply_embeddings(params, cfg, input_ids, position_ids)
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for i in range(cfg.num_layers):
        layer = jax.tree_util.tree_map(lambda t: t[i], params["layers"])
        h = layer_norm(x, layer["norm1"]).astype(cfg.compute_dtype)
        if paged:
            attn, k_c, v_c, ks_c, vs_c = _apply_attention_paged(
                layer, cfg, h, cache["k"][i], cache["v"][i],
                cache["ks"][i] if quant else None,
                cache["vs"][i] if quant else None,
                bt, start, write_mask, mesh=mesh,
            )
            new_ks.append(ks_c)
            new_vs.append(vs_c)
        else:
            attn, k_c, v_c = _apply_attention_cached(
                layer, cfg, h, cache["k"][i], cache["v"][i], start
            )
        new_k.append(k_c)
        new_v.append(v_c)
        x = x + attn
        h = layer_norm(x, layer["norm2"]).astype(cfg.compute_dtype)
        if cfg.num_experts > 0:
            ffn_out, _ = _apply_moe_ffn(layer, cfg, h, None, True)
            x = x + ffn_out
        else:
            x = x + _apply_feed_forward(layer, cfg, h, None, True)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if paged:
        cache["bt"] = bt
        if quant:
            cache["ks"] = jnp.stack(new_ks)
            cache["vs"] = jnp.stack(new_vs)
    return apply_head(params, cfg, x), cache


def forward_hidden(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,
    position_ids: jax.Array,
    mask: jax.Array | None = None,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    aux_out: list | None = None,
) -> jax.Array:
    """Everything up to (and including) the final LayerNorm — the hidden
    states the LM head consumes. Split out so the fused head+CE kernel
    (tpukit/ops/fused_head_ce.py) can take over from here without the
    logits ever materializing; `forward` == `apply_head`-minus-norm of
    this."""
    x = apply_embeddings(params, cfg, input_ids, position_ids)
    x = apply_decoder_layers(
        params["layers"], cfg, x, mask, rng, deterministic, aux_out=aux_out
    )
    return layer_norm(x, params["norm_out"]).astype(cfg.compute_dtype)


def apply_head(params: Params, cfg: GPTConfig, x) -> jax.Array:
    """Final LayerNorm + untied lm_head (models/gpt.py:217-219,229-231).

    Returns `[B, S, padded_vocab_size]`; pad columns (if any) are -1e9, so
    every softmax/argmax consumer behaves as with the logical vocab and the
    pad columns get zero gradient."""
    x = layer_norm(x, params["norm_out"]).astype(cfg.compute_dtype)
    logits = linear(x, params["lm_head"], cfg.compute_dtype)
    if cfg.padded_vocab_size != cfg.vocab_size:
        col = jax.lax.broadcasted_iota(jnp.int32, (cfg.padded_vocab_size,), 0)
        logits = jnp.where(
            col < cfg.vocab_size, logits, jnp.asarray(-1e9, logits.dtype)
        )
    return logits


def forward(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,
    position_ids: jax.Array,
    mask: jax.Array | None = None,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    aux_out: list | None = None,
) -> jax.Array:
    """Full model: logits `[B, S, vocab]` in the compute dtype.

    Twin of `TransformerDecoderLM.forward` (models/gpt.py:221-231, with the
    undefined-`x` bug fixed to the intended `input_ids`). `mask` is `[B, S]`
    bool, True = padding (the inverted convention produced by
    `prepare_batch`, reference utils.py:36).
    """
    x = apply_embeddings(params, cfg, input_ids, position_ids)
    x = apply_decoder_layers(
        params["layers"], cfg, x, mask, rng, deterministic, aux_out=aux_out
    )
    return apply_head(params, cfg, x)


class TransformerDecoderLM:
    """Thin OO veneer over the functional model, mirroring the reference's
    constructor surface (models/gpt.py:187-208) for users arriving from it.

    `model = TransformerDecoderLM(dim=..., ...); params = model.init(rng);
    logits = model(params, input_ids, position_ids, mask)`.
    """

    def __init__(
        self,
        dim: int,
        head_dim: int,
        heads: int,
        num_layers: int,
        vocab_size: int,
        max_position_embeddings: int,
        dropout: float = 0.0,
        **kw,
    ):
        self.config = GPTConfig(
            dim=dim,
            head_dim=head_dim,
            heads=heads,
            num_layers=num_layers,
            vocab_size=vocab_size,
            max_position_embeddings=max_position_embeddings,
            dropout=dropout,
            **kw,
        )

    @property
    def vocab_size(self) -> int:
        return self.config.vocab_size

    def init(self, rng: jax.Array) -> Params:
        return init_params(rng, self.config)

    def __call__(self, params, input_ids, position_ids, mask=None, rng=None, deterministic=True):
        return forward(params, self.config, input_ids, position_ids, mask, rng, deterministic)
