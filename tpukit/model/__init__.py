"""Model zoo. The reference ships exactly one model — a GPT-style decoder LM
re-exported at reference `models/__init__.py:1`; this package mirrors that
surface with the pure-JAX twin."""

from tpukit.model.gpt import (  # noqa: F401
    GPTConfig,
    TransformerDecoderLM,
    apply_decoder_layers,
    apply_embeddings,
    apply_head,
    forward,
    init_params,
)
