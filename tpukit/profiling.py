"""Compatibility shim — the profiling layer moved to `tpukit.obs`.

The round-6 telemetry subsystem (`tpukit/obs/`) absorbed this module's
MFUMeter / trace / StepLogger and added span timelines, XLA static
analysis, training-health sentinels, and multi-host heartbeats. Import
from `tpukit.obs` in new code; this shim keeps old import sites working.
"""

from tpukit.obs.meter import (  # noqa: F401
    MFUMeter,
    StepLogger,
    matmul_param_count,
    peak_flops_per_chip,
    trace,
    train_flops_per_token,
)
