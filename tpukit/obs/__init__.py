"""tpukit.obs — the telemetry subsystem.

Supersedes the old flat `tpukit/profiling.py` (now a compat shim). The
pillars, one per module:

  - `meter`      — MFUMeter (tokens/sec, MFU), `profiler_trace`, JSONL
                   `StepLogger`.
  - `trace`      — request-scoped serving traces (round 20):
                   `TraceRecorder` span-event rings, per-request span
                   trees with phase walls (queue_wait/prefill/handoff/
                   decode/sync_stall), the completeness invariant and
                   the Chrome-trace exporter behind `tools/traceview.py`.
  - `metrics`    — mergeable fleet metrics (round 22): counters, gauges
                   and log-bucket histograms with ONE edge table
                   everywhere (merge = bucket-wise sum, exact), SLO
                   compliance + error-budget burn accounting, atomic
                   per-process snapshot files merged by process 0, and
                   the OpenMetrics textfile exporter behind
                   `tools/top.py`.
  - `spans`      — `SpanTimeline`: host-phase wall-clock accounting and the
                   goodput breakdown (fraction of time inside the compiled
                   step vs data wait / H2D / checkpoint / eval).
  - `xla`        — static analysis of compiled steps: `cost_analysis` FLOPs
                   and bytes, `memory_analysis` peak HBM, per-collective
                   comm bytes parsed from the optimized HLO, plus live
                   `device.memory_stats()` gauges.
  - `sentinels`  — in-jit global grad/update/param norms and the host-side
                   loss-spike/NaN `SpikeSentinel`.
  - `heartbeat`  — per-process liveness files + process-0 straggler and
                   cross-replica divergence checks for multi-host runs.
  - `recorder`   — `FlightRecorder`: always-on bounded ring of the loop's
                   recent history, serialized into diagnostics bundles.
  - `watchdog`   — `HangWatchdog` (hung-step deadline monitor + bundle
                   dumps), `AnomalyTracer` (trace-on-anomaly profiler
                   capture), `write_bundle`/`all_thread_stacks`.
  - `divergence` — periodic in-jit param/opt-state checksums compared
                   across data-parallel replicas via the heartbeat files.

The trainer (`tpukit/train.py`) wires all of it through `fit()`;
`tools/report.py` renders a run's JSONL and `tools/flightview.py` renders
a diagnostics bundle into a human-readable post-mortem.
"""

from tpukit.obs.divergence import (  # noqa: F401
    format_checksum,
    make_state_checksum,
    tree_checksum,
)
from tpukit.obs.heartbeat import Heartbeat  # noqa: F401
from tpukit.obs.meter import (  # noqa: F401
    MFUMeter,
    StepLogger,
    matmul_param_count,
    moe_active_flops_per_token,
    peak_flops_per_chip,
    profiler_trace,
    train_flops_per_token,
)
from tpukit.obs.metrics import (  # noqa: F401
    Histogram,
    MetricRegistry,
    SloAccountant,
    SloSpecError,
    SloTarget,
    merge_snapshot_dir,
    parse_slo,
    publish_snapshot,
    to_openmetrics,
    write_merged,
)
from tpukit.obs.recorder import FlightRecorder  # noqa: F401
from tpukit.obs.trace import (  # noqa: F401
    PHASES,
    TraceRecorder,
    build_trees,
    completeness,
    flush_to_logger,
    phase_stats,
    request_trace_id,
    to_chrome,
)
from tpukit.obs.sentinels import SpikeEvent, SpikeSentinel, global_norms  # noqa: F401
from tpukit.obs.spans import GOODPUT_SPANS, SpanTimeline, format_breakdown  # noqa: F401
from tpukit.obs.watchdog import (  # noqa: F401
    AnomalyTracer,
    HangWatchdog,
    all_thread_stacks,
    write_bundle,
)
from tpukit.obs.xla import (  # noqa: F401
    COLLECTIVE_OPS,
    INVOLUNTARY_REMAT,
    capture_compiler_stderr,
    collective_bytes,
    compiled_stats,
    count_involuntary_remat,
    live_memory_stats,
    wire_bytes,
)
