"""Mergeable metrics for the fleet: counters, gauges, log-bucket
histograms, SLO accounting (round 22, ROADMAP #1/#2b).

The serving JSONL already answers "how did THIS run go" (window
aggregates, PR 16's span trees). What it cannot answer is anything that
requires *combining* distributions — p99 across a fleet's replicas,
across a multi-host world's processes, or across two runs a week apart.
Sampled percentiles don't merge (the p99 of two p99s is not the fleet
p99); raw sample lists don't bound memory. This module takes the
classic fixed-bucket route instead:

**Histograms are log-spaced fixed-bucket counters.** Every histogram in
every process shares ONE edge table: ``EDGES[k] = LO * GROWTH**k`` with
``GROWTH = 2**(1/8)`` (8 buckets per octave), ``LO = 1e-6`` s and
``N_BUCKETS`` finite buckets spanning 1 µs .. ~4.8 h, plus an underflow
and an overflow bucket. Identical edges everywhere means

    merge(A, B) = bucket-wise sum          (exact, associative,
                                            commutative — no sketch
                                            error, no sample loss)

so replica/process/run aggregation is closed-form and auditable (*The
Big Send-off* discipline applied to telemetry). Quantiles are derived
from the buckets: nearest-rank over the cumulative counts, estimated at
the straddling bucket's geometric midpoint ``sqrt(lo*hi)`` and clamped
to the tracked exact [min, max]. Since every sample in bucket
[e, e*GROWTH) is within a factor ``sqrt(GROWTH)`` of the midpoint, the
estimate's RELATIVE ERROR is bounded by

    sqrt(GROWTH) - 1 = 2**(1/16) - 1 ≈ 4.4%

for any sample in [LO, HI); underflow/overflow samples clamp to the
exact min/max instead (tests/test_metrics.py proves the bound against
exact sorted data on adversarial distributions). Counters merge by sum,
gauges are point-in-time (label them per replica/process; on a merge
collision the later snapshot wins — only histograms and counters claim
exact associative merge).

Metrics are labeled (``{replica, phase, class}`` is the vocabulary the
serving stack uses); a (name, sorted-labels) pair is one time series.

**Snapshot files** follow the heartbeat-file discipline
(`tpukit/obs/heartbeat.py`): each process atomically publishes
``metrics-p{index:05d}.json`` into a shared ``--metrics_dir``
(tmp-sibling + rename, so a reader never sees a torn file), readers
skip-and-count torn/foreign files rather than raising, and records from
a stale incarnation (``process >= process_count`` after an elastic
reshard shrank the world) are excluded the same way heartbeat's
straggler check excludes them. Process 0 merges everything by bucket
sum — the metrics half of ROADMAP #1.

**SLO accounting**: ``parse_slo("ttft<=250ms@p99;tpot<=40ms@p95")``
parses the declared objectives at startup (typos fail fast with pointed
errors — the chaos-grammar discipline), and `SloAccountant` turns each
window's samples into a compliance fraction and an error-budget burn
rate (violation fraction over the budget ``1 - q``; burn 1.0 means
exactly consuming budget, >1 means burning toward violation).

Deliberately stdlib-only (no jax, no numpy, no tpukit imports):
`tools/top.py` and `tools/report.py --compare` load this file by path
so dashboards and post-mortems run anywhere, like trace/flightview.
`tools/lint_invariants.py`'s stdlib-only rule asserts this stays true
(trace.py rule, second owner).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from pathlib import Path

# ---- the one bucket table ------------------------------------------------

# 8 buckets per octave: quantile relative error <= 2**(1/16)-1 ~ 4.4%.
GROWTH = 2.0 ** 0.125
# First finite edge: 1 microsecond. 272 finite buckets = 34 octaves,
# so the last edge is 1e-6 * 2**34 ~ 1.7e4 s (~4.8 h) — generous for
# both per-token walls and end-to-end request lifetimes.
LO = 1e-6
N_BUCKETS = 272
EDGES = tuple(LO * GROWTH**k for k in range(N_BUCKETS + 1))
HI = EDGES[-1]
_LOG_G = math.log(GROWTH)

# Bucket layout: index 0 is underflow (< LO), 1..N_BUCKETS hold
# [EDGES[i-1], EDGES[i]), N_BUCKETS+1 is overflow (>= HI).
UNDERFLOW = 0
OVERFLOW = N_BUCKETS + 1

# Bound proven by construction and asserted in tests: any quantile
# estimate for a sample in [LO, HI) is within this relative error.
QUANTILE_REL_ERROR = math.sqrt(GROWTH) - 1.0


def bucket_index(v: float) -> int:
    """Bucket index of a sample — THE one placement function, shared by
    every process so merged histograms are bucket-exact comparable."""
    if v < LO:
        return UNDERFLOW
    if v >= HI:
        return OVERFLOW
    i = int(math.log(v / LO) / _LOG_G) + 1
    # float log can land one off at an edge; restore the invariant
    # v in [EDGES[i-1], EDGES[i]) exactly
    while i > 1 and v < EDGES[i - 1]:
        i -= 1
    while i <= N_BUCKETS and v >= EDGES[i]:
        i += 1
    return min(max(i, UNDERFLOW), OVERFLOW)


class Histogram:
    """One log-bucket histogram: sparse bucket counts plus exact
    count/sum/min/max (all of which also merge exactly: sum, sum, min,
    max). O(1) observe, O(nonzero buckets) merge/quantile."""

    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float, n: int = 1) -> None:
        if n <= 0:
            return
        v = float(v)
        i = bucket_index(v)
        self.buckets[i] = self.buckets.get(i, 0) + n
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def merge(self, other: "Histogram") -> None:
        """Bucket-wise sum — exact, associative, commutative."""
        for i, n in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + n
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile estimate from the buckets, relative
        error <= QUANTILE_REL_ERROR for samples in [LO, HI); underflow/
        overflow ranks clamp to the exact tracked min/max."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        rank = max(0, math.ceil(q * self.count) - 1)  # 0-based
        cum = 0
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if rank < cum:
                if i == UNDERFLOW:
                    est = self.min
                elif i == OVERFLOW:
                    est = self.max
                else:
                    est = math.sqrt(EDGES[i - 1] * EDGES[i])
                return float(min(max(est, self.min), self.max))
        return float(self.max)  # unreachable unless counts drifted

    def fraction_le(self, bound: float) -> float | None:
        """Fraction of samples <= bound, linearly interpolated inside
        the straddling bucket (bucket-resolution accuracy — exact when
        the bound lands on an edge)."""
        if self.count == 0:
            return None
        le = 0.0
        for i, n in self.buckets.items():
            if i == UNDERFLOW:
                lo, hi = 0.0, LO
            elif i == OVERFLOW:
                lo, hi = HI, max(self.max, HI)
            else:
                lo, hi = EDGES[i - 1], EDGES[i]
            if bound >= hi:
                le += n
            elif bound > lo:
                le += n * (bound - lo) / max(hi - lo, 1e-300)
        return le / self.count

    def to_dict(self) -> dict:
        """JSON-safe encoding (sparse buckets keyed by str index)."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "buckets": {str(i): n for i, n in sorted(self.buckets.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        h.buckets = {int(k): int(n) for k, n in (d.get("buckets") or {}).items()}
        return h

    def summary(self) -> dict:
        """count/sum/min/max/p50/p99 — the compact row report.py and the
        `kind="metrics"` JSONL record carry."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
        }


# ---- the registry --------------------------------------------------------


def _label_key(labels: dict) -> tuple:
    """Canonical hashable form: sorted (k, str(v)) pairs — None values
    mean 'label absent' so a standalone engine and a replica-0 engine
    produce distinct series only when a replica label is actually set."""
    return tuple(sorted((k, str(v)) for k, v in labels.items() if v is not None))


class MetricRegistry:
    """Thread-safe home of every named series. One lock, O(1) updates —
    cheap enough to live inside window-boundary host code (the hot
    device path never touches it: metrics are DERIVED from completions,
    trace trees and span walls, never re-instrumented)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, Histogram] = {}

    # -- writers ----------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def counter_set(self, name: str, value: float, **labels) -> None:
        """Mirror an existing cumulative observer (RetryLog, rollback
        seq, preempt count) into a counter — the value is authoritative
        elsewhere; merge across processes still sums."""
        with self._lock:
            self._counters[(name, _label_key(labels))] = float(value)

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = float(value)

    def observe(self, name: str, value: float, n: int = 1, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
        h.observe(value, n)

    # -- readers ----------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def sum_counter(self, name: str) -> float:
        """Sum of a counter over every label combination."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def hist(self, name: str, **labels) -> Histogram | None:
        with self._lock:
            return self._hists.get((name, _label_key(labels)))

    def aggregate_hist(self, name: str) -> Histogram:
        """Merge every label variant of `name` into one histogram — the
        cross-run / fleet-vs-single comparison view (replica labels
        differ between a fleet and a standalone engine; distributions
        must not)."""
        out = Histogram()
        with self._lock:
            hists = [h for (n, _), h in self._hists.items() if n == name]
        for h in hists:
            out.merge(h)
        return out

    def hist_names(self) -> list[str]:
        with self._lock:
            return sorted({n for (n, _) in self._hists})

    def filter(self, **labels) -> "MetricRegistry":
        """Sub-registry of series matching every given label exactly —
        how a fleet splits its shared registry into per-replica
        snapshot files."""
        want = dict(_label_key(labels))
        sub = MetricRegistry()
        with self._lock:
            items = (
                list(self._counters.items()),
                list(self._gauges.items()),
                list(self._hists.items()),
            )
        for (name, lk), v in items[0]:
            if all(dict(lk).get(k) == w for k, w in want.items()):
                sub._counters[(name, lk)] = v
        for (name, lk), v in items[1]:
            if all(dict(lk).get(k) == w for k, w in want.items()):
                sub._gauges[(name, lk)] = v
        for (name, lk), h in items[2]:
            if all(dict(lk).get(k) == w for k, w in want.items()):
                c = Histogram()
                c.merge(h)
                sub._hists[(name, lk)] = c
        return sub

    # -- snapshot / merge --------------------------------------------------

    def snapshot(self) -> dict:
        """Consistent JSON-safe copy of every series."""
        with self._lock:
            return {
                "v": 1,
                "counters": [
                    {"name": n, "labels": dict(lk), "value": v}
                    for (n, lk), v in sorted(self._counters.items())
                ],
                "gauges": [
                    {"name": n, "labels": dict(lk), "value": v}
                    for (n, lk), v in sorted(self._gauges.items())
                ],
                "hists": [
                    {"name": n, "labels": dict(lk), **h.to_dict()}
                    for (n, lk), h in sorted(self._hists.items())
                ],
            }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "MetricRegistry":
        reg = cls()
        reg.merge_snapshot(snap)
        return reg

    def merge_snapshot(self, snap: dict) -> None:
        """Fold a snapshot in: counters sum, histograms bucket-sum,
        gauges last-writer-wins (see module docstring)."""
        with self._lock:
            for row in snap.get("counters", ()):
                key = (row["name"], _label_key(row.get("labels") or {}))
                self._counters[key] = self._counters.get(key, 0.0) + float(row["value"])
            for row in snap.get("gauges", ()):
                key = (row["name"], _label_key(row.get("labels") or {}))
                self._gauges[key] = float(row["value"])
            for row in snap.get("hists", ()):
                key = (row["name"], _label_key(row.get("labels") or {}))
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = Histogram()
                h.merge(Histogram.from_dict(row))

    def summary(self) -> dict:
        """Compact per-series summaries — the `kind="metrics"` record
        body (full bucket tables live in the snapshot files, not the
        JSONL)."""
        with self._lock:
            counters = [
                {"name": n, "labels": dict(lk), "value": v}
                for (n, lk), v in sorted(self._counters.items())
            ]
            gauges = [
                {"name": n, "labels": dict(lk), "value": v}
                for (n, lk), v in sorted(self._gauges.items())
            ]
            hists = [
                {"name": n, "labels": dict(lk), **h.summary()}
                for (n, lk), h in sorted(self._hists.items())
            ]
        return {"counters": counters, "gauges": gauges, "hists": hists}


# ---- snapshot files (heartbeat-file discipline) --------------------------

SNAPSHOT_GLOB = "metrics-p*.json"
MERGED_NAME = "metrics-merged.json"
OPENMETRICS_NAME = "metrics.prom"


def snapshot_path(directory, process_index: int) -> Path:
    return Path(directory) / f"metrics-p{process_index:05d}.json"


def _atomic_write_text(path: Path, text: str) -> None:
    """tmp-sibling + rename publish. Re-spells fsio.atomic_write_text
    verbatim because this module must import nothing from tpukit
    (tpukit/__init__ pulls jax; top.py/report.py load this file by
    path) — the ONE other home of the spelling, waiver below."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)  # lint: allow(atomic-publish): metrics.py is path-loadable stdlib-only (no tpukit import possible); this re-spells fsio.atomic_write_text verbatim


def publish_snapshot(
    directory,
    process_index: int,
    registry: MetricRegistry,
    *,
    process_count: int = 1,
    time_s: float = 0.0,
) -> Path:
    """Atomically publish one process's snapshot file. Readers never see
    a torn file (rename publish); last write wins per process."""
    path = snapshot_path(directory, process_index)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {
        "process": int(process_index),
        "process_count": int(process_count),
        "time": float(time_s),
        "metrics": registry.snapshot(),
    }
    _atomic_write_text(path, json.dumps(payload))
    return path


def read_snapshots(directory, process_count: int | None = None) -> tuple[list[dict], dict]:
    """Every readable snapshot payload in the directory, plus a meta
    dict {"files", "skipped", "stale"}. Torn/foreign files are skipped
    and counted, never raised (heartbeat read_all discipline); payloads
    whose `process >= process_count` are a stale incarnation left over
    from a larger world and are excluded the same way heartbeat's
    straggler check excludes them."""
    out: list[dict] = []
    skipped = 0
    stale = 0
    directory = Path(directory)
    paths = sorted(directory.glob(SNAPSHOT_GLOB)) if directory.is_dir() else []
    for path in paths:
        try:
            payload = json.loads(path.read_text())
            proc = int(payload["process"])
            payload["metrics"]["counters"]  # shape check: a snapshot, not a stray json
        except (ValueError, KeyError, TypeError, OSError):
            skipped += 1  # torn/foreign file: skip, never raise
            continue
        if process_count is not None and proc >= process_count:
            stale += 1
            continue
        out.append(payload)
    return out, {"files": len(paths), "skipped": skipped, "stale": stale}


def merge_snapshot_dir(
    directory, process_count: int | None = None
) -> tuple[MetricRegistry, dict]:
    """Process 0's merge: fold every live snapshot into one registry by
    bucket-wise sum. Deterministic in file order (sorted paths), but the
    result is order-independent for counters/histograms (associative
    commutative merge — tests shuffle to prove it)."""
    payloads, meta = read_snapshots(directory, process_count)
    merged = MetricRegistry()
    for p in payloads:
        merged.merge_snapshot(p["metrics"])
    meta["merged"] = len(payloads)
    return merged, meta


def write_merged(directory, registry: MetricRegistry, *, meta: dict | None = None) -> None:
    """Publish the merged view beside the per-process files: the JSON
    merge (`metrics-merged.json`) and the OpenMetrics textfile
    (`metrics.prom`) external scrapers collect."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    payload = {"meta": meta or {}, "metrics": registry.snapshot()}
    _atomic_write_text(directory / MERGED_NAME, json.dumps(payload))
    _atomic_write_text(directory / OPENMETRICS_NAME, to_openmetrics(registry))


# ---- OpenMetrics textfile export -----------------------------------------


def _om_labels(labels: dict, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _om_name(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def to_openmetrics(registry: MetricRegistry) -> str:
    """OpenMetrics text exposition of the registry (counter/gauge/
    histogram families; histogram buckets are cumulative `le` series —
    only edges whose cumulative count changes are emitted, which is
    valid exposition and keeps 272-bucket tables compact)."""
    snap = registry.snapshot()
    lines: list[str] = []
    seen_types: set[str] = set()

    def head(name: str, kind: str):
        if name not in seen_types:
            seen_types.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for row in snap["counters"]:
        name = _om_name(row["name"]) + "_total"
        head(name, "counter")
        lines.append(f"{name}{_om_labels(row['labels'])} {row['value']:g}")
    for row in snap["gauges"]:
        name = _om_name(row["name"])
        head(name, "gauge")
        lines.append(f"{name}{_om_labels(row['labels'])} {row['value']:g}")
    for row in snap["hists"]:
        name = _om_name(row["name"])
        head(name, "histogram")
        labels = row["labels"]
        cum = 0
        for i in sorted(int(k) for k in row["buckets"]):
            cum += row["buckets"][str(i)]
            le = "+Inf" if i >= OVERFLOW else f"{EDGES[i]:.9g}"
            le_attr = 'le="' + le + '"'
            lines.append(f"{name}_bucket{_om_labels(labels, le_attr)} {cum}")
        if cum < row["count"]:  # defensive: counts are authoritative
            cum = row["count"]
        inf_attr = 'le="+Inf"'
        lines.append(f"{name}_bucket{_om_labels(labels, inf_attr)} {cum}")
        lines.append(f"{name}_sum{_om_labels(labels)} {row['sum']:g}")
        lines.append(f"{name}_count{_om_labels(labels)} {row['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---- SLO grammar + accounting --------------------------------------------

# ttft<=250ms@p99 ; tpot<=40ms@p95 ; e2e<=2s@p99 ; queue_wait<=100ms@p90
_SLO_ITEM_RE = re.compile(
    r"^(?P<metric>[a-z_][a-z0-9_]*)"
    r"<=(?P<value>[0-9]+(?:\.[0-9]+)?)(?P<unit>us|ms|s)"
    r"@p(?P<q>[0-9]+(?:\.[0-9]+)?)$"
)
_UNIT_S = {"us": 1e-6, "ms": 1e-3, "s": 1.0}

# The latency series the serving stack derives (module docstring of the
# engine wiring): what an --slo clause may name.
SLO_METRICS = ("ttft", "tpot", "e2e", "queue_wait")


class SloSpecError(ValueError):
    """A malformed --slo spec — raised at startup so a typo'd objective
    fails the launch, not silently never gates (chaos-grammar
    discipline)."""


class SloTarget:
    """One parsed clause: `metric <= bound_s @ quantile q`."""

    __slots__ = ("metric", "bound_s", "q", "raw")

    def __init__(self, metric: str, bound_s: float, q: float, raw: str):
        self.metric = metric
        self.bound_s = bound_s
        self.q = q
        self.raw = raw

    @property
    def budget(self) -> float:
        """Allowed violation fraction: 1 - q."""
        return 1.0 - self.q

    def __repr__(self):
        return f"SloTarget({self.raw!r})"


def parse_slo(spec: str) -> list[SloTarget]:
    """Parse `"ttft<=250ms@p99;tpot<=40ms@p95"` into targets, failing
    fast with a pointed message on any malformed clause."""
    targets: list[SloTarget] = []
    seen: set[str] = set()
    for raw in spec.split(";"):
        item = raw.strip()
        if not item:
            continue
        m = _SLO_ITEM_RE.match(item)
        if m is None:
            raise SloSpecError(
                f"bad --slo clause {item!r}: expected "
                f"`metric<=VALUE[us|ms|s]@pQQ` like `ttft<=250ms@p99` "
                f"(metrics: {', '.join(SLO_METRICS)})"
            )
        metric = m.group("metric")
        if metric not in SLO_METRICS:
            raise SloSpecError(
                f"bad --slo clause {item!r}: unknown metric {metric!r} "
                f"(metrics: {', '.join(SLO_METRICS)})"
            )
        q = float(m.group("q")) / 100.0
        if not 0.0 < q < 1.0:
            raise SloSpecError(
                f"bad --slo clause {item!r}: quantile p{m.group('q')} "
                f"must be in (p0, p100) exclusive"
            )
        if metric in seen:
            raise SloSpecError(
                f"bad --slo spec: metric {metric!r} declared twice"
            )
        seen.add(metric)
        bound_s = float(m.group("value")) * _UNIT_S[m.group("unit")]
        if bound_s <= 0.0:
            raise SloSpecError(
                f"bad --slo clause {item!r}: bound must be > 0"
            )
        targets.append(SloTarget(metric, bound_s, q, item))
    if not targets:
        raise SloSpecError(
            "empty --slo spec: declare at least one clause like "
            "`ttft<=250ms@p99`"
        )
    return targets


class SloAccountant:
    """Window-by-window compliance + error-budget burn.

    Per window and target: `compliance` is the fraction of that
    window's samples meeting the bound, `met` is compliance >= q, and
    `burn` is the violation fraction over the budget (1-q) — burn 1.0
    consumes budget exactly as fast as allowed, >1 is on track to
    violate. Cumulative rows accumulate samples across windows so the
    run-level verdict (`overall_compliance`, what the
    --min_slo_compliance gate reads) is sample-weighted, not
    window-weighted."""

    def __init__(self, targets: list[SloTarget]):
        self.targets = list(targets)
        self._cum_n = {t.metric: 0 for t in self.targets}
        self._cum_viol = {t.metric: 0 for t in self.targets}
        self.windows = 0

    def evaluate(self, samples: dict[str, list[float]]) -> dict:
        """Account one window. `samples` maps metric name -> that
        window's raw values (seconds). Returns the `kind="slo"` record
        body (minus the kind/window tags the caller stamps)."""
        self.windows += 1
        rows = []
        for t in self.targets:
            vals = samples.get(t.metric) or []
            n = len(vals)
            viol = sum(1 for v in vals if v > t.bound_s)
            self._cum_n[t.metric] += n
            self._cum_viol[t.metric] += viol
            cn = self._cum_n[t.metric]
            cv = self._cum_viol[t.metric]
            compliance = None if n == 0 else (n - viol) / n
            cum_compliance = None if cn == 0 else (cn - cv) / cn
            rows.append({
                "slo": t.raw,
                "metric": t.metric,
                "bound_s": t.bound_s,
                "q": t.q,
                "n": n,
                "violations": viol,
                "compliance": compliance,
                "met": None if compliance is None else compliance >= t.q,
                "burn": None if n == 0 else (viol / n) / max(t.budget, 1e-9),
                "cum_n": cn,
                "cum_compliance": cum_compliance,
                "cum_burn": None if cn == 0 else (cv / cn) / max(t.budget, 1e-9),
            })
        return {"targets": rows, "overall_compliance": self.overall_compliance()}

    def overall_compliance(self) -> float | None:
        """The run verdict: the WORST cumulative compliance across
        targets that have samples (min, not mean — one violated
        objective is a violated SLO). None until any target has a
        sample (the gate treats that as failure, anti-vacuous)."""
        fracs = [
            (self._cum_n[t.metric] - self._cum_viol[t.metric]) / self._cum_n[t.metric]
            for t in self.targets
            if self._cum_n[t.metric] > 0
        ]
        return min(fracs) if fracs else None
