"""Multi-host liveness: per-process heartbeat files + straggler check.

The classic multi-host failure mode is the silent hang: one process stalls
inside a collective (bad host, wedged data loader, the reference's
rank-0-only generate — SURVEY §3.5) and every OTHER process blocks with it,
so nothing is printed anywhere and the job just stops. Heartbeat files turn
that into a diagnosable state: every process writes
`heartbeat-p{index:05d}.json` (step, wall time) to a SHARED directory each
step window, and process 0 reads them back and names the processes whose
beats are stale or whose step lags the fleet.

The check is advisory (it prints/logs; it does not kill anything): when the
hang is inside a collective, process 0 is usually blocked in it too — the
value is the on-disk breadcrumb an operator (or a babysitter script tailing
the directory) reads to see WHICH host stopped advancing and at what step,
instead of staring at N identical frozen consoles.

Writes are atomic (tmp + rename) so a reader never sees a torn JSON file.

Round 8: the beat record optionally carries the divergence checksum
(obs/divergence.py) — `checksum` + `checksum_step` — and process 0's
`check_divergence()` compares checksums across processes at the same
step, turning silent cross-replica drift into a named process.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

from tpukit.fsio import atomic_write_text


def _beat_path(directory: Path, process_index: int) -> Path:
    return directory / f"heartbeat-p{process_index:05d}.json"


class Heartbeat:
    """One process's beat writer + (on any process) the fleet reader.

    `directory` must be shared across hosts (NFS/GCS-fuse) for the
    cross-host check to see every file; per-host local dirs still give
    per-host liveness breadcrumbs.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        process_index: int | None = None,
        process_count: int | None = None,
        timeout_s: float = 120.0,
    ):
        import jax

        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        self.process_count = (
            jax.process_count() if process_count is None else process_count
        )
        self.timeout_s = timeout_s
        self.path = _beat_path(self.directory, self.process_index)
        self._last_beat: float | None = None
        self._cadence: float | None = None  # observed seconds between beats

    def beat(
        self,
        step: int,
        now: float | None = None,
        checksum: str | None = None,
        checksum_step: int | None = None,
        timeline: int = 0,
    ) -> None:
        """Write this process's liveness record (atomic replace).

        `checksum`/`checksum_step` (divergence detection, obs/divergence.py)
        piggyback the latest state checksum on the existing liveness file so
        the cross-process comparison needs no new rendezvous: process 0
        already reads every beat each window.

        `timeline` (round-9 rollback) counts the collective rollbacks this
        process has executed. Divergence comparison only matches checksums
        from the SAME timeline: after a rollback, step numbers repeat with
        different data, so a stale pre-rollback checksum at an equal step
        number must never be compared against a post-rollback one."""
        now = time.time() if now is None else now
        if self._last_beat is not None:
            self._cadence = now - self._last_beat
        self._last_beat = now
        rec = {
            "process": self.process_index,
            "step": int(step),
            "time": now,
        }
        if timeline:
            rec["timeline"] = int(timeline)
        if checksum is not None:
            rec["checksum"] = checksum
            rec["checksum_step"] = int(
                checksum_step if checksum_step is not None else step
            )
        # one atomic-publish spelling repo-wide (tools/lint_invariants.py);
        # fsio is stdlib-only, so this stays importable without jax
        atomic_write_text(self.path, json.dumps(rec))

    def read_all(self) -> dict[int, dict]:
        """All readable beat records in the directory, keyed by process."""
        out: dict[int, dict] = {}
        for path in sorted(self.directory.glob("heartbeat-p*.json")):
            try:
                rec = json.loads(path.read_text())
                out[int(rec["process"])] = rec
            except (ValueError, KeyError, OSError):
                continue  # torn/foreign file: skip, never raise
        return out

    def check(self, now: float | None = None, step_lag: int = 0) -> list[dict]:
        """Straggler report (run on process 0 each window). A process
        straggles when its beat file is missing, its beat is older than the
        effective timeout, or (`step_lag` > 0) its step trails the fleet max
        by more than `step_lag`. Returns one record per straggler:
        `{process, reason, age_s?, step?, behind?}`.

        The effective timeout is `max(timeout_s, 3x this process's own
        observed beat cadence)`: beats land once per PRINT_FREQ window, so
        a big-model run whose window exceeds a fixed timeout would
        otherwise flag every healthy peer on every check — the caller's
        cadence is the only window-duration estimate available in advance.
        """
        now = time.time() if now is None else now
        effective = self.timeout_s
        if self._cadence:
            effective = max(effective, 3.0 * self._cadence)
        beats = self.read_all()
        # Fleet max over THIS world's ranks only (same stale-beat guard as
        # check_divergence, same scoping): after an elastic shrink, a
        # vanished rank's beat that lands post-sweep must not set a step
        # frontier the live world is then "lagging" behind every window.
        max_step = max(
            (
                r.get("step", 0) for r in beats.values()
                if self.process_count == 1
                or int(r.get("process", -1)) < self.process_count
            ),
            default=0,
        )
        out = []
        for proc in range(self.process_count):
            rec = beats.get(proc)
            if rec is None:
                out.append({"process": proc, "reason": "missing"})
                continue
            age = now - rec.get("time", 0.0)
            if age > effective:
                out.append(
                    {"process": proc, "reason": "stale",
                     "age_s": round(age, 1), "step": rec.get("step")}
                )
            elif step_lag and max_step - rec.get("step", 0) > step_lag:
                out.append(
                    {"process": proc, "reason": "lagging",
                     "step": rec.get("step"),
                     "behind": max_step - rec.get("step", 0)}
                )
        return out

    def check_divergence(self) -> list[dict]:
        """Cross-replica checksum comparison (run on process 0 each window).

        Groups the beat files' `checksum` values by (timeline,
        `checksum_step`) and compares only beats taken at the SAME step of
        the SAME rollback timeline — processes mid-window skew (one
        already past the next check step) are simply not compared yet, so
        skew can never produce a false positive, and post-rollback
        re-executed step numbers are never compared against stale
        pre-rollback beats. At any comparable point where more than one
        distinct checksum exists, the minority processes are reported
        against the majority value (ties break deterministically by
        checksum string). Returns one record per diverged process:
        `{process, checksum_step, checksum, expected}`.
        """
        by_key: dict[tuple[int, int], dict[str, list[int]]] = {}
        for rec in self.read_all().values():
            if (
                self.process_count > 1
                and int(rec.get("process", -1)) >= self.process_count
            ):
                # a beat from a rank beyond THIS world: a stale file from a
                # larger previous incarnation (elastic resize). The resize
                # path sweeps these (tpukit/reshard.sweep_stale_world), but
                # an NFS-delayed write can land after the sweep — never
                # compare another world's checksums against this one's.
                # Scoped to real multi-process worlds: a 1-process reader
                # has no peers of its own, and the single-process harness
                # pattern (tests plant a fake peer's beat to exercise this
                # comparison) must keep working.
                continue
            cs, st = rec.get("checksum"), rec.get("checksum_step")
            if cs is None or st is None:
                continue
            key = (int(rec.get("timeline", 0)), int(st))
            by_key.setdefault(key, {}).setdefault(str(cs), []).append(
                int(rec["process"])
            )
        out = []
        for key in sorted(by_key):
            st = key[1]
            groups = by_key[key]
            if len(groups) < 2:
                continue
            ranked = sorted(groups.items(), key=lambda kv: (-len(kv[1]), kv[0]))
            majority = ranked[0][0]
            for cs, procs in ranked[1:]:
                for proc in sorted(procs):
                    out.append(
                        {"process": proc, "checksum_step": st,
                         "checksum": cs, "expected": majority}
                    )
        return out
