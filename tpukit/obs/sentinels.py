"""Training-health sentinels: global norms in-jit, loss spikes on host.

Two complementary guards (SURVEY §5; the reference trains blind beyond its
tqdm bar):

  - `global_norms(grads, updates, params)`: the traced half. Called INSIDE
    the already-jitted train step when `--log_grad_norms` is on, so the
    norms ride the existing compile — no second program, no extra D2H sync
    until the window boundary. With the flag off the train step's traced
    graph is untouched (the call never happens), keeping the compiled HLO
    byte-identical to a telemetry-free build.
  - `SpikeSentinel`: the host half. Watches the window-averaged loss the
    trainer already syncs once per PRINT_FREQ window; fires on NaN/Inf
    immediately and on a loss exceeding the running mean by
    `threshold * max(std, floor)` once enough history exists. The action is
    the caller's ("warn" logs and continues; "abort" checkpoints then
    raises) — complementing `--debug_nans`, which catches NaN at the op
    level inside jit but cannot see a finite-but-diverging loss.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque

import jax.numpy as jnp
import optax


def global_norms(grads, updates=None, params=None) -> dict:
    """Global L2 norms as a dict of f32 scalars — call inside the jitted
    train step. optax.global_norm flattens the pytree; under GSPMD the
    reduction follows the leaves' shardings, so sharded (FSDP/TP/EP) state
    yields the exact global norm with the partial-reduce collectives the
    compiler picks."""
    out = {"grad_norm": optax.global_norm(grads).astype(jnp.float32)}
    if updates is not None:
        out["update_norm"] = optax.global_norm(updates).astype(jnp.float32)
    if params is not None:
        out["param_norm"] = optax.global_norm(params).astype(jnp.float32)
    return out


@dataclasses.dataclass
class SpikeEvent:
    kind: str  # "nan" | "spike"
    step: int
    loss: float
    mean: float | None = None
    std: float | None = None

    def record(self) -> dict:
        """JSONL-ready dict (kind field renamed to avoid the logger's own
        record discriminator; non-finite losses stringified — bare NaN is
        not valid JSON for downstream strict parsers)."""
        loss = self.loss if math.isfinite(self.loss) else str(self.loss)
        return {
            "event": self.kind, "step": self.step, "loss": loss,
            "mean": self.mean, "std": self.std,
        }


class SpikeSentinel:
    """Rolling-window loss-spike and NaN detector.

    `observe(loss, step)` returns a `SpikeEvent` when the sentinel fires,
    else None. Detection: non-finite losses fire always; finite losses fire
    when `loss > mean + threshold * max(std, rel_floor * |mean|)` over the
    last `window` observed losses, once `min_history` of them exist. The
    std floor keeps a flat early loss curve (std ~ 0) from flagging normal
    noise. Spiking values are NOT added to the history, so the baseline
    tracks healthy training and a sustained divergence keeps firing.
    """

    def __init__(
        self,
        threshold: float,
        window: int = 32,
        min_history: int = 4,
        rel_floor: float = 0.05,
    ):
        if threshold <= 0:
            raise ValueError(f"spike threshold must be > 0, got {threshold}")
        self.threshold = threshold
        self.min_history = min_history
        self.rel_floor = rel_floor
        self._hist: deque[float] = deque(maxlen=window)

    def reset(self) -> None:
        """Drop the rolling history (round-9 rollback: after a restore the
        loss returns to an OLDER point on the curve — judging it against
        the pre-anomaly baseline would immediately re-fire the sentinel on
        a perfectly healthy recovery)."""
        self._hist.clear()

    def observe(self, loss: float, step: int) -> SpikeEvent | None:
        loss = float(loss)
        if not math.isfinite(loss):
            return SpikeEvent(kind="nan", step=step, loss=loss)
        if len(self._hist) >= self.min_history:
            n = len(self._hist)
            mean = sum(self._hist) / n
            var = sum((x - mean) ** 2 for x in self._hist) / n
            band = max(math.sqrt(var), self.rel_floor * abs(mean), 1e-12)
            if loss > mean + self.threshold * band:
                return SpikeEvent(
                    kind="spike", step=step, loss=loss,
                    mean=mean, std=math.sqrt(var),
                )
        self._hist.append(loss)
        return None
