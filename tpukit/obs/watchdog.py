"""Hang watchdog, diagnostics bundles, and trace-on-anomaly.

The worst multi-host failure mode is the silent hang: one process wedges
inside a collective and every other process blocks with it, so the
operator gets a stalled tqdm bar and N frozen consoles (SURVEY §3.5 —
exactly the reference's rank-0 FSDP generate hang). The heartbeat files
(obs/heartbeat.py) say WHICH host stopped; this module says WHAT it was
doing when it stopped:

  - `HangWatchdog`: a daemon monitor thread armed/disarmed around each
    step of the training loop. When an armed step overruns its deadline
    (`--hang_timeout`), or when any sentinel asks for it explicitly
    (`trigger()` — loss spike, NaN, heartbeat straggler, cross-replica
    divergence), it dumps a **diagnostics bundle**: one JSON file with
    every Python thread's stack (`sys._current_frames` — the training
    thread's frame shows which call is blocked), the flight-recorder ring
    (obs/recorder.py — what the loop did in the minutes before), live
    `device.memory_stats()` gauges, the heartbeat snapshot across
    processes, the in-flight async-checkpoint/prefetcher state, and the
    run config. The dump is pure host work (stack walk + file write) so
    it succeeds even while every device queue is wedged — which is the
    entire point.
  - `AnomalyTracer`: the first anomaly of a run arms a `jax.profiler`
    capture of the next K steps, so the expensive trace exists exactly
    for the steps that matter instead of being always-on (Megatron-style
    production runs treat this as the difference between a 5-minute and
    a 5-hour debug, PAPERS.md). It arms ONCE per run: anomalies tend to
    repeat, and a trace per spike would bury the signal.

Bundle writes are atomic (tmp + rename, the heartbeat discipline) and the
dump count is bounded (`max_dumps`) so a flapping sentinel cannot fill the
disk. Render a bundle with `python tools/flightview.py <bundle.json>`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Any, Callable

from tpukit.fsio import atomic_write_text


def all_thread_stacks() -> dict[str, list[str]]:
    """Formatted stack of every live Python thread, keyed by
    `"{thread name}-{ident}"`. The GIL makes `sys._current_frames` a
    consistent point-in-time snapshot; frames of threads blocked in C
    extensions (a wedged collective, a queue.get) show the last Python
    line — which is the diagnosis."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = [l.rstrip("\n") for l in traceback.format_stack(frame)]
    return out


def _jsonable(obj):
    """Best-effort JSON coercion: a bundle written during a failure must
    never itself fail on an exotic value."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


def write_bundle(
    directory: str | os.PathLike,
    reason: str,
    step: int | None = None,
    recorder=None,
    heartbeat=None,
    probes: dict[str, Callable[[], Any]] | None = None,
    config=None,
    extra: dict | None = None,
) -> Path:
    """Assemble and atomically write one diagnostics bundle; returns its
    path. Every section is best-effort: a probe that raises lands as its
    error string, never aborts the dump."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)

    bundle: dict[str, Any] = {
        "reason": reason,
        "step": step,
        "time": time.time(),
        "stacks": all_thread_stacks(),
    }
    try:
        import jax

        bundle["process"] = {
            "index": jax.process_index(),
            "count": jax.process_count(),
            "device_kind": jax.devices()[0].device_kind,
            "jax": jax.__version__,
        }
    except Exception as exc:  # pre-init or wedged backend: still dump
        bundle["process"] = {"error": repr(exc)}
    if recorder is not None:
        bundle["ring"] = [
            {k: _jsonable(v) for k, v in r.items()} for r in recorder.snapshot()
        ]
        bundle["ring_total_recorded"] = recorder.total_recorded
    if heartbeat is not None:
        try:
            bundle["heartbeats"] = {
                str(k): v for k, v in heartbeat.read_all().items()
            }
        except Exception as exc:
            bundle["heartbeats"] = {"error": repr(exc)}
    try:
        from tpukit.obs.xla import live_memory_stats

        bundle["memory"] = live_memory_stats()
    except Exception as exc:
        bundle["memory"] = {"error": repr(exc)}
    if probes:
        inflight = {}
        for name, fn in probes.items():
            try:
                inflight[name] = _jsonable(fn())
            except Exception as exc:
                inflight[name] = repr(exc)
        bundle["inflight"] = inflight
    if config is not None:
        if dataclasses.is_dataclass(config) and not isinstance(config, type):
            config = dataclasses.asdict(config)
        bundle["config"] = {str(k): _jsonable(v) for k, v in dict(config).items()}
    if extra:
        bundle.update({k: _jsonable(v) for k, v in extra.items()})

    # one file per dump; the PROCESS INDEX is part of the name because a
    # pod-wide hang makes every process dump the same step at the same
    # millisecond into the same shared --debug_dir — step+reason+ms alone
    # would collide (and os.replace would silently drop all but one)
    proc = bundle.get("process", {}).get("index", 0) or 0
    stamp = f"{int(time.time() * 1000):013d}"
    name = (
        f"bundle-step{step if step is not None else 0:08d}-{reason}"
        f"-p{proc:05d}-{stamp}.json"
    )
    path = directory / name
    # one atomic-publish spelling repo-wide (tools/lint_invariants.py);
    # fsio is stdlib-only, so the monitor thread's dump never waits on a
    # heavyweight import while the main thread is wedged
    atomic_write_text(path, json.dumps(bundle, indent=1, default=repr))
    return path


class HangWatchdog:
    """Deadline monitor around the training loop's step iterations.

    `arm(step)` (re)sets a deadline `timeout_s` from now; `disarm()`
    clears it. A daemon thread polls; an armed deadline that passes dumps
    a `reason="hang"` bundle and clears itself (one bundle per overrun —
    the next `arm` starts a fresh deadline). `trigger(reason)` dumps
    synchronously from the calling thread — the sentinel/divergence path.
    Both share the `max_dumps` budget.

    The watchdog is advisory: it records, it does not kill. When the hang
    is a wedged collective the training thread cannot be safely unwound
    from another thread anyway; the bundle is the artifact the operator
    (or the babysitter tailing `--debug_dir`) acts on.
    """

    def __init__(
        self,
        debug_dir: str | os.PathLike,
        timeout_s: float = 0.0,
        recorder=None,
        heartbeat=None,
        probes: dict[str, Callable[[], Any]] | None = None,
        config=None,
        max_dumps: int = 8,
        poll_s: float | None = None,
    ):
        if timeout_s < 0:
            raise ValueError(f"hang timeout must be >= 0, got {timeout_s}")
        self.debug_dir = Path(debug_dir)
        self.timeout_s = timeout_s
        self.recorder = recorder
        self.heartbeat = heartbeat
        self.probes = probes or {}
        self.config = config
        self.max_dumps = max_dumps
        self.dumps: list[Path] = []
        # one entry per hang overrun, IN ORDER, with the bundle that dump
        # produced (None once the budget is spent) — so the trainer can
        # attribute each surfaced hang to ITS bundle instead of guessing
        # from the shared `dumps` list, which trigger() bundles also feed
        self.hang_events: list[dict] = []
        self.hang_count = 0
        self._deadline: float | None = None
        self._armed_step: int | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if timeout_s > 0:
            # poll a fraction of the timeout so "fires within hang_timeout"
            # means within ~1.25x of it worst-case, bounded for huge timeouts
            self._poll = poll_s if poll_s else max(0.02, min(timeout_s / 4.0, 1.0))
            self._thread = threading.Thread(
                target=self._monitor, daemon=True, name="tpukit-watchdog"
            )
            self._thread.start()

    # -- training-thread surface ------------------------------------------

    def arm(self, step: int) -> None:
        """Start (or reset) the deadline for the iteration handling `step`."""
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s
            self._armed_step = step

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None
            self._armed_step = None

    def trigger(self, reason: str, step: int | None = None, **extra) -> Path | None:
        """Synchronous bundle dump (sentinel / divergence path). Returns the
        bundle path, or None once the dump budget is spent."""
        return self._dump(reason, step=step, extra=extra)

    def close(self) -> None:
        self.disarm()
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- monitor ----------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                deadline, step = self._deadline, self._armed_step
            if deadline is None or time.monotonic() < deadline:
                continue
            overdue = time.monotonic() - (deadline - self.timeout_s)
            self.hang_count += 1
            path = self._dump(
                "hang", step=step, extra={"stuck_for_s": round(overdue, 3)}
            )
            self.hang_events.append(
                {"step": step, "bundle": str(path) if path else None}
            )
            # one bundle per overrun: the stacks of a still-hung step would
            # be identical; a recovered loop re-arms and re-covers itself
            with self._lock:
                if self._deadline == deadline:
                    self._deadline = None

    def _dump(self, reason: str, step: int | None, extra: dict | None) -> Path | None:
        with self._lock:
            if len(self.dumps) >= self.max_dumps:
                return None
        try:
            path = write_bundle(
                self.debug_dir, reason, step=step, recorder=self.recorder,
                heartbeat=self.heartbeat, probes=self.probes,
                config=self.config, extra=extra,
            )
        except Exception as exc:  # the watchdog must never kill the run
            print(f"watchdog: bundle dump failed: {exc!r}", file=sys.stderr)
            return None
        with self._lock:
            self.dumps.append(path)
        print(f"watchdog: {reason} at step {step}; bundle {path}", file=sys.stderr)
        return path


class AnomalyTracer:
    """Arm a jax.profiler capture of the next K steps at the FIRST anomaly.

    State machine: idle -> (trigger) armed -> (maybe_start, training
    thread) tracing -> (step x K) done. `trigger()` after the first call
    is a no-op — one trace per run, collected exactly when it matters.
    `maybe_start`/`step` MUST run on the training thread (jax.profiler is
    not safe to start from the monitor thread); `trigger` may be called
    from anywhere — it only flips a flag.
    """

    def __init__(self, trace_dir: str | os.PathLike, steps: int = 8):
        if steps < 1:
            raise ValueError(f"trace step count must be >= 1, got {steps}")
        self.trace_dir = str(trace_dir)
        self.steps = steps
        self.reason: str | None = None
        self._armed = threading.Event()
        self._tracing = False
        self._done = False
        self._remaining = 0

    @property
    def done(self) -> bool:
        return self._done

    @property
    def tracing(self) -> bool:
        return self._tracing

    def trigger(self, reason: str = "anomaly") -> bool:
        """First call arms the capture; later calls are no-ops. Returns
        True when this call did the arming."""
        if self._done or self._tracing or self._armed.is_set():
            return False
        self.reason = reason
        self._armed.set()
        return True

    def maybe_start(self) -> bool:
        """Call at the top of each step iteration (training thread): starts
        the profiler when armed. Returns True when the trace started."""
        if not self._armed.is_set() or self._tracing or self._done:
            return False
        import jax

        try:
            jax.profiler.start_trace(self.trace_dir)
        except Exception as exc:  # another trace active, backend quirk
            print(f"anomaly trace failed to start: {exc!r}", file=sys.stderr)
            self._done = True  # don't retry every step
            return False
        self._tracing = True
        self._remaining = self.steps
        return True

    def step(self) -> bool:
        """Call once per completed step while tracing; stops the profiler
        after K steps. Returns True when this call stopped the trace."""
        if not self._tracing:
            return False
        self._remaining -= 1
        if self._remaining > 0:
            return False
        return self.stop()

    def stop(self) -> bool:
        """Stop an active capture (also called by fit() on unwind so a
        crashed run still flushes its partial trace)."""
        if not self._tracing:
            return False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            print(f"anomaly trace failed to stop: {exc!r}", file=sys.stderr)
        self._tracing = False
        self._done = True
        return True
