"""Cross-replica divergence detection: in-jit state checksums.

Silent divergence — data-parallel replicas drifting apart through a bad
host, a flaky DIMM, or a non-deterministic collective — is the failure the
fault-tolerance literature calls the most expensive to find (La Malfa et
al., PAPERS.md): the loss keeps printing, every heartbeat stays fresh, and
the model quietly trains to garbage. The detector here is a periodic
checksum of the params + optimizer state computed INSIDE a jitted program
(`--divergence_check_freq N` steps), published through each process's
heartbeat file, and compared across processes by process 0. Replicas that
should be bit-identical (DP keeps the state replicated; SPMD lockstep
keeps every process's copy equal) must produce the same checksum at the
same step — one flipped bit anywhere in params or Adam moments changes it.

Checksum design:

  - XOR-fold of the raw bit patterns (`lax.bitcast_convert_type` to u32),
    not a float sum: order-independent (so resharding/layout cannot change
    it), exact (no cancellation — a 1-ulp perturbation of one element
    flips it), and cheap (one pass, no transcendentals).
  - Per-leaf folds combine through a multiply-xor hash so two leaves
    swapping identical corruption cannot cancel each other out.
  - It is a SEPARATE jitted program, not a branch of the train step: the
    compiled train-step HLO is byte-identical whether the flag is on or
    off (the `--log_grad_norms` discipline, tests/test_flightrec.py), and
    the cost is paid only on check steps.

Scope (documented honestly): for replicated state (SingleDevice/DP) the
fold is process-local math on the local replica, so per-process checksums
are INDEPENDENT measurements and a mismatch localizes the diverged host.
For cross-host *sharded* state (FSDP/TP/pipeline) the fold's reduction is
a collective, so every process reports the same global value — it still
changes on any corruption (a run-integrity stamp, useful for comparing
against a restarted run) but cannot name the bad host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _leaf_fold(leaf) -> jax.Array:
    """u32 XOR-fold of one array's bit pattern. Floats go through f32 so
    bf16/f32 params hash identically to their checkpointed f32 master
    values; bools/ints widen to u32 (deterministic, sign-wrapped)."""
    x = leaf
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    else:
        x = x.astype(jnp.uint32)
    x = x.reshape(-1)
    return jax.lax.reduce(x, jnp.uint32(0), jax.lax.bitwise_xor, (0,))


def tree_checksum(tree) -> jax.Array:
    """u32 checksum of a pytree. Leaf folds are combined positionally with
    a multiply-xor hash (FNV-style) so identical corruption in two
    different leaves cannot cancel."""
    acc = jnp.uint32(2166136261)  # FNV offset basis
    for leaf in jax.tree_util.tree_leaves(tree):
        acc = (acc * jnp.uint32(16777619)) ^ _leaf_fold(leaf)
    return acc


def make_state_checksum():
    """Jitted `state -> {"params": u32, "opt_state": u32}`. One compile per
    state structure; call it every `--divergence_check_freq` steps. The
    result is replicated, so `device_get` is process-local."""

    @jax.jit
    def checksum(state):
        return {
            "params": tree_checksum(state.params),
            "opt_state": tree_checksum(state.opt_state),
        }

    return checksum


def format_checksum(ck: dict) -> str:
    """Host-side rendering of a checksum dict: `params:opt_state` hex —
    the string the heartbeat file and JSONL records carry."""
    p = int(jax.device_get(ck["params"]))
    o = int(jax.device_get(ck["opt_state"]))
    return f"{p:08x}:{o:08x}"
