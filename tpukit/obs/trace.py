"""Request-scoped tracing for the serving stack (round 20, ROADMAP #2/#3).

The serving JSONL is window-aggregate: it answers "how did the run go",
not "where did request 17's 900ms go". This module adds the per-request
substrate: every `serve.Request` carries a trace id (defaulting to its
rid), the engine's step primitives and the fleet router emit small SPAN
EVENTS into a bounded per-replica ring (`TraceRecorder`, the
FlightRecorder discipline: locked deque, O(1) append, memory bounded by
construction), and `build_trees` merges the events into one span tree
per request:

    enqueue -> [route] -> admit -> prefill chunk k -> prefill_done
            -> [handoff claim/copy -> adopt] -> quantum participations
            -> finish            (requeue after a replica_kill links the
                                  old and new attempts under ONE trace id)

Event vocabulary (each record is `{"ev", "trace", "rid", "replica", ...}`
with `t` for points and `t0`/`t1` for spans, seconds on the run clock —
`set_epoch` pins the perf_counter origin so every emitter shares it):

    enqueue      t=arrival_s          request visible to the scheduler
    route        t, dst               router assignment (fleet only)
    admit        t, slot              lane created on `replica`
    prefill      t0, t1, chunk        one (batched) prefill dispatch wall
    prefill_done t                    lane armed for decode
    handoff      t0, t1, claim_s, copy_s, dst   disagg page handoff
    adopt        t                    decode-side lane armed (disagg)
    quantum      t0, t1, s0, s1, steps, lanes   ONE event per decode
                 dispatch+sync pair; `lanes` lists the participating
                 trace ids, [t0,t1] the async-dispatch wall, [s0,s1] the
                 wall-to-sync (device) wall — the per-quantum
                 dispatch-vs-device attribution ROADMAP #3 wants
    finish       t, reason, generated  exactly-once completion
    requeue      t, from_replica       kill victim back to the queue

Phase accounting (`build_trees`): a request's lifetime [enqueue, finish]
partitions into queue_wait (enqueue/requeue -> admit), prefill (admit ->
prefill_done, per attempt), handoff (prefill_done -> adopt, when a
disagg adopt exists), decode (sum of participating quanta's dispatch
walls), sync_stall (sum of their sync walls) and `other` (the residual).
Each named interval is a disjoint sub-interval of the request's own
lifetime, so named phases can never exceed e2e on a correct trace — the
COMPLETENESS INVARIANT: a tree is `closed` when it has an enqueue, at
least one admit and exactly one finish, and `complete` when additionally
the named phase walls sum to <= e2e + 1e-3 s. `tools/report.py
--min_trace_complete` gates on the fraction of complete trees and
`tools/traceview.py` renders/exports them (Chrome-trace JSON via
`to_chrome`).

Deliberately stdlib-only (no jax, no numpy): `tools/traceview.py` loads
this file by path so post-mortems run anywhere, like report/flightview.
"""

from __future__ import annotations

import threading
import time
from collections import deque

# The per-request phase vocabulary, in lifetime order. `other` is the
# residual that makes the walls sum exactly to e2e.
PHASES = ("queue_wait", "prefill", "handoff", "decode", "sync_stall", "other")

# Tolerance on the completeness invariant: named phase walls may exceed
# e2e by at most this much (float accumulation across many quanta).
SUM_TOL_S = 1e-3


def request_trace_id(rid: int, trace: int = -1) -> int:
    """Effective trace id of a request: an explicit `trace` field wins,
    else the rid — requeued attempts reuse the SAME Request object, so
    both attempts land under one id either way."""
    return trace if trace >= 0 else rid


def _ev_time(ev: dict) -> float:
    return ev.get("t", ev.get("t0", 0.0))


class TraceRecorder:
    """Bounded per-replica rings of span events — FlightRecorder
    discipline: one dict allocation + a deque append under a lock per
    event, memory bounded by `capacity` events PER RING (a ring per
    emitting replica, so one hot replica cannot evict another's
    history). `snapshot()` merges all rings time-sorted."""

    def __init__(self, capacity: int = 8192):
        if capacity < 1:
            raise ValueError(f"trace capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rings: dict = {}  # replica label -> deque
        self._ring_totals: dict = {}  # replica label -> events ever emitted
        self._lock = threading.Lock()
        self._total = 0
        self._epoch: float | None = None

    def set_epoch(self, t0: float) -> None:
        """Pin the run clock: `now()` returns perf_counter seconds since
        `t0`. The run loop calls this at its own t0 so event times are
        directly comparable with arrival_s / admit_s / done_s."""
        with self._lock:
            self._epoch = t0

    def now(self) -> float:
        """Run-relative seconds (lazily 0-based when no epoch was set —
        tests driving step primitives directly still get a coherent
        clock)."""
        if self._epoch is None:
            with self._lock:
                if self._epoch is None:
                    self._epoch = time.perf_counter()
        return time.perf_counter() - self._epoch

    def emit(self, ev: str, trace: int, **fields) -> None:
        """Append one event to the emitting replica's ring (`replica`
        key in `fields`, None for a standalone engine). Values must be
        JSON-serializable — they flush to the metrics JSONL as
        `kind="trace_event"` rows."""
        rec = {"ev": ev, "trace": trace, **fields}
        key = fields.get("replica")
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = deque(maxlen=self.capacity)
            ring.append(rec)
            self._total += 1
            self._ring_totals[key] = self._ring_totals.get(key, 0) + 1

    def snapshot(self) -> list[dict]:
        """Consistent merged copy of every ring, time-sorted. Safe from
        any thread while emitters keep appending."""
        with self._lock:
            evs = [e for ring in self._rings.values() for e in ring]
        return sorted(evs, key=_ev_time)

    @property
    def total_emitted(self) -> int:
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        """Events evicted by ring bounds — nonzero means long traces are
        incomplete and `--trace_capacity` should grow."""
        with self._lock:
            return self._total - sum(len(r) for r in self._rings.values())

    @property
    def dropped_by_replica(self) -> dict:
        """Per-ring eviction counts, keyed by the emitting replica label
        (None for a standalone engine), only nonzero entries — the
        summary/report surface that stops a saturated ring from silently
        reading as a complete history (a dropped event poisons every
        phase aggregate built from the ring)."""
        with self._lock:
            return {
                key: self._ring_totals.get(key, 0) - len(ring)
                for key, ring in self._rings.items()
                if self._ring_totals.get(key, 0) > len(ring)
            }

    def __len__(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._rings.values())


# ---- span-tree merge -----------------------------------------------------


def build_trees(events: list[dict]) -> list[dict]:
    """Merge raw events into one span tree per trace id. Quantum events
    are per-ENGINE (their `lanes` field lists the participating trace
    ids), everything else is per-request; the tree's phase walls follow
    the module-docstring partition. Returns trees sorted by trace id."""
    by_trace: dict = {}
    member: dict = {}  # trace id -> participating quantum events
    for ev in events:
        if ev.get("ev") == "quantum":
            for t in ev.get("lanes") or ():
                member.setdefault(t, []).append(ev)
        else:
            by_trace.setdefault(ev.get("trace"), []).append(ev)
    return [
        _build_tree(trace, evs, member.get(trace, []))
        for trace, evs in sorted(by_trace.items())
    ]


def _build_tree(trace, evs: list[dict], quanta: list[dict]) -> dict:
    evs = sorted(evs, key=_ev_time)
    of = lambda name: [e for e in evs if e.get("ev") == name]  # noqa: E731
    enq = of("enqueue")
    admits = of("admit")
    dones = of("prefill_done")
    adopts = of("adopt")
    fins = of("finish")
    requeues = of("requeue")
    rid = next((e["rid"] for e in evs if e.get("rid") is not None), trace)
    arrival = enq[0]["t"] if enq else (admits[0]["t"] if admits else 0.0)
    closed = bool(enq) and bool(admits) and len(fins) == 1

    # queue_wait: per attempt, (re)queue entry -> that attempt's admit
    starts = [arrival] + sorted(r["t"] for r in requeues)
    queue_wait = sum(
        max(a["t"] - starts[min(k, len(starts) - 1)], 0.0)
        for k, a in enumerate(admits)
    )
    # prefill: per attempt, admit -> the prefill_done landing before the
    # next attempt's admit
    bounds = [a["t"] for a in admits[1:]] + [float("inf")]
    prefill = 0.0
    for a, b in zip(admits, bounds):
        pd = next((d for d in dones if a["t"] - 1e-9 <= d["t"] <= b), None)
        if pd is not None:
            prefill += max(pd["t"] - a["t"], 0.0)
    # handoff: prefill_done (on the worker) -> adopt (on the decode
    # replica) — includes wait-for-capacity, claim and the page copy
    handoff = 0.0
    for ad in adopts:
        pd = next((d for d in reversed(dones) if d["t"] <= ad["t"]), None)
        if pd is not None:
            handoff += max(ad["t"] - pd["t"], 0.0)
    decode = sum(q["t1"] - q["t0"] for q in quanta)
    sync_stall = sum(q["s1"] - q["s0"] for q in quanta if "s1" in q)

    end = fins[0]["t"] if fins else max((_ev_time(e) for e in evs), default=arrival)
    e2e = max(end - arrival, 0.0)
    named = queue_wait + prefill + handoff + decode + sync_stall
    residual = named - e2e  # > 0 means named walls overran the lifetime
    complete = closed and residual <= SUM_TOL_S
    replicas = sorted(
        {str(e["replica"]) for e in admits + adopts + fins
         if e.get("replica") is not None}
    )
    return {
        "trace": trace,
        "rid": rid,
        "closed": closed,
        "complete": complete,
        "e2e_s": e2e,
        "phases": {
            "queue_wait": queue_wait,
            "prefill": prefill,
            "handoff": handoff,
            "decode": decode,
            "sync_stall": sync_stall,
            "other": max(e2e - named, 0.0),
        },
        "residual_s": max(residual, 0.0),
        "attempts": len(admits),
        "quanta": len(quanta),
        "replicas": replicas,
        "reason": fins[0].get("reason") if fins else None,
        "generated": fins[0].get("generated") if fins else None,
    }


# ---- derived views -------------------------------------------------------


def percentile(vals: list[float], q: float) -> float | None:
    """np.percentile's linear interpolation, stdlib-only (the exporter
    and report path must not import numpy)."""
    if not vals:
        return None
    v = sorted(vals)
    if len(v) == 1:
        return float(v[0])
    pos = (len(v) - 1) * q / 100.0
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(v) - 1)
    return float(v[lo] * (1.0 - frac) + v[hi] * frac)


def phase_stats(trees: list[dict]) -> tuple[dict, dict]:
    """(p50, p99) per phase over `trees` — the serve_summary /
    fleet_summary per-phase latency rows."""
    p50: dict = {}
    p99: dict = {}
    for ph in PHASES:
        vals = [t["phases"][ph] for t in trees]
        p50[ph] = percentile(vals, 50)
        p99[ph] = percentile(vals, 99)
    return p50, p99


def completeness(trees: list[dict]) -> float | None:
    """Fraction of trees satisfying the completeness invariant."""
    if not trees:
        return None
    return sum(1 for t in trees if t["complete"]) / len(trees)


def flush_to_logger(tracer: TraceRecorder, logger, trees=()) -> None:
    """Persist the ring into the metrics JSONL: one `kind="trace_event"`
    row per raw event plus one `kind="trace"` row per span tree — the
    rows report.py's `--min_trace_complete` gate and traceview read."""
    if tracer is None or logger is None:
        return
    for ev in tracer.snapshot():
        logger.log(kind="trace_event", **ev)
    for t in trees:
        logger.log(kind="trace", **t)


# ---- Chrome-trace / Perfetto export --------------------------------------


def to_chrome(events: list[dict]) -> dict:
    """Export events as Chrome-trace JSON (chrome://tracing / Perfetto
    `traceEvents` array, microsecond timestamps). Layout: one pid per
    emitting replica (tid 0 carries that engine's quantum dispatch/sync
    bars, tid trace+1 the per-request milestones and prefill/handoff
    spans) plus a synthetic "phases" pid with one contiguous bar set per
    request derived from its span tree."""
    pids: dict = {}

    def pid_for(rep) -> int:
        key = "engine" if rep is None else str(rep)
        if key not in pids:
            pids[key] = len(pids) + 1
        return pids[key]

    us = lambda s: round(s * 1e6, 3)  # noqa: E731
    out = []
    for ev in events:
        name = ev.get("ev", "?")
        pid = pid_for(ev.get("replica"))
        if name == "quantum":
            out.append({
                "name": f"dispatch x{ev.get('steps', 1)}", "ph": "X",
                "cat": "quantum", "pid": pid, "tid": 0,
                "ts": us(ev["t0"]), "dur": max(us(ev["t1"] - ev["t0"]), 1),
                "args": {"lanes": ev.get("lanes", [])},
            })
            if "s1" in ev:
                out.append({
                    "name": "sync", "ph": "X", "cat": "quantum",
                    "pid": pid, "tid": 0, "ts": us(ev["s0"]),
                    "dur": max(us(ev["s1"] - ev["s0"]), 1),
                    "args": {"lanes": ev.get("lanes", [])},
                })
        elif "t0" in ev:  # prefill / handoff spans
            label = name
            if ev.get("chunk") is not None:
                label = f"{name}[{ev['chunk']}]"
            out.append({
                "name": label, "ph": "X", "cat": name, "pid": pid,
                "tid": int(ev.get("trace", 0)) + 1, "ts": us(ev["t0"]),
                "dur": max(us(ev["t1"] - ev["t0"]), 1),
                "args": {"rid": ev.get("rid")},
            })
        else:  # point milestones
            args = {k: v for k, v in ev.items()
                    if k not in ("ev", "t", "replica")}
            out.append({
                "name": name, "ph": "i", "s": "t", "cat": "milestone",
                "pid": pid, "tid": int(ev.get("trace", 0)) + 1,
                "ts": us(ev.get("t", 0.0)), "args": args,
            })
    # contiguous per-request phase bars (tree-derived approximation:
    # decode+sync render as one "decode" residency bar)
    phase_pid = len(pids) + 1
    for tree in build_trees(events):
        if not tree["closed"]:
            continue
        tid = int(tree["trace"]) + 1
        # reconstruct boundaries from the cumulative walls; `other` is
        # folded into the decode residency tail
        ph = tree["phases"]
        arrival = None
        for ev in events:
            if ev.get("ev") == "enqueue" and ev.get("trace") == tree["trace"]:
                arrival = ev["t"]
                break
        if arrival is None:
            continue
        t = arrival
        segs = [("queue_wait", ph["queue_wait"]), ("prefill", ph["prefill"]),
                ("handoff", ph["handoff"]),
                ("decode", ph["decode"] + ph["sync_stall"] + ph["other"])]
        for label, dur in segs:
            if dur <= 0:
                continue
            out.append({
                "name": label, "ph": "X", "cat": "phase",
                "pid": phase_pid, "tid": tid, "ts": us(t),
                "dur": max(us(dur), 1), "args": {"rid": tree["rid"]},
            })
            t += dur
    for key, pid in pids.items():
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"replica {key}"}})
        out.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
                    "args": {"name": "engine quanta"}})
    out.append({"name": "process_name", "ph": "M", "pid": phase_pid,
                "args": {"name": "request phases"}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}
