"""Flight recorder: a bounded in-memory ring of the trainer's recent history.

The JSONL metrics log answers "how did the run go"; the flight recorder
answers "what was the run doing RIGHT BEFORE it died". It is a fixed-size
ring buffer (collections.deque with maxlen) of small host-side records —
step dispatches, window metrics, sentinel events, checkpoint saves,
divergence checksums — that is ALWAYS on: a record is one dict allocation
plus a deque append under a lock (sub-microsecond next to any real train
step, the <1% budget bench.py's `obs_overhead` record audits), and memory
is bounded by construction — the ring evicts the oldest record at
capacity, so a month-long run holds exactly `capacity` records.

Nothing reads the ring on the happy path. Its one consumer is the
diagnostics bundle (tpukit/obs/watchdog.py): when the hang watchdog or a
sentinel fires, `snapshot()` serializes the last-N history into the bundle
so the post-mortem shows what the trainer was doing when it stopped —
the Megatron-style production answer to "the tqdm bar froze" (PAPERS.md;
SURVEY §5 names failure observability as a first-class capability the
reference lacks entirely).

Thread-safety: `record()` runs on the training thread in the hot loop;
`snapshot()` runs on the watchdog's monitor thread at dump time. A plain
lock covers both — deque.append is itself atomic, but iterating a deque
while another thread appends raises RuntimeError, and a torn snapshot in
the one artifact written specifically for post-mortems is not acceptable.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class FlightRecorder:
    """Bounded ring of `{"t", "kind", ...}` records, oldest evicted first."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"recorder capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0  # lifetime count, so a bundle shows how much history
        # the ring evicted ("records 3017..3272 of 3272")

    def record(self, kind: str, **fields) -> None:
        """Append one record. Values must be JSON-serializable (the bundle
        writer stringifies anything that is not, but keep it plain)."""
        rec = {"t": time.time(), "kind": kind, **fields}
        with self._lock:
            self._ring.append(rec)
            self._total += 1

    def snapshot(self) -> list[dict]:
        """Consistent copy of the ring, oldest first. Safe to call from any
        thread while the training thread keeps recording."""
        with self._lock:
            return list(self._ring)

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._total

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
