"""Host-side span timeline: where does the step-loop wall clock go?

The trainer's hot loop has a handful of host-visible phases per step window
(PRINT_FREQ steps): waiting on the data loader, host-side batch transforms,
global-array assembly/H2D placement, dispatching the jitted step, and the
one D2H sync that closes the window. `SpanTimeline` accumulates wall clock
into named phases and emits per-window and per-epoch breakdowns whose
seconds sum exactly to the elapsed wall clock (anything not inside a span
lands in "other") — the goodput accounting the MPMD pipeline-parallelism
work (PAPERS.md) motivates per stage, applied to the whole trainer.

Honest-accounting note: JAX dispatch is asynchronous, so the "step" span
(the time spent *calling* the jitted step) is small and the device's compute
time surfaces as the host blocking in the "sync" span at the window end.
The goodput fraction is therefore step + sync over wall clock: the share of
host time spent either feeding the device or waiting for it — everything
else (data wait, H2D assembly, checkpoint I/O) is time the device is
potentially idle. On a healthy run goodput is close to 1; a data-bound run
shows it directly.

With the round-7 input prefetcher on (`--prefetch N`, the default), the
"data"/"h2d" phases move to a background thread and the loop's only input
cost is the "prefetch_stall" span — the time the consumer actually blocked
on the buffer (docs/DESIGN.md §7).
"""

from __future__ import annotations

import contextlib
import time

# Phases whose time counts as "inside the compiled step" for goodput: the
# dispatch call itself plus the device-wait sync at the window boundary.
GOODPUT_SPANS = ("step", "sync")


def _breakdown(acc: dict[str, float], total: float) -> dict:
    """Seconds + fractions for one window/epoch; `other` absorbs wall clock
    outside any span so the seconds always sum to `total`."""
    seconds = dict(acc)
    other = total - sum(seconds.values())
    # float error can push `other` epsilon-negative; clamp for sane output
    seconds["other"] = max(other, 0.0)
    denom = total if total > 0 else 1.0
    fractions = {k: v / denom for k, v in seconds.items()}
    goodput = sum(fractions.get(k, 0.0) for k in GOODPUT_SPANS)
    return {
        "total_s": total,
        "seconds": seconds,
        "fractions": fractions,
        "goodput": goodput,
    }


class SpanTimeline:
    """Accumulate wall clock into named phases; report per window and epoch.

    `span(name)` is a context manager. Nested spans attribute their time to
    the OUTERMOST span only (no double counting), so helpers wrapped in
    their own spans can be called from inside a larger phase safely.
    """

    def __init__(self):
        now = time.perf_counter()
        self._window_start = now
        self._epoch_start = now
        self._window_acc: dict[str, float] = {}
        self._epoch_acc: dict[str, float] = {}
        self._depth = 0

    @contextlib.contextmanager
    def span(self, name: str):
        if self._depth:
            yield  # nested: time already attributed to the outer span
            return
        self._depth += 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._depth -= 1
            self._window_acc[name] = self._window_acc.get(name, 0.0) + dt
            self._epoch_acc[name] = self._epoch_acc.get(name, 0.0) + dt

    def window(self) -> dict:
        """Close the current window: breakdown since the last `window()` (or
        construction/epoch reset), then reset the window accumulators."""
        now = time.perf_counter()
        out = _breakdown(self._window_acc, now - self._window_start)
        self._window_acc = {}
        self._window_start = now
        return out

    def epoch(self) -> dict:
        """Close the current epoch: breakdown since the last `epoch()` call
        (or construction). Also resets the window accumulators so a stale
        partial window does not leak into the next epoch."""
        now = time.perf_counter()
        out = _breakdown(self._epoch_acc, now - self._epoch_start)
        self._epoch_acc = {}
        self._epoch_start = now
        self._window_acc = {}
        self._window_start = now
        return out


def format_breakdown(b: dict) -> str:
    """One-line human rendering: `goodput 83% (step 2% + sync 81%) | data 9% ...`"""
    frac = b["fractions"]
    inside = " + ".join(
        f"{k} {frac.get(k, 0.0) * 100:.0f}%" for k in GOODPUT_SPANS if k in frac
    )
    rest = " | ".join(
        f"{k} {v * 100:.0f}%"
        for k, v in sorted(frac.items(), key=lambda kv: -kv[1])
        if k not in GOODPUT_SPANS and v >= 0.005
    )
    head = f"goodput {b['goodput'] * 100:.0f}%"
    if inside:
        head += f" ({inside})"
    return head + (f" | {rest}" if rest else "")
