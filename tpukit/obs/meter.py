"""Throughput/MFU metering, profiler tracing, and the JSONL step log.

Absorbed from the pre-obs `tpukit/profiling.py` (which is now a compat
shim). The reference has no profiling at all — its only throughput signal
is tqdm's implicit it/s counter (reference main-single.py:81; SURVEY §5).
Since the driver-defined baseline metric is tokens/sec/chip and MFU
(BASELINE.md), the meter is built into the trainer rather than bolted on:

  - `MFUMeter`: step timing -> tokens/sec, tokens/sec/chip, and model FLOPs
    utilization against the chip's peak bf16 FLOPs.
  - `profiler_trace` context: wraps `jax.profiler.trace` when a profile
    dir is set (request-scoped SERVING traces live in `tpukit.obs.trace`).
  - `StepLogger`: machine-readable JSONL step metrics (the surface
    `tools/report.py` renders).

FLOPs model (PaLM-appendix convention): per token, a forward pass costs
`2 * P_matmul` for the parameter matmuls plus `4 * S * inner_dim` per layer
for the attention score/value matmuls; training costs 3x forward (backward
is 2x). Embedding-table gathers are excluded from P_matmul; the lm_head is
included.
"""

from __future__ import annotations

import contextlib
import json
import time

import jax

from tpukit.model.gpt import GPTConfig

# Peak dense bf16 FLOPs/s per chip.
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip(device_kind: str | None = None) -> float | None:
    kind = device_kind or jax.devices()[0].device_kind
    for key, val in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.lower().startswith(key.lower()):
            return val
    return None  # CPU or unknown: MFU undefined


def matmul_param_count(cfg: GPTConfig) -> int:
    """Parameters that participate in matmuls (excludes embedding gathers).
    The lm_head runs at the padded vocab width — count the FLOPs actually
    executed, not the logical vocab."""
    inner = cfg.inner_dim
    per_layer = 3 * cfg.dim * inner + inner * cfg.dim + 2 * cfg.dim * (cfg.dim * cfg.ffn_mult)
    return cfg.num_layers * per_layer + cfg.dim * cfg.padded_vocab_size


def train_flops_per_token(cfg: GPTConfig, seq_len: int) -> float:
    """fwd (2*P + attention) x3 for fwd+bwd."""
    attn = 4 * seq_len * cfg.inner_dim * cfg.num_layers
    return 3 * (2 * matmul_param_count(cfg) + attn)


def moe_active_flops_per_token(cfg: GPTConfig, seq_len: int) -> float:
    """Training FLOPs per token counting only the ACTIVE expert parameters
    — the `top_k` routed experts plus the router — the dropless-MoE
    normalization ROADMAP #3's dispatch ladder uses. Capacity padding and
    one-hot dispatch/combine einsums are *implementation* FLOPs, not model
    FLOPs: normalizing MFU by this number makes the dispatch ladder
    comparable — a dataflow that burns FLOPs on padding rows shows a LOWER
    MFU at equal tokens/s instead of hiding inside a bigger FLOP budget.
    For dense configs this is exactly `train_flops_per_token`."""
    if cfg.num_experts <= 0:
        return train_flops_per_token(cfg, seq_len)
    inner = cfg.inner_dim
    ffn = 2 * cfg.dim * (cfg.dim * cfg.ffn_mult)  # up + down kernels
    per_layer = (
        3 * cfg.dim * inner + inner * cfg.dim          # qkv + attn out
        + cfg.router_top_k * ffn                       # active experts
        + cfg.dim * cfg.num_experts                    # router
    )
    params = cfg.num_layers * per_layer + cfg.dim * cfg.padded_vocab_size
    attn = 4 * seq_len * inner * cfg.num_layers
    return 3 * (2 * params + attn)


class MFUMeter:
    """Rolling tokens/sec + MFU over recent steps. `update()` once per step
    with the number of (real, global) tokens processed."""

    def __init__(self, cfg: GPTConfig, seq_len: int, num_chips: int | None = None):
        self.flops_per_token = train_flops_per_token(cfg, seq_len)
        self.num_chips = num_chips or len(jax.devices())
        self.peak = peak_flops_per_chip()
        self.reset()

    def reset(self):
        self._t0 = None
        self._tokens = 0
        self._steps = 0

    def update(self, tokens: int):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now  # first update starts the clock (skips compile)
            return
        self._tokens += tokens
        self._steps += 1
        self._elapsed = now - self._t0

    @property
    def total_tokens(self) -> int:
        """Global real tokens accumulated (timed steps only — the first
        update starts the clock and is not counted)."""
        return self._tokens

    @property
    def tokens_per_sec(self) -> float | None:
        if self._steps == 0 or self._elapsed == 0:
            return None
        return self._tokens / self._elapsed

    @property
    def tokens_per_sec_per_chip(self) -> float | None:
        tps = self.tokens_per_sec
        return tps / self.num_chips if tps else None

    @property
    def mfu(self) -> float | None:
        tps = self.tokens_per_sec_per_chip
        if tps is None or self.peak is None:
            return None
        return tps * self.flops_per_token / self.peak


@contextlib.contextmanager
def profiler_trace(profile_dir: str = ""):
    """jax.profiler trace hook (SURVEY §5 tracing plan). No-op when unset.

    Renamed from `trace` in round 20: `tpukit.obs.trace` is now the
    request-scoped serving-trace MODULE, so the profiler hook carries an
    unambiguous name. The old spelling survives below for the
    `tpukit.profiling` compat shim."""
    if profile_dir:
        with jax.profiler.trace(profile_dir):
            yield
    else:
        yield


trace = profiler_trace  # legacy alias (tpukit/profiling.py shim)


class StepLogger:
    """JSONL step-metrics log — the machine-readable observability surface
    the reference lacks (SURVEY §5 metrics plan). No-op when path is empty.

    Schema (docs/DESIGN.md "Telemetry & observability"): one JSON object
    per line, discriminated by `kind` — "train" window records, "validation"
    epoch records, "xla" once-per-compile static analysis, "epoch" span
    summaries, "spike" sentinel events, "compile_cache" hit/miss counts.
    `tools/report.py` renders a run.

    Hot-loop I/O discipline (round 7): the stream is opened ONCE,
    line-buffered, and each record is a single `write` of one complete
    line — no explicit per-record flush call, no reopen. Line buffering
    still pushes every record to the OS at its newline, so the worst a
    crash can leave is one torn final line — exactly what report.py's
    loader tolerates.
    """

    def __init__(self, path: str = ""):
        # buffering=1 = line-buffered text: the newline inside the single
        # write below is the flush point
        self._f = open(path, "a", buffering=1) if path else None

    def log(self, **record):
        if self._f is None:
            return
        record.setdefault("time", time.time())
        self._f.write(json.dumps(record) + "\n")  # one write per record

    def close(self):
        if self._f:
            self._f.close()
            self._f = None
