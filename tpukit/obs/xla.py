"""XLA static analysis of compiled step functions + live device memory.

Every run should know its roofline position and memory watermark without a
profiler attached (SURVEY §5; EQuARX in PAPERS.md shows collective volume
is a first-order cost worth metering). Three captures:

  - `compiled_stats(jitted_fn, *avals)`: AOT `lower().compile()` at the
    given avals and pull XLA's `cost_analysis()` (FLOPs, bytes accessed)
    and `memory_analysis()` (argument/output/temp/peak bytes). jit and the
    AOT path share the lowering/compilation caches, so when the trainer has
    already compiled the step this records the SAME executable rather than
    forcing a second compile.
  - `collective_bytes(hlo_text)`: per-collective-kind op counts and payload
    bytes parsed from the optimized HLO — the DP grad psum, FSDP
    all-gather/reduce-scatter, pipeline/ring ppermute, and MoE all_to_all
    traffic REPORTED from the compiled module instead of estimated from
    first principles. Each strategy declares which kinds it expects
    (`Strategy.comm_ops`), so a report can flag surprises.
  - `live_memory_stats()`: `device.memory_stats()` gauges (bytes in use,
    peak, limit) for the per-window HBM watermark line. Returns None on
    backends without the API (CPU).

Round 10 adds the hand-scheduled-collective audit half:
`capture_compiler_stderr()` (fd-level stderr capture — the channel XLA's
C++ partitioner warnings arrive on) and `count_involuntary_remat()` (the
`[SPMD] Involuntary full rematerialization` fallback, GSPMD's
replicate-then-repartition last resort — the round-5 EP dispatch
regression MULTICHIP_r05.json caught; zero is the bar for any step whose
collectives are placed by hand).

Round 16: the flat-regex HLO parse moved into `tpukit/analysis/hlo_ir.py`
as a structured IR (computations → instructions, while-body membership,
async pairing, the alias table). `collective_bytes`/`wire_bytes` here are
thin wrappers over it — same contract, same numbers (the golden-fixture
tests prove byte-for-byte equality against the original regex, kept below
as `_collective_bytes_regex` for exactly that proof).

Everything here is best-effort: any backend that lacks an analysis returns
None for that field rather than raising — telemetry must never take down a
training run.
"""

from __future__ import annotations

import contextlib
import os
import re
import sys
import tempfile

import jax

from tpukit.analysis import hlo_ir as _ir
from tpukit.analysis import plan as _plan
from tpukit.analysis.rules import (  # noqa: F401  (re-exported API)
    INVOLUNTARY_REMAT,
    count_involuntary_remat,
)

# Re-exported: the one spelling lives in analysis/hlo_ir.py.
COLLECTIVE_OPS = _ir.COLLECTIVE_OPS

# The pre-round-16 flat parse: `%x = SHAPES op-name(` where SHAPES is a
# single shape or a (tuple). Kept ONLY as the equivalence oracle for the
# golden-fixture tests (tests/test_analysis.py) — production callers go
# through the IR.
_OP_RE = re.compile(
    r"=\s+((?:\([^)]*\))|(?:\S+))\s+("
    + "|".join(COLLECTIVE_OPS)
    + r")(-start)?\("
)


def _collective_bytes_regex(hlo_text: str) -> dict[str, dict[str, int]]:
    """The original flat-regex parse, verbatim semantics. Test oracle."""
    out: dict[str, dict[str, int]] = {}
    for m in _OP_RE.finditer(hlo_text):
        shape_str, op, start = m.group(1), m.group(2), m.group(3)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _ir.result_payload_bytes(
            shape_str, op, is_start=start is not None
        )
    return out


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """Parse optimized HLO text into {op: {count, bytes}} for the
    collective kinds above. `bytes` is the summed RESULT payload of each op
    instance — the volume moved per executed step (an all-reduce's result
    equals its input size; an all-gather's result is the post-gather
    size). Async `-start`/`-done` pairs count once, by their result.

    Thin wrapper over the structured IR (analysis/hlo_ir.py): each op is
    attributed to its computation once — a collective inside a while body
    is the body's, not a text offset — so rule-engine callers and this
    summary read the same parse."""
    return _ir.collective_summary(_ir.parse_hlo(hlo_text))


def wire_bytes(collectives: dict[str, dict[str, int]], world: int) -> int:
    """Ring-model per-device interconnect bytes for a parsed collective
    summary — see `analysis.plan.ring_wire_bytes` (the one spelling; this
    wrapper keeps the historical obs import path)."""
    return _plan.ring_wire_bytes(collectives, world)


@contextlib.contextmanager
def capture_compiler_stderr(check: bool = False):
    """Capture OS-level stderr (fd 2) for the duration of the block — the
    channel XLA's C++ partitioner warnings arrive on, which Python-level
    sys.stderr redirection cannot see. Yields a dict whose "text" key holds
    the captured output after the block exits; whatever was captured is
    re-emitted to the real stderr so no diagnostics are swallowed.

    The involuntary-remat count is tallied at exit into the holder's
    "involuntary_remat" key — callers that used to re-spell
    `count_involuntary_remat(cap["text"])` read the count instead.
    `check=True` additionally RAISES on a nonzero count (the dryrun/test
    discipline: hand-placed collectives must compile warning-free).

    Used to audit a compile for involuntary-remat warnings (the dryrun's
    EP world, bench.py's moe_ep_comm probe, tests). Note: a compile served
    from the persistent compilation cache emits no warnings either way —
    the audit is meaningful on cold compiles.
    """
    sys.stderr.flush()
    holder = {"text": "", "involuntary_remat": 0}
    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    try:
        os.dup2(tmp.fileno(), 2)
        yield holder
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
        tmp.seek(0)
        holder["text"] = tmp.read().decode("utf-8", errors="replace")
        tmp.close()
        if holder["text"]:
            sys.stderr.write(holder["text"])
            sys.stderr.flush()
        holder["involuntary_remat"] = count_involuntary_remat(holder["text"])
    if check and holder["involuntary_remat"]:
        raise AssertionError(
            f"compile emitted {holder['involuntary_remat']} involuntary-"
            f"remat warning(s) — hand-placed collectives are supposed to "
            f"make these zero:\n{holder['text'][-2000:]}"
        )


def _cost_analysis_dict(compiled) -> dict | None:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # jax returned a list-of-dicts (one per computation) before ~0.5, a
    # plain dict after
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else None


def _memory_analysis_dict(compiled) -> dict | None:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    )
    out = {f: int(getattr(ma, f)) for f in fields if hasattr(ma, f)}
    if "argument_size_in_bytes" in out and "temp_size_in_bytes" in out:
        # arguments alias in place (donated state), so live peak is
        # args + outputs-not-aliased + temps; report the conservative sum
        out["peak_bytes_estimate"] = (
            out["argument_size_in_bytes"]
            + out.get("output_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0)
            + out["temp_size_in_bytes"]
        )
    return out or None


def compiled_stats(jitted_fn, *args, hlo_out: dict | None = None,
                   **kwargs) -> dict | None:
    """Static analysis record for `jitted_fn` at the given avals (pass
    `jax.ShapeDtypeStruct`s or arrays). Returns None when lowering fails;
    individual analyses a backend lacks come back as None fields.

    Record fields: `flops`, `bytes_accessed`, `transcendentals` (per
    executed step, from cost_analysis), `memory` (memory_analysis sizes),
    `collectives` ({op: {count, bytes}} from the optimized HLO).

    `hlo_out`: optional dict that receives the optimized module text under
    "text" — fit()'s rule-engine pass (analysis/rules.py) reads it so the
    hlolint verdicts ride the same AOT compile as the stats instead of
    paying a second lower().
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except Exception:
        return None
    out: dict = {"flops": None, "bytes_accessed": None, "memory": None,
                 "collectives": None}
    ca = _cost_analysis_dict(compiled)
    if ca:
        out["flops"] = ca.get("flops")
        out["bytes_accessed"] = ca.get("bytes accessed")
        if ca.get("transcendentals"):
            out["transcendentals"] = ca.get("transcendentals")
    out["memory"] = _memory_analysis_dict(compiled)
    try:
        text = compiled.as_text()
        if hlo_out is not None:
            hlo_out["text"] = text
        out["collectives"] = collective_bytes(text)
    except Exception:
        pass
    return out


def live_memory_stats(device=None) -> dict | None:
    """Current device memory gauges, or None where the backend has no
    `memory_stats()` (CPU). Keys mirror PJRT's: bytes_in_use,
    peak_bytes_in_use, bytes_limit (whichever the platform reports)."""
    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None
