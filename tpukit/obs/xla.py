"""XLA static analysis of compiled step functions + live device memory.

Every run should know its roofline position and memory watermark without a
profiler attached (SURVEY §5; EQuARX in PAPERS.md shows collective volume
is a first-order cost worth metering). Three captures:

  - `compiled_stats(jitted_fn, *avals)`: AOT `lower().compile()` at the
    given avals and pull XLA's `cost_analysis()` (FLOPs, bytes accessed)
    and `memory_analysis()` (argument/output/temp/peak bytes). jit and the
    AOT path share the lowering/compilation caches, so when the trainer has
    already compiled the step this records the SAME executable rather than
    forcing a second compile.
  - `collective_bytes(hlo_text)`: per-collective-kind op counts and payload
    bytes parsed from the optimized HLO — the DP grad psum, FSDP
    all-gather/reduce-scatter, pipeline/ring ppermute, and MoE all_to_all
    traffic REPORTED from the compiled module instead of estimated from
    first principles. Each strategy declares which kinds it expects
    (`Strategy.comm_ops`), so a report can flag surprises.
  - `live_memory_stats()`: `device.memory_stats()` gauges (bytes in use,
    peak, limit) for the per-window HBM watermark line. Returns None on
    backends without the API (CPU).

Round 10 adds the hand-scheduled-collective audit half:
`capture_compiler_stderr()` (fd-level stderr capture — the channel XLA's
C++ partitioner warnings arrive on) and `count_involuntary_remat()` (the
`[SPMD] Involuntary full rematerialization` fallback, GSPMD's
replicate-then-repartition last resort — the round-5 EP dispatch
regression MULTICHIP_r05.json caught; zero is the bar for any step whose
collectives are placed by hand).

Everything here is best-effort: any backend that lacks an analysis returns
None for that field rather than raising — telemetry must never take down a
training run.
"""

from __future__ import annotations

import contextlib
import os
import re
import sys
import tempfile

import jax

# HLO collective ops worth metering, normalized (async "-start" variants
# fold into the base name; "-done" carries no payload and is skipped).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `f32[8,256]{1,0}` or scalar `f32[]` — group 1 dtype, group 2 dims.
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# `%x = SHAPES op-name(` where SHAPES is a single shape or a (tuple).
_OP_RE = re.compile(
    r"=\s+((?:\([^)]*\))|(?:\S+))\s+("
    + "|".join(COLLECTIVE_OPS)
    + r")(-start)?\("
)


def _shape_list(shape_str: str) -> list[tuple[str, int]]:
    """[(dtype, bytes)] for every array shape in a shape/tuple string."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        size = _ITEMSIZE.get(dtype)
        if size is None:
            continue  # token/opaque types carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dtype, n * size))
    return out


# Async `-start` ops whose result tuple ALIASES the operands alongside the
# results: `(operands..., results..., ctx scalars...)`. all-reduce-start's
# tuple (when present) holds only the reduced results — XLA's combiner
# fuses grad buffers into one variadic all-reduce — so halving it would
# drop real payload.
_START_WITH_OPERAND_ALIASES = ("all-gather", "collective-permute")


def _result_bytes(shape_str: str, op: str, is_start: bool) -> int:
    """Result payload of one collective instance. Sync ops: the full result
    shape (a tuple IS the result for multi-operand all-reduce). For async
    `-start` forms of the operand-aliasing ops above, count only the
    results half, else the aliases double the reported volume on exactly
    the backends (TPU) that emit async pairs."""
    shapes = _shape_list(shape_str)
    if is_start and op in _START_WITH_OPERAND_ALIASES:
        # drop the u32/s32 context scalars these async ops append
        shapes = [
            (dt, b) for dt, b in shapes
            if not (b <= 8 and dt in ("u32", "s32", "u64", "s64"))
        ]
        if len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
    return sum(b for _, b in shapes)


def collective_bytes(hlo_text: str) -> dict[str, dict[str, int]]:
    """Parse optimized HLO text into {op: {count, bytes}} for the
    collective kinds above. `bytes` is the summed RESULT payload of each op
    instance — the volume moved per executed step (an all-reduce's result
    equals its input size; an all-gather's result is the post-gather
    size). Async `-start`/`-done` pairs count once, by their result."""
    out: dict[str, dict[str, int]] = {}
    for m in _OP_RE.finditer(hlo_text):
        shape_str, op, start = m.group(1), m.group(2), m.group(3)
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _result_bytes(shape_str, op, is_start=start is not None)
    return out


def wire_bytes(collectives: dict[str, dict[str, int]], world: int) -> int:
    """Estimated bytes each device actually moves over the interconnect
    for the parsed collectives, from their RESULT payloads (what
    `collective_bytes` reports) via the standard ring-algorithm cost
    model. Needed because result bytes are not comparable ACROSS op kinds:
    a reduce-scatter's result is 1/world of the data it moved, an
    all-reduce moves ~2x its result (reduce-scatter + all-gather phases).
    Per-device wire cost for result payload R on a `world`-way ring:

      all-reduce         2 * R * (world-1)/world   (RS + AG phases)
      all-gather             R * (world-1)/world
      all-to-all             R * (world-1)/world
      reduce-scatter         R * (world-1)          (result is 1/world)
      collective-permute     R                      (one hop)

    This is the denominator-normalizer for the quantized-collective
    headline (bench.py's quant_comm record, tests): "int8 moves <= 30% of
    the f32 wire bytes" compares ring-model wire, not raw result sizes."""
    if world <= 1:
        return 0
    frac = (world - 1) / world
    mult = {
        "all-reduce": 2.0 * frac,
        "all-gather": frac,
        "all-to-all": frac,
        "reduce-scatter": float(world - 1),
        "collective-permute": 1.0,
    }
    total = 0.0
    for op, rec in collectives.items():
        total += rec.get("bytes", 0) * mult.get(op, 1.0)
    return int(total)


# The GSPMD partitioner's last-resort warning (spmd_partitioner.cc): it
# could not move a tensor between two shardings efficiently, so it
# REPLICATES the full tensor and re-partitions — for MoE dispatch that is
# exactly the all-device traffic expert parallelism exists to avoid. The
# round-5 EP dryrun hit this on the backward of the dispatch einsum
# (MULTICHIP_r05.json); the a2a dispatch path must never trigger it.
INVOLUNTARY_REMAT = "Involuntary full rematerialization"


def count_involuntary_remat(text: str) -> int:
    """Number of `[SPMD] Involuntary full rematerialization` warnings in a
    compiler log / captured stderr — each one is a tensor GSPMD gave up on
    and resolved by replicate-then-repartition. Zero is the bar for any
    step whose collectives are hand-placed."""
    return text.count(INVOLUNTARY_REMAT)


@contextlib.contextmanager
def capture_compiler_stderr():
    """Capture OS-level stderr (fd 2) for the duration of the block — the
    channel XLA's C++ partitioner warnings arrive on, which Python-level
    sys.stderr redirection cannot see. Yields a dict whose "text" key holds
    the captured output after the block exits; whatever was captured is
    re-emitted to the real stderr so no diagnostics are swallowed.

    Used to audit a compile for involuntary-remat warnings (the dryrun's
    EP world, bench.py's moe_ep_comm probe, tests). Note: a compile served
    from the persistent compilation cache emits no warnings either way —
    the audit is meaningful on cold compiles.
    """
    sys.stderr.flush()
    holder = {"text": ""}
    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    try:
        os.dup2(tmp.fileno(), 2)
        yield holder
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
        tmp.seek(0)
        holder["text"] = tmp.read().decode("utf-8", errors="replace")
        tmp.close()
        if holder["text"]:
            sys.stderr.write(holder["text"])
            sys.stderr.flush()


def _cost_analysis_dict(compiled) -> dict | None:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # jax returned a list-of-dicts (one per computation) before ~0.5, a
    # plain dict after
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else None


def _memory_analysis_dict(compiled) -> dict | None:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    )
    out = {f: int(getattr(ma, f)) for f in fields if hasattr(ma, f)}
    if "argument_size_in_bytes" in out and "temp_size_in_bytes" in out:
        # arguments alias in place (donated state), so live peak is
        # args + outputs-not-aliased + temps; report the conservative sum
        out["peak_bytes_estimate"] = (
            out["argument_size_in_bytes"]
            + out.get("output_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0)
            + out["temp_size_in_bytes"]
        )
    return out or None


def compiled_stats(jitted_fn, *args, **kwargs) -> dict | None:
    """Static analysis record for `jitted_fn` at the given avals (pass
    `jax.ShapeDtypeStruct`s or arrays). Returns None when lowering fails;
    individual analyses a backend lacks come back as None fields.

    Record fields: `flops`, `bytes_accessed`, `transcendentals` (per
    executed step, from cost_analysis), `memory` (memory_analysis sizes),
    `collectives` ({op: {count, bytes}} from the optimized HLO).
    """
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except Exception:
        return None
    out: dict = {"flops": None, "bytes_accessed": None, "memory": None,
                 "collectives": None}
    ca = _cost_analysis_dict(compiled)
    if ca:
        out["flops"] = ca.get("flops")
        out["bytes_accessed"] = ca.get("bytes accessed")
        if ca.get("transcendentals"):
            out["transcendentals"] = ca.get("transcendentals")
    out["memory"] = _memory_analysis_dict(compiled)
    try:
        out["collectives"] = collective_bytes(compiled.as_text())
    except Exception:
        pass
    return out


def live_memory_stats(device=None) -> dict | None:
    """Current device memory gauges, or None where the backend has no
    `memory_stats()` (CPU). Keys mirror PJRT's: bytes_in_use,
    peak_bytes_in_use, bytes_limit (whichever the platform reports)."""
    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None
