"""Checkpoint save/restore.

The reference checkpoints once, at end of training, write-only, to a
timestamped `checkpoints/checkpoint-<YYYY-mm-dd_HH-MM-SS>.pt` (reference
main-single.py:146-151); there is **no resume path anywhere** (SURVEY §2.8).
tpukit twins the save surface (same directory/naming scheme, process-0-only
in distributed recipes like main-ddp.py:179-185 / main-fsdp.py:193-200) and
adds what the reference lacks: restore, periodic step-keyed saves, and
optimizer-state capture so a restore actually resumes training.

Format: msgpack of the full train-state pytree (params + opt state + step)
via flax.serialization. Sharded states are gathered to host before writing —
the twin of FSDP's full `state_dict()` gather-then-rank-0-save
(main-fsdp.py:194-200): the on-disk artifact is always consolidated
(unsharded), so any strategy can restore any other strategy's checkpoint.
"""

from __future__ import annotations

import datetime
import os
from pathlib import Path

import jax
from flax import serialization

from tpukit.mesh import is_process_zero, sync_global_devices


def _timestamp_name() -> str:
    return "checkpoint-" + datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S") + ".msgpack"


def save(state, directory: str | os.PathLike = "checkpoints", name: str | None = None) -> Path | None:
    """Consolidate + write the train state. Returns the path (process 0) or
    None (other processes). Safe to call from all processes — the gather is
    collective, the write is process-0-only."""
    host_state = jax.device_get(state)  # gathers sharded leaves
    sync_global_devices("checkpoint_gathered")
    if not is_process_zero():
        return None
    directory = Path(directory).resolve()
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / (name or _timestamp_name())
    blob = serialization.to_bytes(host_state)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(blob)
    tmp.rename(path)  # atomic publish: no torn checkpoints on crash
    return path


def restore(template, path: str | os.PathLike):
    """Restore into the structure of `template` (a freshly-initialized train
    state). The caller re-applies the strategy's shardings by passing the
    result through the jitted step (or `jax.device_put` with the state
    sharding)."""
    blob = Path(path).read_bytes()
    return serialization.from_bytes(template, blob)


def latest(directory: str | os.PathLike = "checkpoints") -> Path | None:
    directory = Path(directory)
    if not directory.is_dir():
        return None
    candidates = sorted(directory.glob("checkpoint-*.msgpack"))
    return candidates[-1] if candidates else None


# ---------------------------------------------------------------------------
# Sharded checkpoints (VERDICT r1 #7).
#
# The consolidated msgpack above gathers the whole state to one host —
# fine for GPT-20M, impossible for the GPT-XL pipe x ddp ladder config on a
# pod. The sharded format writes, per process, only the shards that process
# addressably owns (deduplicated by replica_id), so no host ever
# materializes the full state and hosts write in parallel:
#
#   <name>.sharded/
#     manifest.json    # leaf paths, global shapes/dtypes (process 0)
#     shard-<pid>.npz  # "<leaf-idx>|<start,start,...>" -> local block
#
# Restore rebuilds each leaf through `jax.make_array_from_callback` with the
# *target* sharding, so a checkpoint written under one strategy restores
# into any other strategy's shardings (FSDP -> TP, pipe -> single, ...).
# ---------------------------------------------------------------------------


def _leaf_paths(tree) -> list[str]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(str(k) for k in path) for path, _ in flat]


def save_sharded(state, directory: str | os.PathLike = "checkpoints", name: str | None = None) -> Path:
    """Write a sharded checkpoint. Every process participates; returns the
    checkpoint directory. Atomic publish: everything is written into a
    `.tmp` directory that process 0 renames only after all processes have
    finished their shard files — a crash mid-save leaves no directory that
    `latest_sharded`/`restore_sharded` would pick up."""
    import json

    import numpy as np

    base = Path(directory) / ((name or _timestamp_name().replace(".msgpack", "")) + ".sharded")
    tmp = base.with_name(base.name + ".tmp")
    if is_process_zero():
        tmp.mkdir(parents=True, exist_ok=True)
    sync_global_devices("sharded_ckpt_mkdir")

    leaves = [_as_jax_array(l) for l in jax.tree_util.tree_leaves(state)]
    blocks = {}
    for i, arr in enumerate(leaves):
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one process writes each block
            starts = [s.start or 0 for s in shard.index] if shard.index else []
            key = f"{i}|{','.join(map(str, starts))}"
            blocks[key] = np.asarray(shard.data)
    np.savez(tmp / f"shard-{jax.process_index():05d}.npz", **blocks)

    if is_process_zero():
        manifest = {
            "nprocs": jax.process_count(),
            "paths": _leaf_paths(state),
            "leaves": [
                {"shape": list(l.shape), "dtype": str(l.dtype)} for l in leaves
            ],
        }
        (tmp / "manifest.json").write_text(json.dumps(manifest))
    sync_global_devices("sharded_ckpt_written")
    if is_process_zero():
        tmp.rename(base)  # atomic publish
    sync_global_devices("sharded_ckpt_published")
    return base


def _as_jax_array(x) -> jax.Array:
    import jax.numpy as jnp

    return x if isinstance(x, jax.Array) else jnp.asarray(x)


def restore_sharded(path: str | os.PathLike, template, sharding_tree=None):
    """Restore a sharded checkpoint into the structure of `template`,
    placing each leaf with `sharding_tree` (defaults to the template
    leaves' own shardings). The target shardings need not match the ones
    the checkpoint was written under."""
    import json

    import numpy as np

    base = Path(path)
    manifest = json.loads((base / "manifest.json").read_text())
    shard_files = sorted(base.glob("shard-*.npz"))
    archives = [np.load(f) for f in shard_files]

    flat, treedef = jax.tree_util.tree_flatten(template)
    if sharding_tree is None:
        shardings = [getattr(l, "sharding", None) for l in flat]
    else:
        shardings = jax.tree_util.tree_leaves(
            sharding_tree, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding)
        )
    if len(flat) != len(manifest["leaves"]):
        raise ValueError(
            f"template has {len(flat)} leaves, checkpoint has "
            f"{len(manifest['leaves'])} ({base})"
        )

    restored = []
    for i, (leaf, meta, sharding) in enumerate(zip(flat, manifest["leaves"], shardings)):
        shape, dtype = tuple(meta["shape"]), np.dtype(meta["dtype"])
        full = np.empty(shape, dtype)
        covered = 0  # blocks are disjoint by construction (replica_id==0)
        prefix = f"{i}|"
        for ar in archives:
            for key in ar.files:
                if not key.startswith(prefix):
                    continue
                starts_s = key[len(prefix):]
                block = ar[key]
                if starts_s:
                    starts = [int(s) for s in starts_s.split(",")]
                    idx = tuple(
                        slice(st, st + bs) for st, bs in zip(starts, block.shape)
                    )
                    full[idx] = block
                else:
                    full[()] = block
                covered += int(block.size) if block.shape else 1
        expected = int(np.prod(shape)) if shape else 1
        if covered != expected:
            raise ValueError(
                f"checkpoint {base}: leaf {i} ({manifest['paths'][i]}) has "
                f"{covered}/{expected} elements — a shard-*.npz file is "
                f"missing (saved from {manifest['nprocs']} processes; are "
                f"all shard files on this filesystem?)"
            )
        if sharding is not None:
            restored.append(
                jax.make_array_from_callback(shape, sharding, lambda idx, f=full: f[idx])
            )
        else:
            restored.append(_as_jax_array(full))
    return jax.tree_util.tree_unflatten(treedef, restored)


def latest_sharded(directory: str | os.PathLike = "checkpoints") -> Path | None:
    directory = Path(directory)
    if not directory.is_dir():
        return None
    candidates = sorted(
        p for p in directory.glob("*.sharded")
        if p.is_dir() and (p / "manifest.json").exists()
    )
    return candidates[-1] if candidates else None
