"""Checkpoint save/restore.

The reference checkpoints once, at end of training, write-only, to a
timestamped `checkpoints/checkpoint-<YYYY-mm-dd_HH-MM-SS>.pt` (reference
main-single.py:146-151); there is **no resume path anywhere** (SURVEY §2.8).
tpukit twins the save surface (same directory/naming scheme, process-0-only
in distributed recipes like main-ddp.py:179-185 / main-fsdp.py:193-200) and
adds what the reference lacks: restore, periodic step-keyed saves, and
optimizer-state capture so a restore actually resumes training.

Formats (two, auto-selected by `save_auto`):
  - consolidated: msgpack of the full train-state pytree (params + opt state
    + step) via flax.serialization. Sharded states are gathered to host
    before writing — the twin of FSDP's full `state_dict()`
    gather-then-rank-0-save (main-fsdp.py:194-200). Only valid when every
    leaf is host-gatherable (single host, or multi-host fully-replicated —
    exactly the regime where the reference's gather-then-save works too).
  - sharded: per-process shard files + manifest (below). The only format
    that works for state spanning hosts (multi-host FSDP/pipeline), where
    `jax.device_get` of a non-addressable, non-replicated array raises.

Checkpoints are step-keyed (`checkpoint-step000000123.*`), so periodic saves
never collide (two saves in the same wall-clock second used to overwrite
each other) and `latest`/`latest_any` resume picks by training step, not by
timestamp string sort.

Round 9 (the detect→recover loop) adds three properties this file is now
the source of truth for:

  - **Integrity**: every save records a content checksum — a sha256
    sidecar (`<name>.msgpack.sha256`) for the consolidated format, a
    `checksums` map inside `manifest.json` for the sharded one — and
    `latest`/`latest_any`/`latest_good` SKIP corrupt or partial
    checkpoints (checksum mismatch, missing manifest/shards) with a
    warning instead of restoring garbage. "Roll back to the last good
    checkpoint" means the last one that passes `verify_checkpoint`.
  - **Resume metadata**: saves can carry a small `meta` sidecar
    (`read_meta`/`meta_path`) recording the epoch + batch position (and
    whether the save was a preemption save), which is what lets
    `--resume latest` continue a preempted run MID-epoch bit-exact.
  - **Transient-fault tolerance**: the raw file I/O (blob/shard/manifest
    writes, blob reads) runs under `tpukit.retry.retry_io` — a jittered
    exponential backoff that fails loud after its budget — with
    `tpukit.chaos` injection hooks inside the retried operations so the
    path is deterministically testable.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import re
import threading
import time
import warnings
from pathlib import Path

import jax
from flax import serialization

from tpukit import chaos as chaos_lib
from tpukit import fsio
from tpukit.mesh import is_process_zero, sync_global_devices
from tpukit.retry import retry_io


def step_name(state) -> str:
    """Deterministic, step-keyed checkpoint stem. Identical on every process
    (`state.step` is replicated), unlike a per-process wall-clock timestamp —
    on a pod, clock skew across hosts must never split one logical save into
    differently-named directories."""
    step = int(jax.device_get(getattr(state, "step", 0)))
    return f"checkpoint-step{step:09d}"


_STEP_RE = re.compile(r"checkpoint-step(\d+)")


def _step_of(path: Path) -> int:
    m = _STEP_RE.search(path.name)
    return int(m.group(1)) if m else -1  # legacy timestamp names sort first


# ---------------------------------------------------------------------------
# Integrity + resume-metadata sidecars (round 9).
# ---------------------------------------------------------------------------


def checksum_sidecar(path: str | os.PathLike) -> Path:
    """The sha256 sidecar next to a consolidated checkpoint file. (Sharded
    directories carry their checksums inside manifest.json instead.)"""
    path = Path(path)
    return path.with_name(path.name + ".sha256")


def meta_path(path: str | os.PathLike) -> Path:
    """The resume-metadata sidecar: `<file>.meta.json` for a consolidated
    checkpoint, `resume.json` inside a sharded directory."""
    path = Path(path)
    if path.suffix == ".sharded" or path.is_dir():
        return path / "resume.json"
    return path.with_name(path.name + ".meta.json")


def read_meta(path: str | os.PathLike) -> dict | None:
    """The save-time metadata (epoch, batch position, preempted flag), or
    None for checkpoints without it (pre-round-9, or foreign writers)."""
    try:
        return json.loads(meta_path(path).read_text())
    except (OSError, ValueError):
        return None


def _sha256_bytes(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write_text(path: Path, text: str) -> None:
    # historical name, kept for the many existing call sites (here,
    # recovery.py, reshard.py); the actual spelling lives in the
    # stdlib-only tpukit/fsio.py so light consumers (heartbeat, the
    # watchdog's hang-dump thread) can use it without importing this
    # module's jax/flax stack
    fsio.atomic_write_text(path, text)


def _publish_sidecars(path: Path, digest: str, meta: dict | None) -> None:
    """Checksum + metadata sidecars for a just-published consolidated
    checkpoint. Written AFTER the blob publish: a crash in between leaves
    a blob without a sidecar, which verification treats as legacy-
    unverified (accepted with a warning) — never a false corruption."""
    _atomic_write_text(checksum_sidecar(path), digest)
    if meta is not None:
        _atomic_write_text(meta_path(path), json.dumps(meta))


def _parse_block_key(key: str) -> tuple[int, list[int]]:
    """Parse a shard block key `"<leaf-idx>|<start,start,...>"` (the format
    `_shard_blocks` writes; empty starts = scalar leaf). The ONE spelling
    of the key format — shared by the geometry check, `restore_sharded`
    and the elastic reshard pass (tpukit/reshard.py), so a format change
    cannot desynchronize save, verify and restore. Raises ValueError on a
    malformed key."""
    idx_s, _, starts_s = key.partition("|")
    starts = [int(s) for s in starts_s.split(",")] if starts_s else []
    return int(idx_s), starts


def _read_shard_manifest(base: Path) -> tuple[dict, list[Path]]:
    """manifest.json (retried read) + exactly the shard files its recorded
    world wrote, existence-checked — a stale extra shard-*.npz (e.g. from
    a crashed save under a different world size, on a filesystem where
    the pre-save cleanup could not see it) must never be read into a
    restore. Shared by `restore_sharded` and the elastic reshard pass."""
    manifest = json.loads(
        retry_io(_read_blob, base / "manifest.json", label="ckpt_read")
    )
    shard_files = [
        base / f"shard-{pid:05d}.npz" for pid in range(manifest["nprocs"])
    ]
    missing = [str(f) for f in shard_files if not f.exists()]
    if missing:
        raise FileNotFoundError(
            f"checkpoint {base}: missing shard files {missing} (saved from "
            f"{manifest['nprocs']} processes; are all shard files on this "
            f"filesystem?)"
        )
    return manifest, shard_files


def _sharding_leaves(template_flat, sharding_tree) -> list:
    """Per-leaf target shardings: `sharding_tree`'s Sharding leaves, or the
    template leaves' own (None for plain host arrays). Shared by
    `restore_sharded` and the reshard pass."""
    if sharding_tree is None:
        return [getattr(l, "sharding", None) for l in template_flat]
    return jax.tree_util.tree_leaves(
        sharding_tree, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding)
    )


def _npz_block_headers(path: Path):
    """Yield (key, shape, dtype) for every array in an npz WITHOUT reading
    array data — the shapes come from the npy headers inside the zip, so
    checking a multi-GB shard's block geometry costs kilobytes of I/O."""
    import zipfile

    from numpy.lib import format as npformat

    with zipfile.ZipFile(path) as zf:
        for name in zf.namelist():
            key = name[:-4] if name.endswith(".npy") else name
            with zf.open(name) as fp:
                version = npformat.read_magic(fp)
                shape, _, dtype = npformat._read_array_header(fp, version)
            yield key, tuple(shape), dtype


def _check_shard_geometry(shard_files: list[Path], manifest: dict) -> str | None:
    """Cross-check the shard files' block geometry against the manifest's
    recorded per-leaf global shapes/dtypes. Returns a detail string on
    mismatch (geometry-shaped failures carry a `world mismatch:` prefix,
    plain I/O damage does not — the two point an operator at different
    triage paths), None when everything fits and covers exactly.

    This is what catches a manifest paired with shards from a DIFFERENT
    world (a stale shard file surviving a crashed save at another world
    size, or an operator copying shards between runs): the per-file
    checksums only prove each file is internally intact, while the
    geometry check proves the set of blocks actually tiles the state the
    manifest describes. Duplicate (leaf, starts) keys ACROSS shard files
    are rejected outright — element counts alone would let a duplicate
    block mask a missing one exactly. Header-only reads — no array data
    is touched."""
    leaves = manifest.get("leaves")
    if leaves is None:
        return None  # foreign/minimal manifest: nothing to check against
    paths = manifest.get("paths") or [str(i) for i in range(len(leaves))]
    covered = [0] * len(leaves)
    seen: set[tuple[int, tuple[int, ...]]] = set()
    for f in shard_files:
        try:
            headers = list(_npz_block_headers(f))
        except Exception as exc:  # zip/npy damage: verify as unreadable
            return f"unreadable shard {f.name} ({exc})"
        for key, bshape, bdtype in headers:
            try:
                i, starts = _parse_block_key(key)
            except ValueError:
                return f"{f.name}: malformed block key {key!r}"
            if not 0 <= i < len(leaves):
                return (
                    f"world mismatch: {f.name} block {key!r} references "
                    f"leaf {i} but the manifest records {len(leaves)} "
                    f"leaves — shards from a different world?"
                )
            block_id = (i, tuple(starts))
            if block_id in seen:
                return (
                    f"world mismatch: duplicate block {key!r} across shard "
                    f"files ({f.name}) — shards from a different world "
                    f"mixed in?"
                )
            seen.add(block_id)
            shape = tuple(leaves[i]["shape"])
            if len(bshape) != len(shape) or len(starts) != len(shape) or any(
                st + bs > dim for st, bs, dim in zip(starts, bshape, shape)
            ):
                return (
                    f"world mismatch: {f.name} block {key!r} shape {bshape} "
                    f"at offset {tuple(starts)} does not fit the manifest's "
                    f"global shape {shape} for leaf {paths[i]} — shards "
                    f"from a different world?"
                )
            import numpy as np

            if np.dtype(bdtype) != np.dtype(leaves[i]["dtype"]):
                return (
                    f"world mismatch: {f.name} block {key!r} dtype "
                    f"{np.dtype(bdtype)} != manifest dtype "
                    f"{leaves[i]['dtype']} for leaf {paths[i]}"
                )
            n = 1
            for d in bshape:
                n *= int(d)
            covered[i] += n
    for i, got in enumerate(covered):
        want = 1
        for d in leaves[i]["shape"]:
            want *= int(d)
        if got != want:
            return (
                f"world mismatch: leaf {paths[i]} has {got}/{want} elements "
                f"across the manifest's {manifest.get('nprocs')} shard "
                f"files — shards from a different world?"
            )
    return None


def verify_checkpoint(path: str | os.PathLike) -> tuple[bool, str]:
    """Integrity check of either format. Returns (ok, detail).

    Consolidated: the file's sha256 must match its sidecar; a missing
    sidecar is accepted as "unverified legacy" (pre-round-9 checkpoints
    remain restorable) but a PRESENT, mismatching one fails. Sharded: the
    manifest must exist/parse, every shard file of the manifest's world
    must exist, (when the manifest records `checksums`) each shard file's
    sha256 must match, AND the shards' block geometry must tile exactly
    the per-leaf global shapes the manifest records (round 13: the
    checksums prove each file is intact, the geometry check proves the
    set of files belongs to THIS manifest's world — a stale shard from a
    save at a different world size fails here with a named detail).

    Never raises on I/O: a candidate can VANISH mid-verification (a
    lagging rank's `latest_good` scan races process 0's quarantine
    renames during a collective rollback), and the warn-and-skip contract
    demands (False, detail) — not an unclassified crash that strands the
    other ranks in the rollback collectives.
    """
    path = Path(path)
    if path.is_dir():
        try:
            manifest = json.loads((path / "manifest.json").read_text())
        except (OSError, ValueError) as exc:
            return False, f"missing/unreadable manifest ({exc})"
        shard_files = [
            path / f"shard-{pid:05d}.npz" for pid in range(manifest.get("nprocs", 0))
        ]
        missing = [f.name for f in shard_files if not f.exists()]
        if missing:
            return False, f"missing shard files {missing}"
        checksums = manifest.get("checksums")
        if checksums is not None:
            for f in shard_files:
                want = checksums.get(f.name)
                if want is None:
                    return False, f"manifest has no checksum for {f.name}"
                try:
                    got = _sha256_file(f)
                except OSError as exc:
                    return False, f"unreadable shard {f.name} ({exc})"
                if got != want:
                    return False, f"checksum mismatch in {f.name}"
        geo = _check_shard_geometry(shard_files, manifest)
        if geo is not None:
            return False, geo
        if checksums is None:
            return True, "unverified (manifest has no checksums; legacy)"
        return True, "verified"
    if not path.exists():
        return False, "missing file"
    side = checksum_sidecar(path)
    if not side.exists():
        return True, "unverified (no checksum sidecar; legacy)"
    try:
        want = side.read_text().strip()
    except OSError as exc:
        return False, f"unreadable checksum sidecar ({exc})"
    try:
        got = _sha256_file(path)
    except OSError as exc:
        return False, f"unreadable checkpoint ({exc})"
    if got != want:
        return False, "checksum mismatch"
    return True, "verified"


def _warn_skip(path: Path, detail: str) -> None:
    warnings.warn(
        f"skipping corrupt checkpoint {path}: {detail} — resuming from the "
        f"next older good one instead",
        stacklevel=3,
    )


def _write_blob(path: Path, blob: bytes) -> None:
    """The retried unit of a consolidated write: atomic tmp+rename. The
    chaos hook sits INSIDE so an injected transient IOError exercises the
    real retry, not a wrapper around it."""
    chaos_lib.maybe_io_fault("ckpt_write")
    fsio.atomic_write_bytes(path, blob)  # no torn checkpoints on crash


def _read_blob(path: Path) -> bytes:
    chaos_lib.maybe_io_fault("ckpt_read")
    return Path(path).read_bytes()


def save(
    state,
    directory: str | os.PathLike = "checkpoints",
    name: str | None = None,
    meta: dict | None = None,
) -> Path | None:
    """Consolidate + write the train state. Returns the path (process 0) or
    None (other processes). Safe to call from all processes — the gather is
    collective, the write is process-0-only."""
    host_state = jax.device_get(state)  # gathers sharded leaves
    sync_global_devices("checkpoint_gathered")
    if not is_process_zero():
        return None
    directory = Path(directory).resolve()
    directory.mkdir(parents=True, exist_ok=True)
    name = name or (step_name(state) + ".msgpack")
    if not name.endswith(".msgpack"):
        name += ".msgpack"
    path = directory / name
    blob = serialization.to_bytes(host_state)
    retry_io(_write_blob, path, blob, label="ckpt_write")
    _publish_sidecars(path, _sha256_bytes(blob), meta)
    return path


_VOCAB_PAD_HINT = (
    "If the mismatched dimension is the vocab axis (e.g. 50257 vs 50304), "
    "the checkpoint was written under a different GPTConfig.vocab_pad_multiple "
    "— recreate the model with the checkpoint's padding (vocab_pad_multiple=1 "
    "for an unpadded checkpoint, 128 for the default-padded one)."
)


def _adapt_layer_axis(path_str: str, arr, want: tuple):
    """Cross-strategy restore of identity-padded pipeline checkpoints: an
    uneven-layer Pipeline pads the stacked-layer axis to a stage multiple
    with all-zero identity layers, real layers packed at the front
    (tpukit/pipeline.py prepare_params). Restoring such a checkpoint into an
    unpadded template slices the padding off; restoring an unpadded
    checkpoint into a padded template appends zero slots. Returns the
    adapted array, or None when the mismatch is not a layer-axis pad."""
    import numpy as np

    if "layers" not in path_str:
        return None
    arr = np.asarray(arr)
    if arr.ndim == 0 or len(want) != arr.ndim or tuple(arr.shape[1:]) != tuple(want[1:]):
        return None
    saved, target = arr.shape[0], want[0]
    if saved > target:
        if np.any(arr[target:] != 0):
            # not identity padding (e.g. a genuinely deeper model): refuse
            # to silently drop trained layers
            return None
        return np.ascontiguousarray(arr[:target])
    return np.concatenate(
        [arr, np.zeros((target - saved, *arr.shape[1:]), arr.dtype)], axis=0
    )


def restore(template, path: str | os.PathLike):
    """Restore into the structure of `template` (a freshly-initialized train
    state). The caller re-applies the strategy's shardings by passing the
    result through the jitted step (or `jax.device_put` with the state
    sharding). Leaf shapes are validated against the template — flax's
    from_bytes silently accepts mismatched array shapes in plain pytrees,
    which would surface later as an opaque jit/sharding error."""
    blob = retry_io(_read_blob, Path(path), label="ckpt_read")
    try:
        restored = serialization.from_bytes(template, blob)
    except ValueError as exc:
        if "shape" in str(exc).lower():
            raise ValueError(f"{exc}\n{_VOCAB_PAD_HINT}") from exc
        raise
    return _validate_restored(path, template, restored)


def _validate_restored(path, template, restored):
    """Leaf-shape validation shared by `restore` and `restore_params`:
    flax's from_bytes/from_state_dict silently accept mismatched array
    shapes in plain pytrees, which would surface later as an opaque
    jit/sharding error — check every leaf against the template, adapting
    identity-padded pipeline layer axes (`_adapt_layer_axis`)."""
    t_flat = jax.tree_util.tree_flatten_with_path(template)[0]
    r_leaves, r_def = jax.tree_util.tree_flatten(restored)
    out, changed = [], False
    for (keypath, t_leaf), r_leaf in zip(t_flat, r_leaves):
        want = tuple(getattr(t_leaf, "shape", ()) or ())
        got = tuple(getattr(r_leaf, "shape", ()) or ())
        if want != got:
            name = "/".join(str(k) for k in keypath)
            adapted = _adapt_layer_axis(name, r_leaf, want)
            if adapted is None:
                raise ValueError(
                    f"checkpoint {path}: leaf {name} was saved with shape "
                    f"{got} but the target expects {want}. {_VOCAB_PAD_HINT}"
                )
            r_leaf, changed = adapted, True
        out.append(r_leaf)
    return jax.tree_util.tree_unflatten(r_def, out) if changed else restored


def latest(directory: str | os.PathLike = "checkpoints", verify: bool = True) -> Path | None:
    """Newest consolidated checkpoint that passes integrity verification
    (corrupt ones are skipped with a warning; `verify=False` restores the
    raw newest-by-step behavior)."""
    directory = Path(directory)
    if not directory.is_dir():
        return None
    candidates = sorted(
        directory.glob("checkpoint-*.msgpack"), key=lambda p: (_step_of(p), p.name)
    )
    for path in reversed(candidates):
        if not verify:
            return path
        ok, detail = verify_checkpoint(path)
        if ok:
            return path
        _warn_skip(path, detail)
    return None


# ---------------------------------------------------------------------------
# Format auto-selection (VERDICT r2 #1): `fit()` must never take the
# consolidated path for state it cannot gather. On a pod, FSDP/pipeline
# leaves span hosts — `jax.device_get` on a non-addressable, non-replicated
# array raises — so those states route to the sharded format. Single-host
# (any sharding: all devices addressable) and multi-host fully-replicated
# (every host holds a full copy, the reference's own save regime,
# main-fsdp.py:193-200) stay consolidated for parity.
# ---------------------------------------------------------------------------


def needs_sharded(state) -> bool:
    """True iff consolidated `save` would fail: some leaf spans processes
    without being fully replicated."""
    for leaf in jax.tree_util.tree_leaves(state):
        addressable = getattr(leaf, "is_fully_addressable", True)
        replicated = getattr(leaf, "is_fully_replicated", False)
        if not addressable and not replicated:
            return True
    return False


def save_auto(
    state,
    directory: str | os.PathLike = "checkpoints",
    name: str | None = None,
    format: str = "auto",
    meta: dict | None = None,
) -> Path | None:
    """Write `state` in the right format. `format`: "auto" (sharded exactly
    when consolidation is impossible), "consolidated", or "sharded".
    Returns the checkpoint path (all processes for sharded; process 0 only
    for consolidated)."""
    if format == "auto":
        format = "sharded" if needs_sharded(state) else "consolidated"
    if format == "sharded":
        return save_sharded(state, directory, name, meta=meta)
    if format == "consolidated":
        return save(state, directory, name, meta=meta)
    raise ValueError(f"format must be auto|consolidated|sharded, got {format!r}")


def latest_any(
    directory: str | os.PathLike = "checkpoints", verify: bool = True
) -> Path | None:
    """The newest (integrity-verified) checkpoint of either format, by
    training step."""
    candidates = [
        p
        for p in (latest(directory, verify), latest_sharded(directory, verify))
        if p
    ]
    if not candidates:
        return None
    return max(candidates, key=lambda p: (_step_of(p), p.name))


def all_checkpoints(directory: str | os.PathLike = "checkpoints") -> list[Path]:
    """Every published checkpoint of either format, ascending by step
    (no integrity filtering — callers verify what they restore)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    out = list(directory.glob("checkpoint-*.msgpack"))
    out += [
        p for p in directory.glob("*.sharded")
        if p.is_dir() and (p / "manifest.json").exists()
    ]
    return sorted(out, key=lambda p: (_step_of(p), p.name))


def latest_good(
    directory: str | os.PathLike = "checkpoints", max_step: int | None = None
) -> Path | None:
    """The newest integrity-verified checkpoint with step <= `max_step`
    (the rollback target: "last good" means verified AND strictly older
    than the anomaly's detection window). Corrupt candidates are skipped
    with a warning."""
    for path in reversed(all_checkpoints(directory)):
        if max_step is not None and _step_of(path) > max_step:
            continue
        ok, detail = verify_checkpoint(path)
        if ok:
            return path
        _warn_skip(path, detail)
    return None


def prune_checkpoints(
    directory: str | os.PathLike = "checkpoints", keep: int = 1,
    assume_newest_verified: bool = False,
) -> list[str]:
    """Retention (round 13, `--keep_checkpoints K`): delete published
    checkpoints older than the newest `keep`, so long elastic runs don't
    exhaust disk. Two classes of checkpoint are never pruned:

      - quarantined timelines: `RecoveryEngine.quarantine` renames suspect
        checkpoints to `*.quarantined-NNNN`, which no published glob (and
        therefore `all_checkpoints` here) matches — they are forensic
        evidence, retention never touches them;
      - the `latest_good` candidate: when none of the kept (newest)
        checkpoints passes integrity verification, the newest VERIFIED one
        outside the keep window must survive — it is the only state a
        rollback or `--resume latest` could still trust.

    Returns the deleted checkpoint names. Process-0 only on shared
    filesystems (one unlink/rmtree per checkpoint, like the publish).
    Deletion failures are skipped, not fatal — a prune miss costs disk,
    never correctness.

    `assume_newest_verified=True` skips re-verifying the kept set: the
    trainer prunes right after ITS OWN publish, whose writer computed the
    checksums from the in-memory bytes moments earlier — re-hashing a
    multi-GB checkpoint on the training thread every save interval would
    roughly double per-save disk I/O to defend against same-second
    bitrot. Standalone callers (a janitor over a foreign directory) keep
    the full verification."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    candidates = all_checkpoints(directory)
    doomed, kept = candidates[:-keep], candidates[-keep:]
    if not doomed:
        return []
    # `latest_good` protection without hashing the whole directory: if any
    # KEPT checkpoint verifies (newest-first, usually the first try), the
    # doomed set holds nothing a resume/rollback would still resolve to;
    # otherwise spare the newest verified doomed one.
    if not assume_newest_verified and not any(
        verify_checkpoint(p)[0] for p in reversed(kept)
    ):
        for p in reversed(doomed):
            if verify_checkpoint(p)[0]:
                doomed = [d for d in doomed if d != p]
                break
    removed = []
    for path in doomed:
        try:
            if path.is_dir():
                import shutil

                shutil.rmtree(path)
            else:
                path.unlink()
                checksum_sidecar(path).unlink(missing_ok=True)
                meta_path(path).unlink(missing_ok=True)
        except OSError:
            continue
        removed.append(path.name)
    return removed


def restore_any(path: str | os.PathLike, template, sharding_tree=None):
    """Restore either format: a `*.sharded` directory goes through
    `restore_sharded` (shards placed straight into `sharding_tree`); a
    msgpack file is read into host arrays shaped like `template` (the caller
    places them). `template` may be ShapeDtypeStructs — only its tree
    structure is read."""
    path = Path(path)
    if path.is_dir():
        return restore_sharded(path, template, sharding_tree), True
    return restore(template, path), False


_PARAMS_PREFIX = ".params"  # TrainState's params subtree in _leaf_paths form


def restore_params(path: str | os.PathLike, params_template, sharding_tree=None):
    """Params-ONLY restore of a TrainState checkpoint (round 15): serving
    cold start never steps, so reading the Adam moments — ~2/3 of every
    checkpoint's bytes — is pure waste. `params_template` is the params
    subtree only (shapes or ShapeDtypeStructs); `sharding_tree` (its
    matching sharding pytree) places leaves directly at the serving
    shardings. Returns `(params, info)` with an I/O ledger in `info`.

    Sharded checkpoints get the real 3x win: the manifest's leaf paths
    name the `.params` subtree, and `_ShardReader.block_headers()` plans
    which blocks to read from npy HEADERS alone — opt_state blocks are
    never touched, and `info["bytes_skipped"]` records exactly what the
    full restore would have read. Because leaves are assembled whole and
    placed at the TARGET shardings, a checkpoint saved under any world
    (device count, strategy) restores here without the reshard pass — a
    world mismatch is pure data movement for params-only reads.
    Consolidated msgpacks are one blob, so the file is still read once,
    but only the params subtree is decoded into the template/devices —
    the TrainState template, optimizer construction, and the 3x transient
    host/device memory all drop out."""
    path = Path(path)
    if path.is_dir():
        return _restore_params_sharded(path, params_template, sharding_tree)
    blob = retry_io(_read_blob, path, label="ckpt_read")
    raw = serialization.msgpack_restore(blob)
    if not isinstance(raw, dict) or "params" not in raw:
        raise ValueError(
            f"checkpoint {path} has no 'params' subtree — not a TrainState "
            f"checkpoint (top-level keys: {sorted(raw)[:8] if isinstance(raw, dict) else type(raw).__name__})"
        )
    restored = serialization.from_state_dict(params_template, raw["params"])
    restored = _validate_restored(path, params_template, restored)
    if sharding_tree is not None:
        restored = jax.tree_util.tree_map(jax.device_put, restored, sharding_tree)
    n_params = len(jax.tree_util.tree_leaves(params_template))
    info = {
        "format": "consolidated",
        "bytes_read": len(blob),
        "bytes_skipped": 0,
        "leaves_read": n_params,
        "leaves_skipped": len(jax.tree_util.tree_leaves(raw)) - n_params,
    }
    return restored, info


def _restore_params_sharded(base: Path, params_template, sharding_tree):
    """Sharded half of `restore_params`: filter the manifest to the
    `.params` leaves (the subtree flattens in the same relative order as
    the full state, so saved indices zip with the template's leaves),
    plan block reads from headers only, and place each assembled leaf at
    its target sharding."""
    import numpy as np

    manifest, shard_files = _read_shard_manifest(base)
    wanted = [
        i for i, p in enumerate(manifest["paths"]) if p.startswith(_PARAMS_PREFIX)
    ]
    flat, treedef = jax.tree_util.tree_flatten(params_template)
    if not wanted:
        raise ValueError(
            f"checkpoint {base} has no '{_PARAMS_PREFIX}' leaves — not a "
            f"TrainState checkpoint"
        )
    if len(flat) != len(wanted):
        raise ValueError(
            f"checkpoint {base}: {len(wanted)} saved params leaves don't "
            f"match the template's {len(flat)} — the model flags "
            f"(--dim/--heads/--num_layers/--num_experts...) must equal the "
            f"training run's"
        )
    shardings = _sharding_leaves(flat, sharding_tree)
    readers = [_ShardReader(f) for f in shard_files]
    wanted_set = set(wanted)
    bytes_read = bytes_skipped = 0
    plan: list[tuple] = []  # (reader, {saved leaf idx: [block keys]})
    for ar in readers:
        by_leaf: dict[int, list[str]] = {}
        for key, (shape, dtype) in ar.block_headers().items():
            idx, _ = _parse_block_key(key)
            nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize if shape else np.dtype(dtype).itemsize
            if idx in wanted_set:
                by_leaf.setdefault(idx, []).append(key)
                bytes_read += nbytes
            else:
                bytes_skipped += nbytes
        plan.append((ar, by_leaf))
    restored = []
    for saved_idx, leaf, sharding in zip(wanted, flat, shardings):
        meta = manifest["leaves"][saved_idx]
        shape, dtype = tuple(meta["shape"]), np.dtype(meta["dtype"])
        want = tuple(getattr(leaf, "shape", shape))
        full = np.empty(shape, dtype)
        covered = 0  # blocks are disjoint by construction (replica_id==0)
        for ar, by_leaf in plan:
            for key in by_leaf.get(saved_idx, ()):
                block = ar.read(key)
                _, starts = _parse_block_key(key)
                if starts:
                    idx = tuple(
                        slice(st, st + bs) for st, bs in zip(starts, block.shape)
                    )
                    full[idx] = block
                else:
                    full[()] = block
                covered += int(block.size) if block.shape else 1
        expected = int(np.prod(shape)) if shape else 1
        if covered != expected:
            raise ValueError(
                f"checkpoint {base}: params leaf "
                f"({manifest['paths'][saved_idx]}) has {covered}/{expected} "
                f"elements — a shard-*.npz file is missing (saved from "
                f"{manifest['nprocs']} processes; are all shard files on "
                f"this filesystem?)"
            )
        if want != shape:
            adapted = _adapt_layer_axis(manifest["paths"][saved_idx], full, want)
            if adapted is None:
                raise ValueError(
                    f"checkpoint {base}: params leaf "
                    f"({manifest['paths'][saved_idx]}) was saved with shape "
                    f"{shape} but the target expects {want}. {_VOCAB_PAD_HINT}"
                )
            full, shape = adapted, want
        if sharding is not None:
            restored.append(
                jax.make_array_from_callback(shape, sharding, lambda i, f=full: f[i])
            )
        else:
            restored.append(_as_jax_array(full))
    for ar in readers:
        ar.close()  # error paths are fatal; GC closes leaked handles
    info = {
        "format": "sharded",
        "bytes_read": bytes_read,
        "bytes_skipped": bytes_skipped,
        "leaves_read": len(wanted),
        "leaves_skipped": len(manifest["paths"]) - len(wanted),
    }
    return jax.tree_util.tree_unflatten(treedef, restored), info


# ---------------------------------------------------------------------------
# Sharded checkpoints (VERDICT r1 #7).
#
# The consolidated msgpack above gathers the whole state to one host —
# fine for GPT-20M, impossible for the GPT-XL pipe x ddp ladder config on a
# pod. The sharded format writes, per process, only the shards that process
# addressably owns (deduplicated by replica_id), so no host ever
# materializes the full state and hosts write in parallel:
#
#   <name>.sharded/
#     manifest.json    # leaf paths, global shapes/dtypes (process 0)
#     shard-<pid>.npz  # "<leaf-idx>|<start,start,...>" -> local block
#
# Restore rebuilds each leaf through `jax.make_array_from_callback` with the
# *target* sharding, so a checkpoint written under one strategy restores
# into any other strategy's shardings (FSDP -> TP, pipe -> single, ...).
# ---------------------------------------------------------------------------


def _leaf_paths(tree) -> list[str]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(str(k) for k in path) for path, _ in flat]


def _shard_blocks(state, copy: bool = False):
    """D2H snapshot of every replica-0 shard block this process addressably
    owns: `{"<leaf-idx>|<starts>": ndarray}` plus the manifest dict. This is
    the part of a sharded save that must read device memory — it runs on the
    TRAINING thread; the returned host blocks are what a background writer
    publishes. `copy=True` forces materialized copies: on CPU backends
    `np.asarray` of a device buffer can be a zero-copy VIEW, and the async
    writer's blocks must survive the next donated train step reusing those
    buffers."""
    import numpy as np

    leaves = [_as_jax_array(l) for l in jax.tree_util.tree_leaves(state)]
    blocks = {}
    for i, arr in enumerate(leaves):
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one process writes each block
            starts = [s.start or 0 for s in shard.index] if shard.index else []
            key = f"{i}|{','.join(map(str, starts))}"
            blocks[key] = (
                np.array(shard.data) if copy else np.asarray(shard.data)
            )
    manifest = {
        "nprocs": jax.process_count(),
        "paths": _leaf_paths(state),
        "leaves": [
            {"shape": list(l.shape), "dtype": str(l.dtype)} for l in leaves
        ],
    }
    return blocks, manifest


def _write_shard(final: Path, blocks) -> None:
    """The retried unit of one shard write: savez to a `.part` then atomic
    rename, so a shard file never exists half-written under its final
    name. Chaos hook inside (the retry must cover the injected fault)."""
    import numpy as np

    chaos_lib.maybe_io_fault("ckpt_write")
    part = final.with_suffix(final.suffix + ".part")
    with open(part, "wb") as f:
        np.savez(f, **blocks)
    os.replace(part, final)  # lint: allow(atomic-publish): binary shard archive, _atomic_write_text is text-only


def _write_shard_digest(shard: Path) -> None:
    """Each rank hashes the shard it JUST wrote (bytes still in page
    cache — a local re-read, not a network one) and publishes a tiny
    digest sidecar, so process 0's manifest never has to pull every
    host's full shard back over the shared filesystem inside the save's
    critical section."""
    chaos_lib.maybe_io_fault("ckpt_write")
    _atomic_write_text(
        shard.with_name(shard.name + ".sha256"), _sha256_file(shard)
    )


def _finalize_manifest(tmp: Path, manifest: dict, meta: dict | None) -> None:
    """Process-0 tail of a sharded save, once every shard file exists:
    fold each rank's published shard digest into the manifest (the
    integrity contract `verify_checkpoint` checks at restore/rollback
    time), then write the manifest and the optional resume metadata."""

    def _digest(f: Path) -> str:
        side = f.with_name(f.name + ".sha256")

        def read() -> str:
            chaos_lib.maybe_io_fault("ckpt_read")
            return side.read_text().strip()

        try:
            return retry_io(read, label="ckpt_read")
        except OSError:
            return _sha256_file(f)  # sidecar lost: hash the shard itself

    manifest = dict(manifest)
    manifest["checksums"] = {
        f.name: _digest(f) for f in sorted(tmp.glob("shard-*.npz"))
    }

    def write() -> None:
        chaos_lib.maybe_io_fault("ckpt_write")
        _atomic_write_text(tmp / "manifest.json", json.dumps(manifest))

    retry_io(write, label="ckpt_write")
    if meta is not None:
        _atomic_write_text(tmp / "resume.json", json.dumps(meta))


def save_sharded(
    state,
    directory: str | os.PathLike = "checkpoints",
    name: str | None = None,
    meta: dict | None = None,
) -> Path:
    """Write a sharded checkpoint. Every process participates; returns the
    checkpoint directory. Atomic publish: everything is written into a
    `.tmp` directory that process 0 renames only after all processes have
    finished their shard files — a crash mid-save leaves no directory that
    `latest_sharded`/`restore_sharded` would pick up.

    Multi-host runs require `directory` on a SHARED filesystem: restore
    needs every process's shard file, and the atomic publish is a single
    process-0 rename (the same contract as torch.distributed checkpoint
    dirs). On host-local paths each host would publish only its own shards.
    """
    # Deterministic name (ADVICE r2): derived from the replicated step, never
    # per-process wall clock — all processes must agree on the directory.
    base = Path(directory).resolve() / ((name or step_name(state)) + ".sharded")
    tmp = base.with_name(base.name + ".tmp")
    # A crashed save at the same step leaves a stale tmp dir (names are
    # deterministic per step); its leftover shard files would otherwise be
    # published alongside the fresh ones and corrupt the restore. Process 0
    # clears it before anyone writes.
    if is_process_zero() and tmp.exists():
        import shutil

        shutil.rmtree(tmp)
    sync_global_devices("sharded_ckpt_tmp_cleared")
    # Every process mkdirs (exist_ok): on a shared filesystem this is
    # idempotent, and it removes the process-0-wins race where a slow mkdir
    # let other processes' np.savez fail on a missing directory.
    tmp.mkdir(parents=True, exist_ok=True)
    sync_global_devices("sharded_ckpt_mkdir")

    blocks, manifest = _shard_blocks(state)
    shard = tmp / f"shard-{jax.process_index():05d}.npz"
    retry_io(_write_shard, shard, blocks, label="ckpt_write")
    retry_io(_write_shard_digest, shard, label="ckpt_write")

    # barrier BEFORE the manifest: its checksums fold every process's
    # shard digest, so all shard+digest writes must be durable first
    sync_global_devices("sharded_ckpt_written")
    if is_process_zero():
        _finalize_manifest(tmp, manifest, meta)
        if not base.exists():
            tmp.rename(base)  # lint: allow(atomic-publish): DIRECTORY publish — the sharded checkpoint dir swaps in whole, a text helper cannot
        elif name is None:
            # Step-keyed re-save (the final save right after a periodic one
            # at the same step): within one run the state at a given step is
            # deterministic, so the published directory already holds these
            # bytes — keep it rather than opening a window with no valid
            # checkpoint (a directory swap cannot be atomic; pod preemption
            # mid-swap would destroy the previously durable checkpoint).
            # Warn in case the directory is a leftover from a DIFFERENT run
            # (same step, different config) — that stale state would win.
            import shutil
            import warnings

            warnings.warn(
                f"sharded checkpoint {base} already exists; keeping the "
                f"published directory (same-step re-save). If this is a "
                f"fresh run reusing an old checkpoints dir, clear it first "
                f"— --resume latest would restore the OLD run's state.",
                stacklevel=2,
            )
            shutil.rmtree(tmp)
            if meta is not None:
                # The kept directory holds the same state bytes, but the
                # caller's resume metadata (a preemption's epoch/batch
                # position) must still land — dropping it would turn a
                # mid-epoch resume into a restart of the epoch.
                _atomic_write_text(base / "resume.json", json.dumps(meta))
        else:
            # Explicitly named re-save: the caller is deliberately reusing a
            # name with (possibly) new contents — swap the fresh data in.
            # Not crash-atomic (directories cannot be rename-replaced), but
            # this path is never taken by the train loop.
            import shutil

            trash = base.with_name(base.name + ".old")
            if trash.exists():
                shutil.rmtree(trash)
            base.rename(trash)  # lint: allow(atomic-publish): directory swap, see above
            tmp.rename(base)  # lint: allow(atomic-publish): directory swap, see above
            shutil.rmtree(trash)
    sync_global_devices("sharded_ckpt_published")
    return base


def _as_jax_array(x) -> jax.Array:
    import jax.numpy as jnp

    return x if isinstance(x, jax.Array) else jnp.asarray(x)


class _ShardReader:
    """One lazy NpzFile handle per shard file (zip metadata only — an eager
    whole-shard read would hold the entire checkpoint in host RAM on every
    process), with every deferred block read wrapped in retry_io: a failed
    read drops the handle so the retry reopens from a clean zip state
    instead of a poisoned stream position. Shared by `restore_sharded` and
    the round-13 elastic reshard pass (tpukit/reshard.py), which
    additionally uses `block_headers()` to plan which blocks intersect a
    target shard BEFORE reading any array data."""

    def __init__(self, f):
        self.f = f
        self._npz = None
        self._files = None
        self._headers = None

    def _open(self):
        chaos_lib.maybe_io_fault("ckpt_read")
        if self._npz is None:
            import numpy as np

            self._npz = np.load(self.f)
        return self._npz

    def close(self):
        if self._npz is not None:
            try:
                self._npz.close()
            except Exception:
                pass
            self._npz = None

    def files(self):
        if self._files is None:

            def _list():
                try:
                    return list(self._open().files)
                except OSError:
                    self.close()
                    raise

            self._files = retry_io(_list, label="ckpt_read")
        return self._files

    def block_headers(self) -> dict:
        """{key: (shape, dtype)} from the npy headers — no array data is
        read, so planning a reshard over a multi-GB shard costs KBs."""
        if self._headers is None:

            def _read():
                chaos_lib.maybe_io_fault("ckpt_read")
                return {
                    key: (shape, dtype)
                    for key, shape, dtype in _npz_block_headers(self.f)
                }

            self._headers = retry_io(_read, label="ckpt_read")
        return self._headers

    def read(self, key):
        def _read():
            try:
                return self._open()[key]
            except OSError:
                self.close()
                raise

        return retry_io(_read, label="ckpt_read")


def restore_sharded(path: str | os.PathLike, template, sharding_tree=None):
    """Restore a sharded checkpoint into the structure of `template`,
    placing each leaf with `sharding_tree` (defaults to the template
    leaves' own shardings). The target shardings need not match the ones
    the checkpoint was written under, and identity-padded stacked-layer
    axes (uneven pipeline layouts) are sliced/zero-padded to the template's
    layer count (_adapt_layer_axis) — so pipe -> single restores work even
    for uneven layer counts."""
    import numpy as np

    base = Path(path)
    manifest, shard_files = _read_shard_manifest(base)
    flat, treedef = jax.tree_util.tree_flatten(template)
    shardings = _sharding_leaves(flat, sharding_tree)
    if len(flat) != len(manifest["leaves"]):
        raise ValueError(
            f"template has {len(flat)} leaves, checkpoint has "
            f"{len(manifest['leaves'])} ({base})"
        )

    readers = [_ShardReader(f) for f in shard_files]
    restored = []
    for i, (leaf, meta, sharding) in enumerate(zip(flat, manifest["leaves"], shardings)):
        shape, dtype = tuple(meta["shape"]), np.dtype(meta["dtype"])
        want = tuple(getattr(leaf, "shape", shape))
        full = np.empty(shape, dtype)
        covered = 0  # blocks are disjoint by construction (replica_id==0)
        prefix = f"{i}|"
        for ar in readers:
            for key in ar.files():
                if not key.startswith(prefix):
                    continue
                block = ar.read(key)
                _, starts = _parse_block_key(key)
                if starts:
                    idx = tuple(
                        slice(st, st + bs) for st, bs in zip(starts, block.shape)
                    )
                    full[idx] = block
                else:
                    full[()] = block
                covered += int(block.size) if block.shape else 1
        expected = int(np.prod(shape)) if shape else 1
        if covered != expected:
            raise ValueError(
                f"checkpoint {base}: leaf {i} ({manifest['paths'][i]}) has "
                f"{covered}/{expected} elements — a shard-*.npz file is "
                f"missing (saved from {manifest['nprocs']} processes; are "
                f"all shard files on this filesystem?)"
            )
        if want != shape:
            adapted = _adapt_layer_axis(manifest["paths"][i], full, want)
            if adapted is None:
                raise ValueError(
                    f"checkpoint {base}: leaf {i} ({manifest['paths'][i]}) "
                    f"was saved with shape {shape} but the target expects "
                    f"{want}. {_VOCAB_PAD_HINT}"
                )
            full, shape = adapted, want
        if sharding is not None:
            restored.append(
                jax.make_array_from_callback(shape, sharding, lambda idx, f=full: f[idx])
            )
        else:
            restored.append(_as_jax_array(full))
    for ar in readers:
        ar.close()  # error paths are fatal; GC closes leaked handles
    return jax.tree_util.tree_unflatten(treedef, restored)


# ---------------------------------------------------------------------------
# Async (non-blocking) checkpointing — round-7 host overlap.
#
# The sync writers above charge the WHOLE save — device->host gather, msgpack
# encode / npz write, publish — to the training loop, visible as the
# `checkpoint` span in the goodput breakdown. The async writer splits a save
# at the only boundary that must see live device state: the snapshot (D2H
# reads + host copies) stays on the training thread, everything after is
# pure host I/O on a background thread that overlaps subsequent steps.
#
# The background half must NOT issue device collectives (sync_global_devices
# is one): a collective enqueued off the training thread can interleave
# differently with training collectives on different processes and deadlock
# the pod. The sharded format's cross-process rendezvous is therefore
# FILE-based here — each process renames its shard into the staging dir
# atomically, and process 0 publishes only once all `nprocs` shard files
# exist. SIGKILL at any instant still leaves only the previous published
# checkpoint or the new one, never a torn directory (the atomic tmp+rename
# contract of the sync writers, exercised by the kill-midrun harness in
# tests/test_multiprocess.py).
# ---------------------------------------------------------------------------


def _write_consolidated_blob(host_state, path: Path, meta: dict | None = None) -> None:
    """Background half of an async consolidated save: encode + atomic write
    of an already-snapshotted host pytree (same retry + integrity-sidecar
    contract as the sync writer). Pure host work."""
    path.parent.mkdir(parents=True, exist_ok=True)
    blob = serialization.to_bytes(host_state)
    retry_io(_write_blob, path, blob, label="ckpt_write")
    _publish_sidecars(path, _sha256_bytes(blob), meta)


def _publish_sharded_snapshot(
    blocks, manifest, base: Path, timeout: float = 600.0,
    meta: dict | None = None,
) -> None:
    """Background half of an async sharded save: write this process's shard
    atomically, then (process 0) wait for every process's shard file and
    publish the directory. All rendezvous is via the shared filesystem — no
    device collectives off the training thread.

    Same-step re-save (`base` already published): the state at a given step
    is deterministic within a run, so the published directory already holds
    these bytes — skip, exactly like the sync writer's keep-the-published-
    directory policy.

    Stale `.tmp` staging dirs (a crashed prior save at the same step) need
    no rmtree here, unlike the sync writer: a shard file only ever appears
    under its final name via the atomic `.part` rename, so a stale
    `shard-*.npz` is always a COMPLETE write from the crashed attempt —
    and a crash-then-resume of the same run reproduces the same state at
    the same step, so publishing stale-alongside-fresh shards publishes
    identical bytes. The remaining hazard is the one the sync writer also
    only warns about: reusing an old checkpoints dir across runs with
    DIFFERENT config/data, where a same-step stale shard could win — fresh
    runs must start with a clean checkpoints dir."""
    if base.exists():
        # Same-step re-save: already durable (see docstring) — but the
        # caller's resume metadata (a preemption's epoch/batch position)
        # must still land in the kept directory.
        if meta is not None and is_process_zero():
            _atomic_write_text(base / "resume.json", json.dumps(meta))
        return
    tmp = base.with_name(base.name + ".tmp")
    tmp.mkdir(parents=True, exist_ok=True)
    pid = jax.process_index()
    shard = tmp / f"shard-{pid:05d}.npz"
    retry_io(_write_shard, shard, blocks, label="ckpt_write")
    retry_io(_write_shard_digest, shard, label="ckpt_write")
    deadline = time.monotonic() + timeout
    if not is_process_zero():
        # Publish barrier for every process: wait() on ANY host must mean
        # "the checkpoint directory exists" — otherwise a non-zero host
        # could return from fit() (or report an abort checkpoint path) and
        # read `latest` while process 0 is still publishing, resuming a
        # step behind the rest of the pod.
        while not base.exists():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"async sharded checkpoint {base}: timed out after "
                    f"{timeout}s waiting for process 0 to publish"
                )
            time.sleep(0.05)
        return
    expected = [
        tmp / name
        for p in range(manifest["nprocs"])
        for name in (f"shard-{p:05d}.npz", f"shard-{p:05d}.npz.sha256")
    ]
    while True:
        missing = [str(p.name) for p in expected if not p.exists()]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"async sharded checkpoint {base}: timed out after {timeout}s "
                f"waiting for shard files {missing} (is the checkpoint "
                f"directory on a filesystem shared by all hosts?)"
            )
        time.sleep(0.05)
    _finalize_manifest(tmp, manifest, meta)
    if not base.exists():
        tmp.rename(base)  # lint: allow(atomic-publish): DIRECTORY publish — the sharded checkpoint dir swaps in whole, a text helper cannot


class AsyncCheckpointer:
    """Non-blocking checkpoint writer.

    `save_auto` SNAPSHOTS on the calling (training) thread — the D2H reads
    and buffer copies, the only part that must run before the next donated
    train step reuses the state's device buffers — then hands the encode/
    write/publish to a background thread and returns the path the write
    will publish. A join barrier at the next save (and `wait()`, which fit
    calls at exit and before abort-saves) keeps AT MOST ONE write in
    flight and re-raises any background failure on the training thread, so
    an async save error is never silently lost.

    Durability is the sync writers' contract: atomic tmp+rename publish in
    both formats — SIGKILL at any instant leaves the previous checkpoint or
    the new one, never a torn file.
    """

    def __init__(self, shard_timeout: float = 600.0):
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._shard_timeout = shard_timeout

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait(self) -> None:
        """Join barrier: block until the in-flight write (if any) has
        published, then re-raise its failure (if any)."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint write failed") from err

    def save_auto(
        self,
        state,
        directory: str | os.PathLike = "checkpoints",
        name: str | None = None,
        format: str = "auto",
        meta: dict | None = None,
    ) -> Path | None:
        """Async twin of module-level `save_auto` (same routing, same return
        convention). Blocks only for the previous write's join barrier plus
        the snapshot; call `wait()` when durability must be certain."""
        import numpy as np

        self.wait()
        if format == "auto":
            format = "sharded" if needs_sharded(state) else "consolidated"
        if format == "consolidated":
            # Consolidated implies host-gatherable state (fully replicated
            # in the multi-host case), so device_get is process-local and
            # non-zero hosts can skip the whole snapshot — unlike the sync
            # writer there is no collective barrier here to participate in.
            if not is_process_zero():
                return None
            # np.array (copy) on top of device_get: on CPU backends the
            # gather can return zero-copy views of buffers the next donated
            # train step will overwrite.
            host_state = jax.tree.map(np.array, jax.device_get(state))
            nm = name or (step_name(state) + ".msgpack")
            if not nm.endswith(".msgpack"):
                nm += ".msgpack"
            path = Path(directory).resolve() / nm
            work = functools.partial(
                _write_consolidated_blob, host_state, path, meta
            )
        elif format == "sharded":
            blocks, manifest = _shard_blocks(state, copy=True)
            path = Path(directory).resolve() / (
                (name or step_name(state)) + ".sharded"
            )
            work = functools.partial(
                _publish_sharded_snapshot, blocks, manifest, path,
                self._shard_timeout, meta,
            )
        else:
            raise ValueError(
                f"format must be auto|consolidated|sharded, got {format!r}"
            )

        def run():
            try:
                work()
            except BaseException as exc:  # noqa: BLE001 — re-raised at wait()
                self._error = exc

        self._thread = threading.Thread(
            target=run, daemon=True, name="tpukit-async-ckpt"
        )
        self._thread.start()
        return path


def latest_sharded(
    directory: str | os.PathLike = "checkpoints", verify: bool = True
) -> Path | None:
    """Newest sharded checkpoint that passes integrity verification —
    a directory missing its manifest (a torn publish) is invisible here by
    construction; one with a checksum-mismatching or missing shard file is
    skipped with a warning."""
    directory = Path(directory)
    if not directory.is_dir():
        return None
    candidates = sorted(
        (
            p for p in directory.glob("*.sharded")
            if p.is_dir() and (p / "manifest.json").exists()
        ),
        key=lambda p: (_step_of(p), p.name),
    )
    for path in reversed(candidates):
        if not verify:
            return path
        ok, detail = verify_checkpoint(path)
        if ok:
            return path
        _warn_skip(path, detail)
    return None
