"""Checkpoint save/restore.

The reference checkpoints once, at end of training, write-only, to a
timestamped `checkpoints/checkpoint-<YYYY-mm-dd_HH-MM-SS>.pt` (reference
main-single.py:146-151); there is **no resume path anywhere** (SURVEY §2.8).
tpukit twins the save surface (same directory/naming scheme, process-0-only
in distributed recipes like main-ddp.py:179-185 / main-fsdp.py:193-200) and
adds what the reference lacks: restore, periodic step-keyed saves, and
optimizer-state capture so a restore actually resumes training.

Format: msgpack of the full train-state pytree (params + opt state + step)
via flax.serialization. Sharded states are gathered to host before writing —
the twin of FSDP's full `state_dict()` gather-then-rank-0-save
(main-fsdp.py:194-200): the on-disk artifact is always consolidated
(unsharded), so any strategy can restore any other strategy's checkpoint.
"""

from __future__ import annotations

import datetime
import os
from pathlib import Path

import jax
from flax import serialization

from tpukit.mesh import is_process_zero, sync_global_devices


def _timestamp_name() -> str:
    return "checkpoint-" + datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S") + ".msgpack"


def save(state, directory: str | os.PathLike = "checkpoints", name: str | None = None) -> Path | None:
    """Consolidate + write the train state. Returns the path (process 0) or
    None (other processes). Safe to call from all processes — the gather is
    collective, the write is process-0-only."""
    host_state = jax.device_get(state)  # gathers sharded leaves
    sync_global_devices("checkpoint_gathered")
    if not is_process_zero():
        return None
    directory = Path(directory).resolve()
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / (name or _timestamp_name())
    blob = serialization.to_bytes(host_state)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(blob)
    tmp.rename(path)  # atomic publish: no torn checkpoints on crash
    return path


def restore(template, path: str | os.PathLike):
    """Restore into the structure of `template` (a freshly-initialized train
    state). The caller re-applies the strategy's shardings by passing the
    result through the jitted step (or `jax.device_put` with the state
    sharding)."""
    blob = Path(path).read_bytes()
    return serialization.from_bytes(template, blob)


def latest(directory: str | os.PathLike = "checkpoints") -> Path | None:
    directory = Path(directory)
    if not directory.is_dir():
        return None
    candidates = sorted(directory.glob("checkpoint-*.msgpack"))
    return candidates[-1] if candidates else None
