"""Deterministic fault injection: the chaos harness behind `--chaos_spec`.

Rounds 6-8 built detection (sentinels, watchdog, divergence checksums) and
round 9 builds recovery (rollback, preemption, retry) — but a recovery
path that only executes when production actually fails is untested code on
the critical path. This module closes that gap: every failure class the
detectors know is injectable AT AN EXACT STEP, seeded and replayable, so
the detect→recover loop runs end to end in CI on a healthy host.

Spec grammar (documented in docs/DESIGN.md "recovery"):

    --chaos_spec "nan_loss@120,sigterm@300,ckpt_io_fail@2,hang@450:2.5"

    spec   := entry ("," entry)*
    entry  := kind "@" int (":" float)?     # the float is kind-specific

step-indexed kinds (`@N` = fires when training step N completes):
    nan_loss@N        poison the host-observed loss with NaN (the state is
                      untouched — the detector/recovery path is the target)
    spike_loss@N[:m]  multiply the observed loss by m (default 1e3)
    sigterm@N         raise SIGTERM in-process (the preemption path)
    sigint@N          raise SIGINT in-process
    hang@N[:s]        sleep s seconds (default 1.0) inside the armed
                      iteration — trips the hang watchdog
    bitflip@N[:p]     flip one mantissa bit of the first parameter leaf on
                      process p (default: the last process) — a divergent
                      replica for the checksum detector
    resize@N:M        elastic world resize (round 13): force a graceful
                      preempt-save at step N (in-process SIGTERM, exit 75)
                      whose resume metadata records the TARGET world M —
                      the relaunch at M devices must RESHARD the
                      checkpoint (tpukit/reshard.py) and fit() raises if
                      it comes back at any other world, so a resize chaos
                      run asserts the elastic path instead of hoping
    skip@N            consume (discard) the first N batches of the first
                      trained epoch before training starts — the stream
                      fast-forward primitive, exposed so a control run can
                      reproduce a rollback's post-recovery stream exactly

occurrence-indexed kinds (`@K` = the K-th I/O operation of that site
fails; `:c` = fail c consecutive attempts, default 1 — c <= --io_retries
is recovered by the backoff wrapper, c > fails loud):
    ckpt_io_fail@K[:c]     checkpoint write path (sync + async writers)
    ckpt_read_fail@K[:c]   checkpoint read path (restore)
    loader_io_fail@K[:c]   DataLoader batch fetch

fleet-scoped kinds (rounds 19 + 24, tpukit/serve/fleet.py + ledger.py —
the serving router's failure model, indexed by fleet DISPATCH ROUND
(or supervisor poll round in `--fleet_procs` mode), not training step;
legal only in `FleetConfig.kill_spec` / `--fleet_kill`, validated by
`validate_fleet_spec`, consumed by `ServingChaos`, and rejected by the
training ChaosEngine with a named error so a misplaced entry fails at
startup):
    replica_kill@R[:idx]    at dispatch round R, drop replica idx
                            (default: the highest live id) — its
                            in-flight requests re-queue onto the
                            surviving replicas (simulated, in-process)
    replica_sigkill@R[:idx] same targeting, but REAL process death:
                            SIGKILL the replica worker process (only
                            meaningful under `--fleet_procs`; the
                            in-process router treats it as replica_kill
                            and says so in the fired event)
    slow_replica@R:ms       at round R the target's HEARTBEAT stalls for
                            ms milliseconds without the replica dying —
                            the straggler/dead discrimination case:
                            ms < --replica_timeout must NOT kill it
    stuck_request@N         request rid N never reaches EOS (its lane is
                            pinned host-side past natural retirement) —
                            exercises deadline_ms eviction; the device
                            program is untouched
    ledger_io_fail@K[:c]    the K-th ledger file operation fails c
                            consecutive attempts (occurrence-indexed,
                            same semantics as ckpt_io_fail; c <=
                            --io_retries is absorbed by retry_io)

Injection sites call the module-level hooks (`maybe_io_fault`), which are
a single `is None` test when no harness is installed — chaos off costs
one predictable branch per I/O call and NOTHING in the compiled step (all
injection is host-side; the train-step HLO is byte-identical with the
flag on or off, asserted in tests/test_recovery.py).
"""

from __future__ import annotations

import re
import signal
import threading
import time

STEP_KINDS = (
    "nan_loss", "spike_loss", "sigterm", "sigint", "hang", "bitflip", "resize",
)
IO_KINDS = ("ckpt_io_fail", "ckpt_read_fail", "loader_io_fail")
# fleet-scoped kinds (rounds 19 + 24): parsed by the shared grammar,
# consumed by ServingChaos (serve/fleet.FleetRouter + serve/ledger),
# REJECTED by the training ChaosEngine below
FLEET_KINDS = (
    "replica_kill", "replica_sigkill", "slow_replica", "stuck_request",
    "ledger_io_fail",
)
# the fleet kinds whose `@R` is a dispatch round and whose optional param
# is a replica id (shared targeting grammar)
_REPLICA_TARGET_KINDS = ("replica_kill", "replica_sigkill")
# io-site label (as used by the checkpoint/loader call sites) per kind
_IO_SITE = {
    "ckpt_io_fail": "ckpt_write",
    "ckpt_read_fail": "ckpt_read",
    "loader_io_fail": "loader_fetch",
}

_ENTRY_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<at>\d+)(?::(?P<param>[0-9.eE+-]+))?$"
)


class ChaosSpecError(ValueError):
    pass


def parse_spec(spec: str) -> list[dict]:
    """Parse the `--chaos_spec` grammar into a list of
    {kind, at, param} dicts. Raises ChaosSpecError with the offending
    entry named — a typo'd fault plan must fail at startup, not silently
    never fire."""
    entries = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _ENTRY_RE.match(raw)
        if not m:
            raise ChaosSpecError(
                f"chaos spec entry {raw!r} does not match kind@step[:param]"
            )
        kind = m.group("kind")
        known = STEP_KINDS + IO_KINDS + FLEET_KINDS + ("skip",)
        if kind not in known:
            raise ChaosSpecError(
                f"chaos spec entry {raw!r}: unknown kind {kind!r} "
                f"(known: {', '.join(known)})"
            )
        param = m.group("param")
        entry = {
            "kind": kind,
            "at": int(m.group("at")),
            "param": float(param) if param is not None else None,
        }
        # Param sanity is part of the fail-at-startup contract: a plan
        # that parses but then crashes mid-run (time.sleep(-2)) or
        # silently never fires (I/O occurrence 0, failure count 0) is the
        # exact failure mode this parser exists to prevent.
        if kind == "hang" and entry["param"] is not None and entry["param"] < 0:
            raise ChaosSpecError(
                f"chaos spec entry {raw!r}: hang duration must be >= 0"
            )
        if kind == "spike_loss" and entry["param"] is not None and entry["param"] <= 0:
            raise ChaosSpecError(
                f"chaos spec entry {raw!r}: spike multiplier must be > 0"
            )
        if kind == "resize":
            p = entry["param"]
            if p is None or p != int(p) or int(p) < 1:
                raise ChaosSpecError(
                    f"chaos spec entry {raw!r}: resize needs an integer "
                    f"target world size >= 1 (resize@N:M)"
                )
        if kind in _REPLICA_TARGET_KINDS and entry["param"] is not None:
            p = entry["param"]
            if p != int(p) or int(p) < 0:
                raise ChaosSpecError(
                    f"chaos spec entry {raw!r}: {kind}'s optional "
                    f"target must be an integer replica id >= 0"
                )
        if kind == "slow_replica":
            p = entry["param"]
            if p is None or p <= 0:
                raise ChaosSpecError(
                    f"chaos spec entry {raw!r}: slow_replica needs a stall "
                    f"duration in ms > 0 (slow_replica@R:ms)"
                )
        if kind == "stuck_request" and entry["param"] is not None:
            raise ChaosSpecError(
                f"chaos spec entry {raw!r}: stuck_request takes no param "
                f"(stuck_request@RID pins request RID past EOS)"
            )
        if kind in IO_KINDS or kind == "ledger_io_fail":
            if entry["at"] < 1:
                raise ChaosSpecError(
                    f"chaos spec entry {raw!r}: I/O occurrences are 1-based "
                    f"(@0 would never fire)"
                )
            if entry["param"] is not None and int(entry["param"]) < 1:
                raise ChaosSpecError(
                    f"chaos spec entry {raw!r}: failure count must be >= 1 "
                    f"(0 would never fire)"
                )
        entries.append(entry)
    return entries


class ChaosEngine:
    """One run's fault plan. Deterministic and replayable: the same spec
    (plus seed, for any future randomized kinds) fires the same faults at
    the same steps/occurrences on every run.

    The trainer calls `on_step` after each completed training step; the
    I/O sites call `io_fault(site)` from inside their retried operation.
    Each fired fault is recorded in `fired` (and returned to the caller)
    so the run's JSONL carries a `kind="chaos"` audit trail.
    """

    def __init__(self, spec: str, seed: int = 0, process_index: int = 0,
                 process_count: int = 1):
        self.spec = spec
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self._lock = threading.Lock()
        self.fired: list[dict] = []
        self._step_faults: dict[int, list[dict]] = {}
        # per-site: {occurrence_index: remaining_failures}
        self._io_plan: dict[str, dict[int, int]] = {s: {} for s in _IO_SITE.values()}
        self._io_seen: dict[str, int] = {s: 0 for s in _IO_SITE.values()}
        self.skip_batches = 0
        # resize@N:M — set when the fault FIRES (the preempt-save's resume
        # metadata records it as `resize_to`, what the relaunch asserts)
        self.resize_target: int | None = None
        for e in parse_spec(spec):
            if e["kind"] in FLEET_KINDS:
                # a fleet fault in a training spec would silently never
                # fire (the trainer has no dispatch rounds) — the exact
                # failure mode the fail-at-startup contract forbids
                raise ChaosSpecError(
                    f"chaos spec {e['kind']}@{e['at']}: fleet-scoped faults "
                    f"belong to the serving router — pass them via "
                    f"--fleet_kill / FleetConfig.kill_spec, not --chaos_spec"
                )
            if e["kind"] == "bitflip" and e["param"] is not None and not (
                0 <= int(e["param"]) < process_count
            ):
                # a target outside the world would silently never flip —
                # the CI divergence test would then test nothing
                raise ChaosSpecError(
                    f"chaos spec bitflip@{e['at']}:{int(e['param'])}: target "
                    f"process out of range for world size {process_count}"
                )
            if e["kind"] == "skip":
                self.skip_batches = max(self.skip_batches, e["at"])
            elif e["kind"] in IO_KINDS:
                site = _IO_SITE[e["kind"]]
                count = int(e["param"]) if e["param"] is not None else 1
                self._io_plan[site][e["at"]] = count
            else:
                self._step_faults.setdefault(e["at"], []).append(e)

    # -- step-indexed faults (training thread) -----------------------------

    def mutates_state_at(self, step: int) -> bool:
        """True when a fault scheduled at `step` will device_put into the
        state (bitflip). The trainer brackets that `on_step` call with a
        prefetcher quiesce — the same two-threads-never-place rule the
        rollback restore follows (prefetch.HostPrefetcher.quiesce)."""
        return any(
            f["kind"] == "bitflip" for f in self._step_faults.get(step, ())
        )

    def on_step(self, step: int, state, loss):
        """Apply any fault scheduled for `step`. Returns
        (state, loss, fired_events); state/loss are unchanged unless a
        fault targets them."""
        faults = self._step_faults.pop(step, None)
        if not faults:
            return state, loss, []
        events = []
        for f in faults:
            kind, param = f["kind"], f["param"]
            ev = {"fault": kind, "step": step}
            if kind == "nan_loss":
                loss = self._poison_loss(loss, float("nan"))
            elif kind == "spike_loss":
                loss = self._poison_loss(loss, None, mult=param or 1e3)
                ev["mult"] = param or 1e3
            elif kind == "sigterm":
                signal.raise_signal(signal.SIGTERM)
            elif kind == "resize":
                # same graceful-preemption machinery as sigterm@N; the
                # target world rides the preempt checkpoint's resume
                # metadata so the relaunch can ASSERT it resharded to M
                self.resize_target = int(param)
                ev["to"] = int(param)
                signal.raise_signal(signal.SIGTERM)
            elif kind == "sigint":
                signal.raise_signal(signal.SIGINT)
            elif kind == "hang":
                dur = param if param is not None else 1.0
                ev["sleep_s"] = dur
                time.sleep(dur)
            elif kind == "bitflip":
                target = (
                    int(param) if param is not None else self.process_count - 1
                )
                ev["process"] = target
                if target == self.process_index:
                    state = self._flip_bit(state)
                    ev["flipped"] = True
            events.append(ev)
        with self._lock:
            self.fired.extend(events)
        return state, loss, events

    @staticmethod
    def _poison_loss(loss, value, mult=None):
        import jax.numpy as jnp

        if mult is not None:
            return loss * jnp.asarray(mult, dtype=loss.dtype)
        return jnp.full_like(loss, value)

    @staticmethod
    def _flip_bit(state):
        """Flip one low mantissa bit of the first parameter leaf — the
        minimal divergence the XOR checksum must catch. Placement (device
        + sharding) is preserved so the perturbed state re-enters the
        donated step unchanged in layout. Cross-host-sharded leaves (not
        fully addressable — device_get would raise) are perturbed through
        their first LOCAL shard and reassembled in place."""
        import jax
        import numpy as np

        def _flip_first(arr):
            flat = np.array(arr, copy=True).reshape(-1)
            bits = flat[:1].view(
                {2: np.uint16, 4: np.uint32, 8: np.uint64}[arr.dtype.itemsize]
            )
            bits[0] ^= 1
            return flat.reshape(arr.shape)

        leaves, treedef = jax.tree_util.tree_flatten(state)
        for i, leaf in enumerate(leaves):
            dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
            if dtype.kind != "f" or getattr(leaf, "size", 0) == 0:
                continue
            if (
                isinstance(leaf, jax.Array)
                and not leaf.is_fully_addressable
            ):
                shards = leaf.addressable_shards
                if not shards or shards[0].data.size == 0:
                    continue
                bufs = [
                    jax.device_put(
                        _flip_first(np.asarray(s.data)) if j == 0
                        else np.asarray(s.data),
                        s.device,
                    )
                    for j, s in enumerate(shards)
                ]
                leaves[i] = jax.make_array_from_single_device_arrays(
                    leaf.shape, leaf.sharding, bufs
                )
                break
            arr = np.asarray(jax.device_get(leaf))
            flipped = _flip_first(arr)
            sharding = getattr(leaf, "sharding", None)
            leaves[i] = (
                jax.device_put(flipped, sharding)
                if sharding is not None
                else jax.numpy.asarray(flipped)
            )
            break
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- occurrence-indexed I/O faults (any thread) ------------------------

    def io_fault(self, site: str) -> None:
        """Called from inside a retried I/O operation; raises IOError when
        this occurrence (1-based, per site) is scheduled to fail. A
        scheduled count of c fails the first c ATTEMPTS of that occurrence
        (retries re-enter here without advancing the occurrence index)."""
        with self._lock:
            plan = self._io_plan.get(site)
            if plan is None:
                return
            seen = self._io_seen[site] + 1
            remaining = plan.get(seen)
            if remaining is not None and remaining > 0:
                plan[seen] = remaining - 1
                self.fired.append(
                    {"fault": f"{site}_io", "occurrence": seen,
                     "remaining": remaining - 1}
                )
                raise IOError(
                    f"chaos: injected transient {site} failure "
                    f"(occurrence {seen})"
                )
            # the occurrence completed (or was never scheduled): advance
            self._io_seen[site] = seen

    def drain_fired(self) -> list[dict]:
        """Events fired since the last drain (the trainer logs these as
        kind=\"chaos\" JSONL records)."""
        with self._lock:
            out, self.fired = self.fired, []
        return out


def validate_fleet_spec(spec: str) -> list[dict]:
    """Parse + validate a `FleetConfig.kill_spec` / `--fleet_kill` plan.

    The ONE grammar/validation path for fleet fault plans (round 24 closed
    the bespoke check fleet.py used to carry): entries go through the same
    `parse_spec` as `--chaos_spec`, then any non-fleet kind is rejected
    with a named error — the mirror image of ChaosEngine rejecting
    fleet-scoped kinds."""
    entries = parse_spec(spec)
    for e in entries:
        if e["kind"] not in FLEET_KINDS:
            raise ChaosSpecError(
                f"fleet kill spec {e['kind']}@{e['at']}: only fleet-scoped "
                f"faults ({', '.join(FLEET_KINDS)}, e.g. replica_kill@R) "
                f"are legal in FleetConfig.kill_spec / --fleet_kill — "
                f"training faults go via --chaos_spec"
            )
    return entries


class ServingChaos:
    """One serving run's fleet fault plan (round 24) — the serving-side
    twin of ChaosEngine, consumed by serve/fleet.FleetRouter (in-process)
    and serve/ledger.ProcessFleet (real worker processes).

    Same determinism contract: round-indexed faults fire exactly once at
    their dispatch/poll round, occurrence-indexed ledger I/O faults fail
    the scheduled attempt counts and never re-fire. The router installs
    this via `install()` for the run's duration so the ledger's raw file
    helpers can reach `io_fault(\"ledger\")` through the same module hook
    the checkpoint sites use."""

    def __init__(self, spec: str):
        self.spec = spec
        self._lock = threading.Lock()
        self.fired: list[dict] = []
        # round -> [target replica id or None (= highest live)]
        self.kills: dict[int, list[int | None]] = {}
        self.sigkills: dict[int, list[int | None]] = {}
        # round -> [stall duration in seconds]
        self.stalls: dict[int, list[float]] = {}
        # request rids pinned past EOS (deadline eviction's quarry)
        self.stuck: set[int] = set()
        self._io_plan: dict[str, dict[int, int]] = {"ledger": {}}
        self._io_seen: dict[str, int] = {"ledger": 0}
        for e in validate_fleet_spec(spec):
            kind, at, param = e["kind"], e["at"], e["param"]
            if kind == "replica_kill":
                self.kills.setdefault(at, []).append(
                    int(param) if param is not None else None
                )
            elif kind == "replica_sigkill":
                self.sigkills.setdefault(at, []).append(
                    int(param) if param is not None else None
                )
            elif kind == "slow_replica":
                self.stalls.setdefault(at, []).append(float(param) / 1e3)
            elif kind == "stuck_request":
                self.stuck.add(at)
            elif kind == "ledger_io_fail":
                count = int(param) if param is not None else 1
                self._io_plan["ledger"][at] = count

    def io_fault(self, site: str) -> None:
        """Occurrence-indexed ledger I/O faults — identical semantics to
        ChaosEngine.io_fault (a scheduled count of c fails the first c
        ATTEMPTS of that occurrence; retries re-enter without advancing
        the index). Sites other than \"ledger\" are not this plan's and
        pass through untouched."""
        with self._lock:
            plan = self._io_plan.get(site)
            if plan is None:
                return
            seen = self._io_seen[site] + 1
            remaining = plan.get(seen)
            if remaining is not None and remaining > 0:
                plan[seen] = remaining - 1
                self.fired.append(
                    {"fault": f"{site}_io", "occurrence": seen,
                     "remaining": remaining - 1}
                )
                raise IOError(
                    f"chaos: injected transient {site} failure "
                    f"(occurrence {seen})"
                )
            self._io_seen[site] = seen

    def record(self, event: dict) -> None:
        """Router/supervisor-side fault firings (kills, stalls) land in the
        same audit trail as the I/O faults."""
        with self._lock:
            self.fired.append(dict(event))

    def drain_fired(self) -> list[dict]:
        with self._lock:
            out, self.fired = self.fired, []
        return out


# ---------------------------------------------------------------------------
# Module-level injection hooks. The I/O sites (checkpoint.py, loader.py,
# serve/ledger.py) call `maybe_io_fault(site)` unconditionally — a no-op
# unless a harness is installed (one None check). fit() installs the
# training engine for the run's duration; the fleet router installs its
# ServingChaos the same way; both uninstall on exit, so chaos never leaks
# across runs.
# ---------------------------------------------------------------------------

_ENGINE: ChaosEngine | ServingChaos | None = None


def install(
    engine: ChaosEngine | ServingChaos | None,
) -> ChaosEngine | ServingChaos | None:
    """Install (or clear, with None) the process-wide engine; returns the
    previous one so callers can restore it."""
    global _ENGINE
    prev, _ENGINE = _ENGINE, engine
    return prev


def installed() -> ChaosEngine | ServingChaos | None:
    return _ENGINE


def maybe_io_fault(site: str) -> None:
    eng = _ENGINE
    if eng is not None:
        eng.io_fault(site)
