"""Greedy (argmax) text generation.

Twin of `generate` (reference utils.py:42-91): greedy decoding, at most
`max_new_tokens` (default 20) new tokens, stop *before* appending when the
model emits EOS (utils.py:67-68), decode with special tokens skipped
(utils.py:91). The reference's prompt handling — tokenize with truncation to
max_length=256 (utils.py:57) — is kept.

TPU-native redesign of the loop itself: the reference re-forwards a *growing*
sequence each step via `torch.cat` (utils.py:63-87), which under jit would
recompile at every length. Here the sequence lives in a fixed
`[1, prompt + max_new_tokens]` buffer and the whole decode loop is a single
jitted `lax.while_loop`: one compile per prompt length, zero host round-trips
inside the loop. Because attention is causal and the model is called without
a padding mask (as in the reference, utils.py:64), the trailing unwritten
buffer positions cannot influence the logits at the current position, so the
fixed-buffer decode is token-for-token equivalent to the growing-buffer one.

Unlike the reference (which re-runs the full forward per token,
utils.py:63-64), decoding defaults to a KV-cached path: prefill the prompt
once, then one-token steps against per-layer K/V buffers. The naive loop is
kept (`use_cache=False`) and the two are equivalence-tested token-for-token.
Both loops support temperature/top-k sampling (round 11 — the cached loop
previously raised on temperature>0, VERDICT r5 #5): the per-position key
fold is identical in the two loops, so a fixed seed samples the same tokens
cached and uncached.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpukit.model import gpt


@partial(
    jax.jit,
    static_argnames=("cfg", "prompt_len", "max_new_tokens", "eos_id", "temperature", "top_k"),
)
def _decode_loop(
    params, cfg: gpt.GPTConfig, buf, prompt_len: int, max_new_tokens: int,
    eos_id: int, temperature: float = 0.0, top_k: int = 0, rng=None,
):
    """Returns (buf, final_length). buf: [1, prompt_len + max_new_tokens].

    temperature == 0 (default) is the reference's greedy argmax; > 0
    samples from softmax(logits / temperature), optionally truncated to
    the top_k candidates (beyond-parity — the reference decodes greedily
    only, utils.py:65). The step key folds the cursor into `rng`, so a
    fixed seed reproduces exactly."""
    total = buf.shape[1]
    position_ids = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), buf.shape)

    def cond(carry):
        _, cur, done = carry
        return jnp.logical_and(~done, cur < total)

    def body(carry):
        buf, cur, _ = carry
        logits = gpt.forward(params, cfg, buf, position_ids)
        last = logits[0, cur - 1].astype(jnp.float32)
        if temperature > 0.0:  # static branch: greedy decode trace unchanged
            scaled = last / temperature
            if top_k > 0:
                kth = jax.lax.top_k(scaled, top_k)[0][-1]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            next_token = jax.random.categorical(
                jax.random.fold_in(rng, cur), scaled
            ).astype(buf.dtype)
        else:
            next_token = jnp.argmax(last, axis=-1).astype(buf.dtype)
        done = next_token == eos_id
        # Only append when not EOS — the reference breaks before appending
        # (utils.py:67-68), so EOS never enters the sequence.
        new_buf = jnp.where(done, buf, buf.at[0, cur].set(next_token))
        new_cur = jnp.where(done, cur, cur + 1)
        return (new_buf, new_cur, done)

    buf, cur, _ = jax.lax.while_loop(cond, body, (buf, jnp.int32(prompt_len), jnp.bool_(False)))
    return buf, cur


@partial(
    jax.jit,
    static_argnames=("cfg", "prompt_len", "max_new_tokens", "eos_id", "temperature", "top_k"),
)
def _decode_loop_cached(
    params, cfg: gpt.GPTConfig, buf, prompt_len: int, max_new_tokens: int,
    eos_id: int, temperature: float = 0.0, top_k: int = 0, rng=None,
):
    """KV-cached twin of `_decode_loop`: the prompt is prefilled once, then
    each step forwards ONE token against the cache — O(S) attention per
    token instead of the naive loop's O(S^2) full re-forward (the
    reference's known wart, utils.py:63-64). Token-for-token equivalent to
    the naive loop (tests/test_sampling.py).

    temperature/top_k mirror `_decode_loop` exactly (round 11, the first
    rung of the serving ladder — VERDICT r5 #5 flagged the cached path
    raising on temperature>0): the SAME per-position key fold
    (`fold_in(rng, cur)`) and the same truncate-then-categorical math, so
    a fixed seed samples the same tokens cached and uncached — the
    same-seed equivalence tests/test_sampling.py asserts. The static
    temperature==0 branch keeps the greedy decode trace byte-unchanged."""
    total = buf.shape[1]
    cache = gpt.init_kv_cache(cfg, 1, total)
    if prompt_len > 1:
        ids = buf[:, : prompt_len - 1]
        pos = jnp.arange(prompt_len - 1, dtype=jnp.int32)[None, :]
        _, cache = gpt.forward_cached(params, cfg, ids, pos, cache, 0)

    def cond(carry):
        _, _, cur, done = carry
        return jnp.logical_and(~done, cur < total)

    def body(carry):
        buf, cache, cur, _ = carry
        tok = jax.lax.dynamic_slice(buf, (0, cur - 1), (1, 1))
        pos = jnp.reshape(cur - 1, (1, 1)).astype(jnp.int32)
        logits, cache = gpt.forward_cached(params, cfg, tok, pos, cache, cur - 1)
        last = logits[0, -1].astype(jnp.float32)
        if temperature > 0.0:  # static branch: greedy decode trace unchanged
            scaled = last / temperature
            if top_k > 0:
                kth = jax.lax.top_k(scaled, top_k)[0][-1]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            next_token = jax.random.categorical(
                jax.random.fold_in(rng, cur), scaled
            ).astype(buf.dtype)
        else:
            next_token = jnp.argmax(last, axis=-1).astype(buf.dtype)
        done = next_token == eos_id
        new_buf = jnp.where(done, buf, buf.at[0, cur].set(next_token))
        new_cur = jnp.where(done, cur, cur + 1)
        return (new_buf, cache, new_cur, done)

    buf, _, cur, _ = jax.lax.while_loop(
        cond, body, (buf, cache, jnp.int32(prompt_len), jnp.bool_(False))
    )
    return buf, cur


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "eos_id"))
def _decode_loop_batch(params, cfg: gpt.GPTConfig, buf, prompt_lens, max_new_tokens: int, eos_id: int):
    """Batched twin of `_decode_loop`: N prompts of (traced) per-row lengths
    decode in ONE jitted while_loop — one compile and one decode for the
    whole prompt set instead of a compile + serial decode per prompt
    (VERDICT r4 #7: the per-epoch qualitative eval stalls a pod N times
    otherwise). Rows carry independent cursors/EOS flags; causality makes
    each row's logits at `cur-1` depend only on its own written prefix, so
    the output is token-for-token the serial decode's
    (tests/test_sampling.py parity). Returns (buf [N, W], lengths [N])."""
    n, total = buf.shape
    position_ids = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), buf.shape)
    limits = jnp.minimum(prompt_lens + max_new_tokens, total)
    rows = jnp.arange(n)

    def cond(carry):
        _, cur, done = carry
        return jnp.any(~done & (cur < limits))

    def body(carry):
        buf, cur, done = carry
        logits = gpt.forward(params, cfg, buf, position_ids)
        read = jnp.clip(cur - 1, 0, total - 1)
        # gather the one [N, V] row set first, THEN cast — like the serial
        # loop; casting the whole [N, W, V] tensor would be W x the traffic
        last = jnp.take_along_axis(logits, read[:, None, None], axis=1)[
            :, 0
        ].astype(jnp.float32)
        next_token = jnp.argmax(last, axis=-1).astype(buf.dtype)
        active = ~done & (cur < limits)
        hit_eos = next_token == eos_id
        # stop BEFORE appending on EOS (reference utils.py:67-68)
        append = active & ~hit_eos
        write = jnp.clip(cur, 0, total - 1)
        kept = buf[rows, write]
        buf = buf.at[rows, write].set(jnp.where(append, next_token, kept))
        cur = jnp.where(append, cur + 1, cur)
        done = done | (active & hit_eos)
        return buf, cur, done

    buf, cur, _ = jax.lax.while_loop(
        cond, body,
        (buf, prompt_lens.astype(jnp.int32), jnp.zeros((n,), jnp.bool_)),
    )
    return buf, cur


def _replicate_like(params, buf):
    """Place the decode buffer replicated on the params' mesh. Plain
    `jnp.asarray` would commit it to a single device, which is invalid for
    a multi-host SPMD decode (every process must hold the same global,
    fully-addressable-per-host value)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from tpukit.mesh import place_host_array

    sh = next(
        (
            leaf.sharding
            for leaf in jax.tree_util.tree_leaves(params)
            if isinstance(getattr(leaf, "sharding", None), NamedSharding)
        ),
        None,
    )
    if sh is None:
        return jnp.asarray(buf)
    return place_host_array(buf, NamedSharding(sh.mesh, PartitionSpec()))


def generate(
    params,
    cfg: gpt.GPTConfig,
    prompt: str,
    tokenizer,
    max_new_tokens: int = 20,
    use_cache: bool | None = None,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
) -> str:
    """Decode a continuation of `prompt`. Default is the reference's greedy
    argmax; `temperature > 0` switches to softmax sampling (optionally
    `top_k`-truncated), reproducible under `seed`. See module docstring."""
    # The reference truncates prompts at a hard 256 (utils.py:57). Also cap
    # at the position-embedding table so the whole buffer (prompt + new
    # tokens) stays in-range — beyond it, position lookups would silently
    # clamp to the last learned position instead of erroring.
    max_prompt = min(256, cfg.max_position_embeddings - max_new_tokens)
    if max_prompt < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} leaves no room for a prompt "
            f"within max_position_embeddings={cfg.max_position_embeddings}"
        )
    encoded = tokenizer([prompt], truncation=True, max_length=max_prompt)
    ids = np.asarray(encoded["input_ids"][0], dtype=np.int32)
    prompt_len = int(ids.shape[0])

    buf = np.zeros((1, prompt_len + max_new_tokens), dtype=np.int32)
    buf[0, :prompt_len] = ids

    eos = tokenizer.eos_token_id
    if use_cache is None:
        # Measured on v5e: the cached path wins on long buffers (O(S) vs
        # O(S^2) per token) but its per-step cache updates cost more than
        # the naive re-forward saves on short ones. MoE models default to
        # the exact full-reforward path: the cached decode routes each
        # chunk with its own expert-capacity window, which can diverge
        # from full-sequence routing (gpt._apply_moe_ffn docstring).
        use_cache = buf.shape[1] >= 512 and cfg.num_experts == 0
    if use_cache:
        # Round 11 (first rung of the serving ladder, ROADMAP #1): the
        # cached loop samples too — same key fold, same truncation math as
        # the naive loop, so a fixed seed decodes the same tokens either
        # way (the r5 #5 raise is gone; same-seed equivalence is tested).
        buf, length = _decode_loop_cached(
            params, cfg, _replicate_like(params, buf), prompt_len,
            max_new_tokens, int(eos), temperature=float(temperature),
            top_k=min(int(top_k), cfg.padded_vocab_size),
            rng=_replicate_like(params, np.asarray(jax.random.PRNGKey(seed)))
            if temperature > 0.0
            else None,
        )
    else:
        buf, length = _decode_loop(
            params, cfg, _replicate_like(params, buf), prompt_len,
            max_new_tokens, int(eos), temperature=float(temperature),
            # lax.top_k rejects k beyond the logits width — clamp
            top_k=min(int(top_k), cfg.padded_vocab_size),
            rng=_replicate_like(params, np.asarray(jax.random.PRNGKey(seed)))
            if temperature > 0.0
            else None,
        )
    out_ids = np.asarray(buf)[0, : int(length)]
    return tokenizer.decode(out_ids, skip_special_tokens=True)


def generate_batch(
    params,
    cfg: gpt.GPTConfig,
    prompts: list[str],
    tokenizer,
    max_new_tokens: int = 20,
) -> list[str]:
    """Greedy-decode continuations of every prompt in ONE jitted call.

    Prompts are right-padded into a common `[N, max_prompt + new]` buffer
    with per-row (traced) lengths, so any prompt set of the same max length
    reuses one compiled program. Output is token-for-token identical to
    `generate` called per prompt (tests/test_sampling.py)."""
    if not prompts:
        return []
    max_prompt = min(256, cfg.max_position_embeddings - max_new_tokens)
    if max_prompt < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} leaves no room for a prompt "
            f"within max_position_embeddings={cfg.max_position_embeddings}"
        )
    encoded = tokenizer(list(prompts), truncation=True, max_length=max_prompt)
    ids = [np.asarray(row, dtype=np.int32) for row in encoded["input_ids"]]
    lens = np.asarray([r.shape[0] for r in ids], dtype=np.int32)

    buf = np.zeros((len(ids), int(lens.max()) + max_new_tokens), dtype=np.int32)
    for r, row in enumerate(ids):
        buf[r, : row.shape[0]] = row

    buf, lengths = _decode_loop_batch(
        params, cfg, _replicate_like(params, buf),
        _replicate_like(params, lens), max_new_tokens,
        int(tokenizer.eos_token_id),
    )
    buf, lengths = np.asarray(buf), np.asarray(lengths)
    return [
        tokenizer.decode(buf[r, : int(lengths[r])], skip_special_tokens=True)
        for r in range(buf.shape[0])
    ]
