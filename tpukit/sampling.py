"""Greedy (argmax) text generation.

Twin of `generate` (reference utils.py:42-91): greedy decoding, at most
`max_new_tokens` (default 20) new tokens, stop *before* appending when the
model emits EOS (utils.py:67-68), decode with special tokens skipped
(utils.py:91). The reference's prompt handling — tokenize with truncation to
max_length=256 (utils.py:57) — is kept.

TPU-native redesign of the loop itself: the reference re-forwards a *growing*
sequence each step via `torch.cat` (utils.py:63-87), which under jit would
recompile at every length. Here the sequence lives in a fixed
`[1, prompt + max_new_tokens]` buffer and the whole decode loop is a single
jitted `lax.while_loop`: one compile per prompt length, zero host round-trips
inside the loop. Because attention is causal and the model is called without
a padding mask (as in the reference, utils.py:64), the trailing unwritten
buffer positions cannot influence the logits at the current position, so the
fixed-buffer decode is token-for-token equivalent to the growing-buffer one.

Unlike the reference (which re-runs the full forward per token,
utils.py:63-64), decoding defaults to a KV-cached path: prefill the prompt
once, then one-token steps against per-layer K/V buffers. The naive loop is
kept (`use_cache=False`) and the two are equivalence-tested token-for-token.
Both loops support temperature/top-k sampling (round 11 — the cached loop
previously raised on temperature>0, VERDICT r5 #5): the per-position key
fold is identical in the two loops, so a fixed seed samples the same tokens
cached and uncached.

Round 14: `generate_batch` rides the serving engine's batched KV-cached
decode (`tpukit/serve/decode.decode_loop` — per-row cursors over a
preallocated per-slot cache) instead of the retired `_decode_loop_batch`,
which re-forwarded the whole growing buffer per token: O(S) attention per
generated token now, same token-for-token parity with the serial decode,
plus temperature/top-k sampling per row.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpukit.model import gpt


@partial(
    jax.jit,
    static_argnames=("cfg", "prompt_len", "max_new_tokens", "eos_id", "temperature", "top_k"),
)
def _decode_loop(
    params, cfg: gpt.GPTConfig, buf, prompt_len: int, max_new_tokens: int,
    eos_id: int, temperature: float = 0.0, top_k: int = 0, rng=None,
):
    """Returns (buf, final_length). buf: [1, prompt_len + max_new_tokens].

    temperature == 0 (default) is the reference's greedy argmax; > 0
    samples from softmax(logits / temperature), optionally truncated to
    the top_k candidates (beyond-parity — the reference decodes greedily
    only, utils.py:65). The step key folds the cursor into `rng`, so a
    fixed seed reproduces exactly."""
    total = buf.shape[1]
    position_ids = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), buf.shape)

    def cond(carry):
        _, cur, done = carry
        return jnp.logical_and(~done, cur < total)

    def body(carry):
        buf, cur, _ = carry
        logits = gpt.forward(params, cfg, buf, position_ids)
        last = logits[0, cur - 1].astype(jnp.float32)
        next_token = _sample_next(last, cur, rng, temperature, top_k).astype(buf.dtype)
        done = next_token == eos_id
        # Only append when not EOS — the reference breaks before appending
        # (utils.py:67-68), so EOS never enters the sequence.
        new_buf = jnp.where(done, buf, buf.at[0, cur].set(next_token))
        new_cur = jnp.where(done, cur, cur + 1)
        return (new_buf, new_cur, done)

    buf, cur, _ = jax.lax.while_loop(cond, body, (buf, jnp.int32(prompt_len), jnp.bool_(False)))
    return buf, cur


@partial(
    jax.jit,
    static_argnames=("cfg", "prompt_len", "max_new_tokens", "eos_id", "temperature", "top_k"),
)
def _decode_loop_cached(
    params, cfg: gpt.GPTConfig, buf, prompt_len: int, max_new_tokens: int,
    eos_id: int, temperature: float = 0.0, top_k: int = 0, rng=None,
):
    """KV-cached twin of `_decode_loop`: the prompt is prefilled once, then
    each step forwards ONE token against the cache — O(S) attention per
    token instead of the naive loop's O(S^2) full re-forward (the
    reference's known wart, utils.py:63-64). Token-for-token equivalent to
    the naive loop (tests/test_sampling.py).

    temperature/top_k mirror `_decode_loop` exactly (round 11, the first
    rung of the serving ladder — VERDICT r5 #5 flagged the cached path
    raising on temperature>0): the SAME per-position key fold
    (`fold_in(rng, cur)`) and the same truncate-then-categorical math, so
    a fixed seed samples the same tokens cached and uncached — the
    same-seed equivalence tests/test_sampling.py asserts. The static
    temperature==0 branch keeps the greedy decode trace byte-unchanged."""
    total = buf.shape[1]
    cache = gpt.init_kv_cache(cfg, 1, total)
    if prompt_len > 1:
        ids = buf[:, : prompt_len - 1]
        pos = jnp.arange(prompt_len - 1, dtype=jnp.int32)[None, :]
        _, cache = gpt.forward_cached(params, cfg, ids, pos, cache, 0)

    def cond(carry):
        _, _, cur, done = carry
        return jnp.logical_and(~done, cur < total)

    def body(carry):
        buf, cache, cur, _ = carry
        tok = jax.lax.dynamic_slice(buf, (0, cur - 1), (1, 1))
        pos = jnp.reshape(cur - 1, (1, 1)).astype(jnp.int32)
        logits, cache = gpt.forward_cached(params, cfg, tok, pos, cache, cur - 1)
        last = logits[0, -1].astype(jnp.float32)
        next_token = _sample_next(last, cur, rng, temperature, top_k).astype(buf.dtype)
        done = next_token == eos_id
        new_buf = jnp.where(done, buf, buf.at[0, cur].set(next_token))
        new_cur = jnp.where(done, cur, cur + 1)
        return (new_buf, cache, new_cur, done)

    buf, _, cur, _ = jax.lax.while_loop(
        cond, body, (buf, cache, jnp.int32(prompt_len), jnp.bool_(False))
    )
    return buf, cur


def _adjust_logits(last, temperature: float, top_k: int):
    """The temperature/top-k logits transform every sampler draws from:
    scale by 1/temperature, then mask everything below the k-th largest
    to -inf. Factored out of `_sample_next` (round 17) so the speculative
    verify step (tpukit/serve/spec.py) builds its target distribution
    from the SAME math — the rejection-sampling correction is only exact
    against the distribution vanilla sampling actually draws from.
    `last` is `[..., V]` f32; only `temperature > 0` callers may use it."""
    scaled = last / temperature
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return scaled


def _sample_next(last, cur, rng, temperature: float = 0.0, top_k: int = 0):
    """THE sampling spelling — one token from one f32 logits vector
    `last [V]` at cursor `cur`: temperature == 0 is greedy argmax (static
    branch, `rng` untouched); > 0 scales, optionally top-k-truncates
    (`_adjust_logits`), and draws `categorical(fold_in(rng, cur), ...)`.
    Every decode loop — serial naive, serial cached, and the serving
    engine's batched step (which vmaps this over slots) — calls this ONE
    function, because the cached==uncached and batched==serial parity
    guarantees are exactly the bit-for-bit agreement of this math across
    loops."""
    if temperature > 0.0:  # static branch: greedy decode trace unchanged
        scaled = _adjust_logits(last, temperature, top_k)
        return jax.random.categorical(jax.random.fold_in(rng, cur), scaled)
    return jnp.argmax(last, axis=-1)


def _cached_decode_exact(cfg: gpt.GPTConfig) -> bool:
    """True when the KV-cached decode is token-for-token the full-reforward
    decode. Dense models always are (causality — module docstring). MoE
    models route each cached chunk with its own capacity window, so the
    buffer dispatches ("xla"/"a2a") can drop different tokens cached vs
    uncached — EXCEPT dropless "pallas" (no capacity override): per-token
    routing there is chunk-composition-independent and nothing is ever
    dropped, so cached decode is exact (round 14; equivalence tested in
    tests/test_serve.py, rationale at gpt._apply_moe_ffn)."""
    return cfg.num_experts == 0 or (
        cfg.moe_dispatch == "pallas" and cfg.moe_capacity == 0
    )


def _replicate_like(params, buf):
    """Place the decode buffer replicated on the params' mesh. Plain
    `jnp.asarray` would commit it to a single device, which is invalid for
    a multi-host SPMD decode (every process must hold the same global,
    fully-addressable-per-host value)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from tpukit.mesh import place_host_array

    sh = next(
        (
            leaf.sharding
            for leaf in jax.tree_util.tree_leaves(params)
            if isinstance(getattr(leaf, "sharding", None), NamedSharding)
        ),
        None,
    )
    if sh is None:
        return jnp.asarray(buf)
    return place_host_array(buf, NamedSharding(sh.mesh, PartitionSpec()))


def generate(
    params,
    cfg: gpt.GPTConfig,
    prompt: str,
    tokenizer,
    max_new_tokens: int = 20,
    use_cache: bool | None = None,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
) -> str:
    """Decode a continuation of `prompt`. Default is the reference's greedy
    argmax; `temperature > 0` switches to softmax sampling (optionally
    `top_k`-truncated), reproducible under `seed`. See module docstring."""
    # The reference truncates prompts at a hard 256 (utils.py:57). Also cap
    # at the position-embedding table so the whole buffer (prompt + new
    # tokens) stays in-range — beyond it, position lookups would silently
    # clamp to the last learned position instead of erroring.
    max_prompt = min(256, cfg.max_position_embeddings - max_new_tokens)
    if max_prompt < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} leaves no room for a prompt "
            f"within max_position_embeddings={cfg.max_position_embeddings}"
        )
    encoded = tokenizer([prompt], truncation=True, max_length=max_prompt)
    ids = np.asarray(encoded["input_ids"][0], dtype=np.int32)
    prompt_len = int(ids.shape[0])

    buf = np.zeros((1, prompt_len + max_new_tokens), dtype=np.int32)
    buf[0, :prompt_len] = ids

    eos = tokenizer.eos_token_id
    if use_cache is None:
        # Measured on v5e: the cached path wins on long buffers (O(S) vs
        # O(S^2) per token) but its per-step cache updates cost more than
        # the naive re-forward saves on short ones. MoE models with a
        # capacity'd buffer dispatch default to the exact full-reforward
        # path — the cached decode routes each chunk with its own
        # expert-capacity window (gpt._apply_moe_ffn docstring); dropless
        # "pallas" MoE is chunk-composition-independent, so its cached
        # decode is exact and auto-resolves like a dense model (round 14).
        use_cache = buf.shape[1] >= 512 and _cached_decode_exact(cfg)
    if use_cache:
        # Round 11 (first rung of the serving ladder, ROADMAP #1): the
        # cached loop samples too — same key fold, same truncation math as
        # the naive loop, so a fixed seed decodes the same tokens either
        # way (the r5 #5 raise is gone; same-seed equivalence is tested).
        buf, length = _decode_loop_cached(
            params, cfg, _replicate_like(params, buf), prompt_len,
            max_new_tokens, int(eos), temperature=float(temperature),
            top_k=min(int(top_k), cfg.padded_vocab_size),
            rng=_replicate_like(params, np.asarray(jax.random.PRNGKey(seed)))
            if temperature > 0.0
            else None,
        )
    else:
        buf, length = _decode_loop(
            params, cfg, _replicate_like(params, buf), prompt_len,
            max_new_tokens, int(eos), temperature=float(temperature),
            # lax.top_k rejects k beyond the logits width — clamp
            top_k=min(int(top_k), cfg.padded_vocab_size),
            rng=_replicate_like(params, np.asarray(jax.random.PRNGKey(seed)))
            if temperature > 0.0
            else None,
        )
    out_ids = np.asarray(buf)[0, : int(length)]
    return tokenizer.decode(out_ids, skip_special_tokens=True)


def generate_batch(
    params,
    cfg: gpt.GPTConfig,
    prompts: list[str],
    tokenizer,
    max_new_tokens: int = 20,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
) -> list[str]:
    """Decode continuations of every prompt in ONE jitted call — the
    KV-cached batched decode (`tpukit/serve/decode.decode_loop`, round 14):
    one full-width prefill, then one-token-per-row cached steps in a single
    `lax.while_loop`. This retired the round-4 `_decode_loop_batch`, which
    re-forwarded the whole growing buffer every token — O(S^2) attention
    per generated token vs O(S) here.

    Prompts are right-padded into a common `[N, max_prompt + new]` buffer
    with per-row (traced) lengths, so any prompt set of the same max length
    reuses one compiled program. Greedy output is token-for-token identical
    to `generate` called per prompt (tests/test_sampling.py), and
    `temperature`/`top_k`/`seed` sample per row with the same
    `fold_in(key, cursor)` fold as the serial loops — a fixed seed decodes
    each row exactly as `generate(..., seed=seed)` would. For MoE configs
    the batched decode equals the serial CACHED decode always; it equals
    the full-reforward decode exactly when `_cached_decode_exact(cfg)`
    (dense, or dropless-pallas MoE — gpt._apply_moe_ffn docstring)."""
    if not prompts:
        return []
    max_prompt = min(256, cfg.max_position_embeddings - max_new_tokens)
    if max_prompt < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} leaves no room for a prompt "
            f"within max_position_embeddings={cfg.max_position_embeddings}"
        )
    encoded = tokenizer(list(prompts), truncation=True, max_length=max_prompt)
    ids = [np.asarray(row, dtype=np.int32) for row in encoded["input_ids"]]
    lens = np.asarray([r.shape[0] for r in ids], dtype=np.int32)

    buf = np.zeros((len(ids), int(lens.max()) + max_new_tokens), dtype=np.int32)
    for r, row in enumerate(ids):
        buf[r, : row.shape[0]] = row

    from tpukit.serve.decode import decode_loop

    buf, lengths = decode_loop(
        params, cfg, _replicate_like(params, buf),
        _replicate_like(params, lens), max_new_tokens,
        int(tokenizer.eos_token_id), temperature=float(temperature),
        top_k=min(int(top_k), cfg.padded_vocab_size),
        rng=_replicate_like(params, np.asarray(jax.random.PRNGKey(seed)))
        if temperature > 0.0
        else None,
    )
    buf, lengths = np.asarray(buf), np.asarray(lengths)
    return [
        tokenizer.decode(buf[r, : int(lengths[r])], skip_special_tokens=True)
        for r in range(buf.shape[0])
    ]
