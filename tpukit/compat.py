"""JAX version-compat shims.

tpukit tracks current JAX API spellings; deployment images sometimes pin an
older jax (no new deps may be installed there — the repo must gate, not
require). Two surfaces moved between jax 0.4.x and newer releases:

  - `shard_map`: newer jax exports it as `jax.shard_map` and spells the
    replication-check kwarg `check_vma`; 0.4.x has it under
    `jax.experimental.shard_map` with the kwarg named `check_rep`.
  - `custom_partitioning.def_partition`: newer jax accepts a
    `sharding_rule` einsum-style hint (for the Shardy partitioner) next to
    `partition`/`infer_sharding_from_operands`; 0.4.x rejects the kwarg.
    Every tpukit kernel supplies the real partition/infer callbacks, so on
    old jax the hint is simply dropped.

Import `shard_map` and `def_partition` from here instead of jax directly.
"""

from __future__ import annotations

import functools
import inspect

try:  # newer jax
    from jax import shard_map as _shard_map
except ImportError:  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    if not _HAS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)


def def_partition(cp, **kwargs):
    """`cp.def_partition(**kwargs)`, dropping the `sharding_rule` hint on
    jax versions whose signature predates it."""
    try:
        return cp.def_partition(**kwargs)
    except TypeError:
        kwargs.pop("sharding_rule", None)
        return cp.def_partition(**kwargs)


def axis_size(axis_name) -> "int | object":
    """`jax.lax.axis_size` (newer jax) with the classic psum-of-ones
    fallback for versions that predate it. Only valid inside shard_map/pmap
    contexts, like the original."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
