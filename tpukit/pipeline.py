"""Pipeline parallelism: a GPipe schedule over a `stage` mesh axis.

TPU-native re-design of the reference's pipeline recipe (main-pipe.py, which
uses the deprecated torch `Pipe` over an `nn.Sequential` of per-GPU stages
with TensorPipe RPC, main-pipe.py:21-28,75-83). Here there is no RPC layer
and no wrapper modules: the decoder's stacked layer parameters are sharded
along their leading `num_layers` axis over the `stage` mesh axis, and a
`shard_map` runs the classic GPipe micro-batch schedule with
`jax.lax.ppermute` (XLA collective-permute over ICI) moving activations
stage-to-stage. Autodiff through `ppermute`/`scan` gives the pipelined
backward for free — the capability torch `Pipe` implements by hand.

Faithful structure (intent of main-pipe.py:52-83, which has syntax errors —
SURVEY §2.9 #3-5):
  - embeddings are applied on stage 0 and the norm+lm_head on the last stage
    (stage layout of main-pipe.py:53-55,67-68,75-77);
  - the padding mask (and here, the targets) are threaded through the
    pipeline alongside the activations — the twin of the `(x, mask)` tuple
    threading every reference stage performs (main-pipe.py:35-37,43-50);
  - the number of micro-batches defaults to the number of stages
    (`chunks=num_stages`, main-pipe.py:83,93).

Uneven layer counts (intent of main-pipe.py:63-68, VERDICT r2 #5): any
`num_layers >= 1` trains on any stage count. The stacked layer parameters
are padded to `ceil(L/S)*S` with all-zero identity layers (zero projections
make `x + attn(...) + ffn(...) == x` exactly), appended at the end so real
layers keep their order; the schedule gates padded slots off with a `where`
on the residual stream, so padded parameters receive zero gradient and the
loss matches the unpadded single-device model exactly. Padding happens at
init via `prepare_params` (wired through `create_train_state`); checkpoints
of an uneven config therefore carry the padded layer axis and restore into
layouts with the same padded count.

Memory placement (VERDICT r2 #3): the token embedding table and the lm_head
kernel shard their VOCAB dimension over the `stage` axis (and their Adam
state follows, via `state_sharding`), so no device holds a full table — the
reference's stage layout (embeddings on the first GPU, head on the last,
main-pipe.py:53-55,75-77) achieved as sharding rather than placement.
Compute stays role-specific: stage 0 ingests through a distributed lookup
(each stage contributes its vocab slice, one exact psum), the last stage's
activations feed a Megatron-style vocab-parallel head + CE
(`ops/layers.py vocab_parallel_ce`) in which every stage owns V/S logit
columns and no full-vocab tensor ever materializes. Falls back to
replicated embeddings/head when the padded vocab does not divide the stage
count (the default 128-multiple padding divides any power-of-two count).

Loss is computed as a (sum, count) pair and `psum`-broadcast, so the
returned loss equals the non-pipelined global mean exactly (twin of
main-pipe.py:162-165).

The same shard_map serves the 2-D pipeline x data hybrid (`main-pipe-ddp.py`,
a stub in the reference — SURVEY §2.4): with a `(data, stage)` mesh the
micro-batch dimension is sharded over `data` and layer params are replicated
across it; GSPMD adds the data-axis gradient psum. That recipe is exactly
"the pipeline strategy with a second mesh axis".
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from tpukit.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpukit import mesh as mesh_lib
from tpukit.model import gpt
from tpukit.ops.layers import (
    cross_entropy_sum,
    layer_norm,
    linear,
    psum_bcast,
    vocab_parallel_ce,
)
from tpukit.shardings import Strategy


def _vocab_slice_ce(norm_p, lm_kernel, y, targets, offset, v_local, cfg):
    """Vocab-parallel head: layer_norm -> this stage's `v_local` logit
    columns -> pad-column -1e9 mask -> collective CE over `stage`. The ONE
    definition both pipeline schedules differentiate (GPipe via autodiff,
    1F1B via an explicit jax.vjp); returns ((loss_sum, count), local_logits)
    — the logits so the eval path can compute the global argmax accuracy."""
    h = layer_norm(y, norm_p).astype(cfg.compute_dtype)
    local_logits = linear(h, {"kernel": lm_kernel}, cfg.compute_dtype)
    col = offset + jax.lax.broadcasted_iota(jnp.int32, (v_local,), 0)
    local_logits = jnp.where(
        col < cfg.vocab_size, local_logits,
        jnp.asarray(-1e9, local_logits.dtype),
    )
    return vocab_parallel_ce(local_logits, targets, offset, "stage"), local_logits


def _is_layers_path(path) -> bool:
    return any(
        isinstance(k, jax.tree_util.DictKey) and k.key == "layers" for k in path
    )


def _path_names(path) -> tuple:
    return tuple(k.key for k in path if isinstance(k, jax.tree_util.DictKey))


class Pipeline(Strategy):
    """GPipe pipeline strategy. Use mesh axes `("stage",)` or
    `("data", "stage")` for the DDP hybrid."""

    name = "pipe"
    # activation/cotangent hops between stages; the final loss/grad psums
    # (GSPMD may also emit all-reduce for the data-hybrid grad sum)
    comm_ops = ("collective-permute", "all-reduce")

    def __init__(
        self, mesh: Mesh | None = None, num_microbatches: int | str | None = None
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"stage": -1})
        if "stage" not in self.mesh.axis_names:
            raise ValueError("Pipeline strategy needs a 'stage' mesh axis")
        self.num_stages = self.mesh.shape["stage"]
        # None -> chunks = num_stages, the reference twin (main-pipe.py:83,93).
        # "4x"-style multipliers scale with the stage count: the GPipe bubble
        # is (S-1)/(M+S-1), so M = 4S cuts it from ~43% to ~16% at S=4 —
        # the recipes default to 4x (documented divergence; --microbatches
        # restores any count including the reference's).
        if isinstance(num_microbatches, str):
            if not num_microbatches.endswith("x"):
                raise ValueError(
                    f"num_microbatches: int, None, or '<k>x', got {num_microbatches!r}"
                )
            self.num_microbatches = int(num_microbatches[:-1]) * self.num_stages
        else:
            self.num_microbatches = num_microbatches or self.num_stages
        if self.num_microbatches < 1:
            raise ValueError(
                f"num_microbatches must be positive, got {self.num_microbatches} "
                f"(from {num_microbatches!r})"
            )
        self.data_size = self.mesh.shape.get("data", 1)

    # -- shardings ---------------------------------------------------------

    @property
    def batch_divisor(self) -> int:
        # loss_fn splits the global batch into num_microbatches, each sharded
        # over the data axis.
        return self.num_microbatches * self.data_size

    def padded_layers(self, num_layers: int) -> int:
        """Stacked-layer count after padding to a stage multiple."""
        return -(-num_layers // self.num_stages) * self.num_stages

    @staticmethod
    def _reject_moe(cfg: gpt.GPTConfig) -> None:
        """The curated MoE rejection — raised from validate_config (the
        fit() entry point) AND from loss_fn/value_and_grad, so direct
        strategy calls fail just as loudly (ADVICE r5 #1)."""
        if cfg.num_experts > 0:
            raise ValueError(
                "the pipeline schedules do not support MoE configs (the "
                "micro-batched loss paths have no aux-loss channel) — use "
                "ExpertParallel (main-moe.py), optionally with a data axis"
            )

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        self._validate_comm_dtype(cfg)
        if cfg.num_layers < 1:
            raise ValueError(f"num_layers must be >= 1, got {cfg.num_layers}")
        self._reject_moe(cfg)

    def _vocab_spec(self, names: tuple, shape: tuple) -> P | None:
        """Single source of truth for vocab-over-stage placement. Both
        `state_sharding` and the schedule's shard_map in_specs call this —
        they MUST agree, or the in_specs would mismatch the actual array
        layout at the shard_map boundary. Returns None for leaves that stay
        replicated (including the fallback when the padded vocab does not
        divide the stage count)."""
        if "token" in names and len(shape) == 2 and shape[0] % self.num_stages == 0:
            return P("stage", None)
        if (
            "lm_head" in names
            and names
            and names[-1] == "kernel"
            and shape[-1] % self.num_stages == 0
        ):
            return P(None, "stage")
        return None

    def prepare_params(self, params, cfg: gpt.GPTConfig):
        """Pad the stacked layers to `ceil(L/S)*S` with identity layers.

        Padding layers are all-zero: zero attn-out and ffn-down projections
        make the residual block an exact identity, so a plain `gpt.forward`
        over the padded stack (the generation path) equals the L-layer
        model bit-for-bit; inside the pipeline schedule the padded slots are
        additionally gated off so their parameters get zero gradient (and
        AdamW's decay of an exactly-zero parameter is zero — they stay
        identity forever). This is the twin of the reference's uneven stage
        arithmetic (main-pipe.py:52-68): L=10 on 4 stages runs 3/3/3/1 real
        layers per stage."""
        pad = self.padded_layers(cfg.num_layers) - cfg.num_layers
        if pad == 0:
            return params

        def pad_leaf(leaf):
            return jnp.concatenate(
                [leaf, jnp.zeros((pad, *leaf.shape[1:]), leaf.dtype)], axis=0
            )

        return {**params, "layers": jax.tree.map(pad_leaf, params["layers"])}

    def state_sharding(self, state_shapes):
        """Layer params shard over `stage`; the token embedding and lm_head
        (and their Adam state, which shares these paths) shard their vocab
        dimension over `stage` too (VERDICT r2 #3) — the reference's
        stage-placement of embeddings/head (main-pipe.py:53-55,75-77) as
        *memory layout*, not just compute gating. The tiny position table
        and norms stay replicated. Vocab sharding needs the padded vocab to
        divide the stage count (the default 128-multiple padding divides
        every power-of-two stage count); otherwise those leaves fall back
        to replicated — the same condition loss_fn uses."""
        from jax.sharding import NamedSharding

        def spec(path, leaf):
            if _is_layers_path(path):
                if leaf.shape[0] % self.num_stages:
                    raise ValueError(
                        f"stacked layer axis {leaf.shape[0]} must be a "
                        f"multiple of {self.num_stages} stages — initialize "
                        f"through create_train_state(..., strategy=pipeline) "
                        f"(or pipeline.prepare_params) so uneven layer "
                        f"counts are identity-padded"
                    )
                return NamedSharding(self.mesh, P("stage"))
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return NamedSharding(self.mesh, vocab if vocab is not None else P())

        return jax.tree_util.tree_map_with_path(spec, state_shapes)

    def batch_spec(self) -> P:
        return P("data") if "data" in self.mesh.axis_names else P()

    # -- the schedule ------------------------------------------------------

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        # `aux_out` matches the base signature so a direct
        # `strategy.value_and_grad` call on an MoE config hits the curated
        # error below, not an opaque TypeError (ADVICE r5 #1).
        self._reject_moe(cfg)
        num_stages, num_micro = self.num_stages, self.num_microbatches
        padded = self.padded_layers(cfg.num_layers)
        per_stage = padded // num_stages
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        if stack != padded:
            raise ValueError(
                f"stacked layer axis is {stack} but num_layers="
                f"{cfg.num_layers} on {num_stages} stages needs {padded} "
                f"(identity-padded) — initialize through "
                f"create_train_state(..., strategy=pipeline) or pass params "
                f"through pipeline.prepare_params"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {num_micro} microbatches "
                f"x {self.data_size} data shards"
            )
        micro = global_batch // num_micro
        seq = batch["input_ids"].shape[1]

        def split(x):
            return x.reshape(num_micro, micro, *x.shape[1:])

        inputs = split(batch["input_ids"])
        positions = split(batch["position_ids"])
        masks = split(batch["mask"])
        tgts = split(targets)

        # Specs: layer params split over stage; the token table and lm_head
        # kernel split their vocab dim over stage (memory placement,
        # VERDICT r2 #3) when it divides; position/norms replicated;
        # micro-batch rows split over data (if present).
        data = "data" if "data" in self.mesh.axis_names else None
        batch_spec = P(None, data)
        layers = params["layers"]
        rest = {k: v for k, v in params.items() if k != "layers"}

        v_pad = cfg.padded_vocab_size
        # Derived from the same predicate state_sharding uses, so the
        # in_specs below always match the arrays' actual placement.
        shard_vocab = (
            self._vocab_spec(
                ("embeddings", "token"), rest["embeddings"]["token"].shape
            )
            is not None
        )
        v_local = v_pad // num_stages if shard_vocab else v_pad

        def rest_spec(path, leaf):
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return vocab if vocab is not None else P()

        rest_specs = jax.tree_util.tree_map_with_path(rest_spec, rest)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P("stage"), rest_specs, batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = num_stages - 1
            mb_local = inputs.shape[1]

            x0 = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            # The three accumulators are carried (and returned) as shape
            # (1,), not scalars: older jax (0.4.x) shard_map partial-eval
            # mishandles rank-0 autodiff residuals that forward to other
            # residual slots (structural _SpecError in the transpose; fixed
            # upstream). Rank-1 costs nothing and sidesteps the bug on the
            # pinned-jax deployment image.
            carry0 = (
                x0,
                jnp.zeros((mb_local, seq), jnp.bool_),  # threaded pad mask
                jnp.zeros((mb_local, seq), jnp.int32),  # threaded targets
                jnp.zeros((1,), jnp.float32),  # loss sum
                jnp.zeros((1,), jnp.float32),  # valid-token count
                jnp.zeros((1,), jnp.float32),  # correct count
            )

            def step(carry, t):
                x, mask_c, tgt_c, loss_sum, count, correct = carry
                idx = jnp.clip(t, 0, num_micro - 1)

                # Stage 0 ingests a fresh micro-batch through the embeddings
                # (embeddings live on the first stage, main-pipe.py:53,67,75).
                if shard_vocab:
                    # Vocab-sharded table: every stage contributes its slice
                    # of the lookup (each token id hits exactly one slice, so
                    # the psum is an exact select) and stage 0 ingests the
                    # result. psum_bcast: the cotangent arrives only on
                    # stage 0's path, so the transpose must psum it back to
                    # every stage's table slice.
                    tok_tab = rest_params["embeddings"]["token"]
                    pos_tab = rest_params["embeddings"]["position"]
                    rel = inputs[idx] - stage * v_local
                    ok = (rel >= 0) & (rel < v_local)
                    part = jnp.where(
                        ok[..., None],
                        jnp.take(tok_tab, jnp.where(ok, rel, 0), axis=0),
                        0.0,
                    )
                    emb = psum_bcast(part, "stage") + jnp.take(
                        pos_tab, positions[idx], axis=0
                    )
                    emb = emb.astype(cfg.compute_dtype)
                    is0 = stage == 0
                    x_in = jnp.where(is0, emb, x)
                    mask_in = jnp.where(is0, masks[idx], mask_c)
                    tgt_in = jnp.where(is0, tgts[idx], tgt_c)
                else:

                    def ingest(_):
                        emb = gpt.apply_embeddings(rest_params, cfg, inputs[idx], positions[idx])
                        return emb, masks[idx], tgts[idx]

                    def passthrough(_):
                        return x, mask_c, tgt_c

                    x_in, mask_in, tgt_in = jax.lax.cond(
                        stage == 0, ingest, passthrough, None
                    )

                if rng is None:
                    step_rng = None
                else:
                    # independent dropout per (stage, schedule step, and data
                    # shard if present): fold a linearized index into the key
                    lin = stage * (num_micro + num_stages) + t
                    if data is not None:
                        lin = lin * self.data_size + jax.lax.axis_index(data)
                    step_rng = jax.random.fold_in(rng, lin)
                # Uneven layers: slots past the real layer count are
                # identity-padded AND gated off so they take zero gradient
                # (real layers fill the stack front-to-back, so the last
                # stage holds any inactive slots).
                if padded == cfg.num_layers:
                    active = None
                else:
                    active = (
                        stage * per_stage + jnp.arange(per_stage)
                    ) < cfg.num_layers
                y = gpt.apply_decoder_layers(
                    local_layers, cfg, x_in, mask_in,
                    rng=step_rng, deterministic=step_rng is None,
                    active=active,
                )

                # Head + loss on micro-batch m = t - (S-1) (norm+lm_head on
                # the last stage, main-pipe.py:55,68,77; loss on the last
                # stage's output, main-pipe.py:162-165).
                if shard_vocab:
                    # Vocab-parallel head: broadcast the last stage's
                    # activations/targets, each stage computes its vocab
                    # slice of the logits and the collective CE. Every stage
                    # accumulates the SAME totals; the final psum over the
                    # stage axis scales numerator and denominator alike, so
                    # the loss/accuracy ratios are exact.
                    #
                    # The whole block — including the activation psum_bcast —
                    # is gated behind `emit` (VERDICT r3 #7): during the S-1
                    # warm-up steps no micro-batch has reached the last stage
                    # yet, so broadcasting + head compute there is pure
                    # waste (and its backward too). `emit` depends only on t,
                    # so every device takes the same cond branch and the
                    # collectives inside stay globally matched.
                    def head_loss(_):
                        y_last = psum_bcast(
                            jnp.where(stage == last, y, jnp.zeros_like(y)),
                            "stage",
                        )
                        tgt_last = jax.lax.psum(
                            jnp.where(stage == last, tgt_in, 0), "stage"
                        )
                        offset = stage * v_local
                        # no f32 [micro, S, V] anywhere: each stage holds V/S
                        # columns, CE backward is local (vocab_parallel_ce)
                        (l_sum, cnt), local_logits = _vocab_slice_ce(
                            rest_params["norm_out"],
                            rest_params["lm_head"]["kernel"],
                            y_last, tgt_last, offset, v_local, cfg,
                        )
                        if with_accuracy:
                            lf = local_logits.astype(jnp.float32)
                            lmax = jnp.max(lf, axis=-1)
                            larg = jnp.argmax(lf, axis=-1) + offset
                            gmax = jax.lax.pmax(lmax, "stage")
                            # global argmax, first-index tie-break like argmax
                            preds = jax.lax.pmin(
                                jnp.where(lmax >= gmax, larg, v_pad), "stage"
                            )
                            valid = tgt_last != -100
                            corr = jnp.sum(
                                jnp.where(valid, preds == tgt_last, False)
                            ).astype(jnp.float32)
                        else:
                            corr = jnp.float32(0)
                        return l_sum, cnt, corr

                    def no_loss(_):
                        return jnp.float32(0), jnp.float32(0), jnp.float32(0)

                    emit = t >= num_stages - 1  # uniform across stages
                    l_sum, cnt, corr = jax.lax.cond(emit, head_loss, no_loss, None)
                else:

                    def head_loss(_):
                        logits = gpt.apply_head(rest_params, cfg, y)
                        # custom-VJP sum: no f32 [micro, S, V] tensor in
                        # either direction (ops/layers.py cross_entropy_sum)
                        l_sum, cnt = cross_entropy_sum(logits, tgt_in)
                        if with_accuracy:
                            valid = tgt_in != -100
                            preds = jnp.argmax(logits, axis=-1)
                            corr = jnp.sum(
                                jnp.where(valid, preds == tgt_in, False)
                            ).astype(jnp.float32)
                        else:
                            corr = jnp.float32(0)
                        return l_sum, cnt, corr

                    def no_loss(_):
                        return jnp.float32(0), jnp.float32(0), jnp.float32(0)

                    emit = jnp.logical_and(stage == last, t >= num_stages - 1)
                    l_sum, cnt, corr = jax.lax.cond(emit, head_loss, no_loss, None)

                # Ship activations (and the threaded mask/targets — the twin
                # of the reference's (x, mask) tuple threading) to the next
                # stage over ICI.
                perm = [(i, i + 1) for i in range(num_stages - 1)]
                x_next = jax.lax.ppermute(y, "stage", perm)
                mask_next = jax.lax.ppermute(mask_in, "stage", perm)
                tgt_next = jax.lax.ppermute(tgt_in, "stage", perm)

                return (
                    (x_next, mask_next, tgt_next, loss_sum + l_sum, count + cnt, correct + corr),
                    None,
                )

            total_steps = num_micro + num_stages - 1
            (_, _, _, loss_sum, count, correct), _ = jax.lax.scan(
                step, carry0, jnp.arange(total_steps)
            )

            # Vocab-sharded path: every stage accumulated identical totals
            # from the collective CE, so this psum multiplies numerator and
            # denominator by num_stages alike — the loss/accuracy ratios are
            # exact, and vocab_parallel_ce's backward psums its incoming
            # cotangent over `stage` to undo the same inflation.
            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            count = jax.lax.psum(count, axes)
            correct = jax.lax.psum(correct, axes)
            return loss_sum, count, correct  # each shape (1,), see carry0

        loss_sum, count, correct = (
            x[0] for x in schedule(layers, rest, inputs, positions, masks, tgts)
        )
        denom = jnp.maximum(count, 1.0)
        loss = loss_sum / denom
        accuracy = correct / denom * 100.0
        return loss, accuracy


class Pipeline1F1B(Pipeline):
    """1F1B pipeline schedule: activation memory bounded by the STAGE count.

    The GPipe parent differentiates its whole schedule with autodiff, so
    residuals for every scheduled step stay live until the backward — temp
    memory grows linearly with the micro-batch count (measured in
    docs/DESIGN.md). Here the training gradient is built EXPLICITLY inside
    the tick loop: each tick, every stage runs one primal forward (sending
    its activation on) and one remat-style `jax.vjp` backward for the
    oldest outstanding micro-batch (recomputing the stage trunk from the
    saved stage INPUT, then transposing with the cotangent that arrived
    from the next stage). The scan itself is never differentiated, so each
    tick's internals are freed by XLA as it retires; the only persistent
    activation state is a depth-2S ring buffer of stage inputs —
    independent of the micro-batch count.

    Scheduling is correct-by-dataflow: validity flags travel with the
    forward activations and backward cotangents, invalid work is computed
    but masked to zero (a vjp is linear in its cotangent, so a zero
    cotangent contributes exactly zero gradient), and per-stage counters
    pace the in-order micro-batch streams. The last stage triggers its own
    backward the same tick as its forward — the 1F1B interleave. Ticks:
    num_micro + 2*num_stages - 2 (the bubble is the standard 1F1B one;
    the win is memory, not bubble).

    Embeddings and lm_head shard their VOCAB dimension over `stage`
    exactly like the parent (VERDICT r4 #4): the per-stage vjp covers only
    the trunk (collective-free, so stages may replay *different* micros
    the same tick), while the two vocab-collective computations run at
    TICK level where their micro index is a uniform function of the tick —
    stage 0 ingests micro `t`, the last stage's head+CE serves micro
    `t-(S-1)` — so every stage participates in the same psum for the same
    logical micro-batch and the collectives stay globally matched:

      - ingest: each stage gathers its vocab slice of the lookup, one
        psum assembles the embedding, stage 0 consumes it (the saved
        stage input is POST-ingest, so the trunk replay never re-embeds);
      - head: `jax.vjp` of (layer_norm -> local logits -> collective
        vocab_parallel_ce) at micro `t-(S-1)`, whose primal output is the
        loss contribution and whose pullback yields the lm_head/norm
        grads plus the cotangent the last stage's trunk backward consumes
        the SAME tick (the 1F1B self-trigger);
      - the embedding-table transpose: the cotangent of stage 0's trunk
        input IS d(embedding) for the micro stage 0 is retiring — also a
        uniform function of the tick, `t-(2S-2)` — so one psum broadcasts
        it and every stage scatter-adds its own vocab slice.

    With the replicated fallback (padded vocab not divisible by the stage
    count), ingest / head / table-transpose are instead `lax.cond`-gated
    to the stages that need them (no collectives inside, so the
    non-uniform predicate is safe) — stages no longer compute-and-discard
    the embedding gather every tick (VERDICT r4 #5).

    Eval reuses the parent's forward-only schedule (loss_fn). Dropout
    keys derive from (stage, micro) — not the tick — so the backward's
    recompute sees exactly the forward's mask.
    """

    name = "pipe-1f1b"

    def value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        """(loss, grads) for one global batch — the hook make_step_fns uses
        instead of jax.value_and_grad (tpukit/train.py)."""
        self._reject_moe(cfg)  # fail loudly from any entry point (ADVICE r5 #1)
        num_stages, num_micro = self.num_stages, self.num_microbatches
        padded = self.padded_layers(cfg.num_layers)
        per_stage = padded // num_stages
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        if stack != padded:
            raise ValueError(
                f"stacked layer axis is {stack} but num_layers="
                f"{cfg.num_layers} on {num_stages} stages needs {padded} — "
                f"initialize through create_train_state(..., strategy=...)"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {num_micro} "
                f"microbatches x {self.data_size} data shards"
            )
        micro = global_batch // num_micro
        seq = batch["input_ids"].shape[1]

        def split(x):
            return x.reshape(num_micro, micro, *x.shape[1:])

        inputs = split(batch["input_ids"])
        positions = split(batch["position_ids"])
        masks = split(batch["mask"])
        tgts = split(targets)

        data = "data" if "data" in self.mesh.axis_names else None
        batch_spec = P(None, data)
        layers = params["layers"]
        rest = {k: v for k, v in params.items() if k != "layers"}

        v_pad = cfg.padded_vocab_size
        # Same predicate as state_sharding/loss_fn, so the in/out specs
        # below always match the arrays' actual placement.
        shard_vocab = (
            self._vocab_spec(
                ("embeddings", "token"), rest["embeddings"]["token"].shape
            )
            is not None
        )
        v_local = v_pad // num_stages if shard_vocab else v_pad

        def rest_spec(path, leaf):
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return vocab if vocab is not None else P()

        rest_specs = jax.tree_util.tree_map_with_path(rest_spec, rest)
        # Gradients of vocab-sharded leaves stay stage-local (each stage
        # owns its slice); replicated leaves' contributions are gated to
        # one stage and psum'd. Derived from rest_specs (single source of
        # truth) — decided OUTSIDE shard_map, which needs global shapes.
        rest_sharded = jax.tree.map(
            lambda spec: spec != P(), rest_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P("stage"), rest_specs, batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), P(), P("stage"), rest_specs),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = num_stages - 1
            depth = 2 * num_stages  # ring depth: in-flight micros < 2S - 1
            mb_local = inputs.shape[1]
            # micro m forwards at stage s on tick m+s; the last stage
            # backwards it the same tick; the cotangent reaches stage 0 at
            # tick (m + S - 1) + (S - 1) — so the last backward retires at
            # tick M + 2S - 3, i.e. M + 2S - 2 ticks total.
            ticks = num_micro + 2 * num_stages - 2

            if padded == cfg.num_layers:
                active = None
            else:
                active = (
                    stage * per_stage + jnp.arange(per_stage)
                ) < cfg.num_layers

            def key_for(mi):
                if rng is None:
                    return None
                lin = stage * num_micro + mi
                if data is not None:
                    lin = lin * self.data_size + jax.lax.axis_index(data)
                return jax.random.fold_in(rng, lin)

            def stage_trunk(lp, x_in, mask_in, mi):
                """One stage's trunk slice for micro `mi` — collective-free,
                so its vjp can replay a DIFFERENT micro per stage."""
                k = key_for(mi)
                return gpt.apply_decoder_layers(
                    lp, cfg, x_in, mask_in,
                    rng=k, deterministic=k is None, active=active,
                )

            def sharded_ingest(mi):
                """Distributed lookup: every stage contributes its vocab
                slice, one exact psum assembles the embedding. `mi` must be
                tick-uniform (the psum is collective)."""
                rel = inputs[mi] - stage * v_local
                ok = (rel >= 0) & (rel < v_local)
                part = jnp.where(
                    ok[..., None],
                    jnp.take(
                        rest_params["embeddings"]["token"],
                        jnp.where(ok, rel, 0),
                        axis=0,
                    ),
                    0.0,
                )
                emb = jax.lax.psum(part, "stage") + jnp.take(
                    rest_params["embeddings"]["position"], positions[mi], axis=0
                )
                return emb.astype(cfg.compute_dtype)

            def zeros_rest():
                return jax.tree.map(jnp.zeros_like, rest_params)

            def add_emb_grads(grp, d_tok, d_pos):
                return {
                    **grp,
                    "embeddings": {
                        "token": grp["embeddings"]["token"] + d_tok,
                        "position": grp["embeddings"]["position"] + d_pos,
                    },
                }

            perm_f = [(i, i + 1) for i in range(num_stages - 1)]
            perm_b = [(i + 1, i) for i in range(num_stages - 1)]

            def tick(carry, t):
                (x_fwd, mask_fwd, fvalid, dy_bwd, bvalid, xbuf, maskbuf,
                 fcnt, bcnt, glp, grp, loss_sum, cnt_sum) = carry
                is0 = stage == 0
                at_last = stage == last

                # ---- forward unit: one primal trunk step of micro `fcnt`.
                # Stage 0 ingests through the embeddings; the saved stage
                # input is POST-ingest, so backward replay never re-embeds.
                okf = jnp.where(is0, fcnt < num_micro, fvalid)
                mi_f = jnp.clip(fcnt, 0, num_micro - 1)
                mask_in = jnp.where(is0, masks[mi_f], mask_fwd)
                if shard_vocab:
                    # stage 0's forward micro is `t` (its fcnt advances every
                    # tick until exhausted), a tick-uniform index — so every
                    # stage participates in the ingest psum for the same
                    # logical micro. The predicate is tick-uniform too, so
                    # the 2S-2 drain ticks skip the gather + psum entirely
                    # (collectives inside a uniform cond stay matched).
                    x_eff = jax.lax.cond(
                        t < num_micro,
                        lambda: jnp.where(is0, sharded_ingest(t), x_fwd),
                        lambda: x_fwd,
                    )
                else:
                    x_eff = jax.lax.cond(
                        is0,
                        lambda: gpt.apply_embeddings(
                            rest_params, cfg, inputs[mi_f], positions[mi_f]
                        ),
                        lambda: x_fwd,
                    )
                y = stage_trunk(local_layers, x_eff, mask_in, mi_f)
                slot = fcnt % depth
                # gate the single written slot, not a select over the whole
                # depth-2S buffer (keeps the carry update in place)
                xbuf = xbuf.at[slot].set(jnp.where(okf, x_eff, xbuf[slot]))
                maskbuf = maskbuf.at[slot].set(
                    jnp.where(okf, mask_in, maskbuf[slot])
                )
                fcnt = fcnt + okf.astype(fcnt.dtype)

                # ---- head + CE for the micro reaching the last stage this
                # tick. Its primal output is the loss contribution; its
                # pullback yields the head grads AND the trunk cotangent the
                # last stage consumes the same tick (the 1F1B self-trigger).
                okb_last = bcnt < fcnt  # last stage's backward validity
                if shard_vocab:
                    # tick-uniform micro t-(S-1): collectives inside match.
                    idx_h = t - (num_stages - 1)
                    okh = (idx_h >= 0) & (idx_h < num_micro)
                    mi_h = jnp.clip(idx_h, 0, num_micro - 1)

                    def head_block(_):
                        y_b = jax.lax.psum(
                            jnp.where(at_last, y, jnp.zeros_like(y)), "stage"
                        )
                        tgt_h = tgts[mi_h]
                        offset = stage * v_local

                        def f(norm_p, lm_k, yy):
                            (l, c), _ = _vocab_slice_ce(
                                norm_p, lm_k, yy, tgt_h, offset, v_local, cfg
                            )
                            return l, c

                        (l_s, c_s), pull_h = jax.vjp(
                            f,
                            rest_params["norm_out"],
                            rest_params["lm_head"]["kernel"],
                            y_b,
                        )
                        # vocab_parallel_ce's backward psums the incoming
                        # cotangent over `stage`; gating it to stage 0 makes
                        # that psum recover exactly 1.
                        dl = jnp.where(is0, 1.0, 0.0).astype(jnp.float32)
                        dnorm, dlm, dyb = pull_h((dl, jnp.float32(0)))
                        # f consumed the broadcast y on every stage, so the
                        # true cotangent at the last stage's y is the sum of
                        # every stage's dyb (the psum_bcast transpose).
                        dy_l = jax.lax.psum(dyb, "stage")
                        return l_s, c_s, dnorm, dlm, dy_l

                    def no_head(_):
                        return (
                            jnp.float32(0), jnp.float32(0),
                            jax.tree.map(jnp.zeros_like, rest_params["norm_out"]),
                            jnp.zeros_like(rest_params["lm_head"]["kernel"]),
                            jnp.zeros_like(y),
                        )

                    l_s, c_s, dnorm, dlm, dy_head = jax.lax.cond(
                        okh, head_block, no_head, None
                    )
                    # l_s/c_s are replicated (collective CE); accumulate on
                    # stage 0 only so the final all-axes psum counts them once
                    # per data shard.
                    loss_sum = loss_sum + jnp.where(okh & is0, l_s, 0.0)
                    cnt_sum = cnt_sum + jnp.where(okh & is0, c_s, 0.0)
                    grp = {
                        **grp,
                        "norm_out": jax.tree.map(
                            jnp.add, grp["norm_out"], dnorm
                        ),
                        "lm_head": {
                            "kernel": grp["lm_head"]["kernel"] + dlm
                        },
                    }
                else:
                    mi_b_last = jnp.clip(bcnt, 0, num_micro - 1)

                    def head_block(_):
                        def f(rp, yy):
                            logits = gpt.apply_head(rp, cfg, yy)
                            return cross_entropy_sum(logits, tgts[mi_b_last])

                        (l_s, c_s), pull_h = jax.vjp(f, rest_params, y)
                        dl = jnp.where(okb_last, 1.0, 0.0).astype(jnp.float32)
                        drp, dy_l = pull_h((dl, jnp.float32(0)))
                        return (
                            jnp.where(okb_last, l_s, 0.0),
                            jnp.where(okb_last, c_s, 0.0),
                            drp, dy_l,
                        )

                    def no_head(_):
                        return (
                            jnp.float32(0), jnp.float32(0),
                            zeros_rest(), jnp.zeros_like(y),
                        )

                    # no collectives inside -> the non-uniform predicate is
                    # safe; only the last stage pays the head compute.
                    l_s, c_s, drp_head, dy_head = jax.lax.cond(
                        at_last, head_block, no_head, None
                    )
                    loss_sum = loss_sum + l_s
                    cnt_sum = cnt_sum + c_s
                    grp = jax.tree.map(jnp.add, grp, drp_head)

                # ---- backward unit: remat vjp of the trunk for micro
                # `bcnt` (the last stage self-triggers: its cotangent is
                # dy_head from this very tick).
                okb = jnp.where(at_last, okb_last, bvalid)
                mi_b = jnp.clip(bcnt, 0, num_micro - 1)
                slot_b = bcnt % depth
                f = lambda lp, x: stage_trunk(lp, x, maskbuf[slot_b], mi_b)
                _, pull = jax.vjp(f, local_layers, xbuf[slot_b])
                dy_eff = jnp.where(
                    okb, jnp.where(at_last, dy_head, dy_bwd), 0
                ).astype(cfg.compute_dtype)
                dlp, dx = pull(dy_eff)
                glp = jax.tree.map(jnp.add, glp, dlp)
                bcnt = bcnt + okb.astype(bcnt.dtype)

                # ---- embedding-table transpose: stage 0's trunk-input
                # cotangent IS d(embedding) for the micro stage 0 retires.
                dx_gated = jnp.where(okb & is0, dx, 0).astype(jnp.float32)
                if shard_vocab:
                    # stage 0 retires micro t-(2S-2) — tick-uniform, so one
                    # psum broadcasts d(emb) and every stage scatter-adds its
                    # own vocab slice of the table gradient.
                    idx_b0 = t - (2 * num_stages - 2)
                    mi_e = jnp.clip(idx_b0, 0, num_micro - 1)
                    d_emb = jax.lax.psum(dx_gated, "stage")
                    rel = inputs[mi_e] - stage * v_local
                    ok = (rel >= 0) & (rel < v_local)
                    d_tok = (
                        jnp.zeros_like(grp["embeddings"]["token"])
                        .at[jnp.where(ok, rel, v_local)]
                        .add(
                            jnp.where(ok[..., None], d_emb, 0.0),
                            mode="drop",
                        )
                    )
                    d_pos = (
                        jnp.zeros_like(grp["embeddings"]["position"])
                        .at[positions[mi_e]]
                        .add(d_emb)
                    )
                    # position table is replicated (final psum over stage):
                    # count its contribution once.
                    grp = add_emb_grads(
                        grp, d_tok, jnp.where(is0, d_pos, 0.0)
                    )
                else:

                    def emb_bwd(_):
                        d_tok = (
                            jnp.zeros_like(grp["embeddings"]["token"])
                            .at[inputs[mi_b]]
                            .add(dx_gated)
                        )
                        d_pos = (
                            jnp.zeros_like(grp["embeddings"]["position"])
                            .at[positions[mi_b]]
                            .add(dx_gated)
                        )
                        return d_tok, d_pos

                    def no_emb(_):
                        return (
                            jnp.zeros_like(grp["embeddings"]["token"]),
                            jnp.zeros_like(grp["embeddings"]["position"]),
                        )

                    d_tok, d_pos = jax.lax.cond(is0, emb_bwd, no_emb, None)
                    grp = add_emb_grads(grp, d_tok, d_pos)

                # ---- ship: activations forward, cotangents backward ----
                x_next = jax.lax.ppermute(y, "stage", perm_f)
                mask_next = jax.lax.ppermute(mask_in, "stage", perm_f)
                fvalid_next = jax.lax.ppermute(okf, "stage", perm_f)
                dy_next = jax.lax.ppermute(dx, "stage", perm_b)
                bvalid_next = jax.lax.ppermute(okb, "stage", perm_b)
                return (
                    (x_next, mask_next, fvalid_next, dy_next, bvalid_next,
                     xbuf, maskbuf, fcnt, bcnt, glp, grp, loss_sum, cnt_sum),
                    None,
                )

            zeros_x = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            carry0 = (
                zeros_x,
                jnp.zeros((mb_local, seq), jnp.bool_),
                jnp.bool_(False),
                zeros_x,
                jnp.bool_(False),
                jnp.zeros((depth, mb_local, seq, cfg.dim), cfg.compute_dtype),
                jnp.zeros((depth, mb_local, seq), jnp.bool_),
                jnp.int32(0),
                jnp.int32(0),
                jax.tree.map(jnp.zeros_like, local_layers),
                jax.tree.map(jnp.zeros_like, rest_params),
                jnp.float32(0),
                jnp.float32(0),
            )
            final_carry, _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
            glp, grp, loss_sum, cnt_sum = final_carry[-4:]

            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            cnt_sum = jax.lax.psum(cnt_sum, axes)
            # layer grads are stage-local; sum row-shards over `data`.
            # Vocab-sharded leaves (token table / lm_head kernel) likewise
            # stay stage-local; replicated rest leaves were gated to a
            # single stage's contribution and psum over every axis.
            if data is not None:
                glp = jax.tree.map(lambda g: jax.lax.psum(g, data), glp)

            def reduce_rest(g, is_sharded):
                if is_sharded:
                    return jax.lax.psum(g, data) if data is not None else g
                return jax.lax.psum(g, axes)

            grp = jax.tree.map(reduce_rest, grp, rest_sharded)
            return loss_sum, cnt_sum, glp, grp

        loss_sum, count, glp, grp = schedule(
            layers, rest, inputs, positions, masks, tgts
        )
        denom = jnp.maximum(count, 1.0)
        grads = {**grp, "layers": glp}
        grads = jax.tree.map(lambda g: (g / denom).astype(g.dtype), grads)
        return loss_sum / denom, grads
