"""Pipeline parallelism: a GPipe schedule over a `stage` mesh axis.

TPU-native re-design of the reference's pipeline recipe (main-pipe.py, which
uses the deprecated torch `Pipe` over an `nn.Sequential` of per-GPU stages
with TensorPipe RPC, main-pipe.py:21-28,75-83). Here there is no RPC layer
and no wrapper modules: the decoder's stacked layer parameters are sharded
along their leading `num_layers` axis over the `stage` mesh axis, and a
`shard_map` runs the classic GPipe micro-batch schedule with
`jax.lax.ppermute` (XLA collective-permute over ICI) moving activations
stage-to-stage. Autodiff through `ppermute`/`scan` gives the pipelined
backward for free — the capability torch `Pipe` implements by hand.

Faithful structure (intent of main-pipe.py:52-83, which has syntax errors —
SURVEY §2.9 #3-5):
  - embeddings are applied on stage 0 and the norm+lm_head on the last stage
    (stage layout of main-pipe.py:53-55,67-68,75-77);
  - the padding mask (and here, the targets) are threaded through the
    pipeline alongside the activations — the twin of the `(x, mask)` tuple
    threading every reference stage performs (main-pipe.py:35-37,43-50);
  - the number of micro-batches defaults to the number of stages
    (`chunks=num_stages`, main-pipe.py:83,93).

Uneven layer counts (intent of main-pipe.py:63-68, VERDICT r2 #5): any
`num_layers >= 1` trains on any stage count. The stacked layer parameters
are padded to `ceil(L/S)*S` with all-zero identity layers (zero projections
make `x + attn(...) + ffn(...) == x` exactly), appended at the end so real
layers keep their order; the schedule gates padded slots off with a `where`
on the residual stream, so padded parameters receive zero gradient and the
loss matches the unpadded single-device model exactly. Padding happens at
init via `prepare_params` (wired through `create_train_state`); checkpoints
of an uneven config therefore carry the padded layer axis and restore into
layouts with the same padded count.

Memory placement (VERDICT r2 #3): the token embedding table and the lm_head
kernel shard their VOCAB dimension over the `stage` axis (and their Adam
state follows, via `state_sharding`), so no device holds a full table — the
reference's stage layout (embeddings on the first GPU, head on the last,
main-pipe.py:53-55,75-77) achieved as sharding rather than placement.
Compute stays role-specific: stage 0 ingests through a distributed lookup
(each stage contributes its vocab slice, one exact psum), the last stage's
activations feed a Megatron-style vocab-parallel head + CE
(`ops/layers.py vocab_parallel_ce`) in which every stage owns V/S logit
columns and no full-vocab tensor ever materializes. Falls back to
replicated embeddings/head when the padded vocab does not divide the stage
count (the default 128-multiple padding divides any power-of-two count).

Loss is computed as a (sum, count) pair and `psum`-broadcast, so the
returned loss equals the non-pipelined global mean exactly (twin of
main-pipe.py:162-165).

The same shard_map serves the 2-D pipeline x data hybrid (`main-pipe-ddp.py`,
a stub in the reference — SURVEY §2.4): with a `(data, stage)` mesh the
micro-batch dimension is sharded over `data` and layer params are replicated
across it; GSPMD adds the data-axis gradient psum. That recipe is exactly
"the pipeline strategy with a second mesh axis".
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from tpukit.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpukit import mesh as mesh_lib
from tpukit.model import gpt
from tpukit.ops.layers import (
    cross_entropy_sum,
    layer_norm,
    linear,
    psum_bcast,
    vocab_parallel_ce,
)
from tpukit.pipeline_schedule import cached_schedule
from tpukit.shardings import Strategy


def _vocab_slice_ce(norm_p, lm_kernel, y, targets, offset, v_local, cfg):
    """Vocab-parallel head: layer_norm -> this stage's `v_local` logit
    columns -> pad-column -1e9 mask -> collective CE over `stage`. The ONE
    definition both pipeline schedules differentiate (GPipe via autodiff,
    1F1B via an explicit jax.vjp); returns ((loss_sum, count), local_logits)
    — the logits so the eval path can compute the global argmax accuracy."""
    h = layer_norm(y, norm_p).astype(cfg.compute_dtype)
    local_logits = linear(h, {"kernel": lm_kernel}, cfg.compute_dtype)
    col = offset + jax.lax.broadcasted_iota(jnp.int32, (v_local,), 0)
    local_logits = jnp.where(
        col < cfg.vocab_size, local_logits,
        jnp.asarray(-1e9, local_logits.dtype),
    )
    return vocab_parallel_ce(local_logits, targets, offset, "stage"), local_logits


def _is_layers_path(path) -> bool:
    return any(
        isinstance(k, jax.tree_util.DictKey) and k.key == "layers" for k in path
    )


def _path_names(path) -> tuple:
    return tuple(k.key for k in path if isinstance(k, jax.tree_util.DictKey))


class Pipeline(Strategy):
    """GPipe pipeline strategy. Use mesh axes `("stage",)` or
    `("data", "stage")` for the DDP hybrid."""

    name = "pipe"
    # activation/cotangent hops between stages; the final loss/grad psums
    # (GSPMD may also emit all-reduce for the data-hybrid grad sum)
    comm_ops = ("collective-permute", "all-reduce")
    # Interleaved virtual stages (cfg.virtual_stages > 1) need a schedule
    # whose tick machine understands non-contiguous chunk ownership; the
    # autodiffed GPipe scan runs one contiguous block per stage only.
    supports_interleave = False

    def __init__(
        self,
        mesh: Mesh | None = None,
        num_microbatches: int | str | None = None,
        moe_dispatch: str | None = None,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"stage": -1})
        if "stage" not in self.mesh.axis_names:
            raise ValueError("Pipeline strategy needs a 'stage' mesh axis")
        self.num_stages = self.mesh.shape["stage"]
        # None -> chunks = num_stages, the reference twin (main-pipe.py:83,93).
        # "4x"-style multipliers scale with the stage count: the GPipe bubble
        # is (S-1)/(M+S-1), so M = 4S cuts it from ~43% to ~16% at S=4 —
        # the recipes default to 4x (documented divergence; --microbatches
        # restores any count including the reference's).
        if isinstance(num_microbatches, str):
            if not num_microbatches.endswith("x"):
                raise ValueError(
                    f"num_microbatches: int, None, or '<k>x', got {num_microbatches!r}"
                )
            self.num_microbatches = int(num_microbatches[:-1]) * self.num_stages
        else:
            self.num_microbatches = num_microbatches or self.num_stages
        if self.num_microbatches < 1:
            raise ValueError(
                f"num_microbatches must be positive, got {self.num_microbatches} "
                f"(from {num_microbatches!r})"
            )
        self.data_size = self.mesh.shape.get("data", 1)
        # Expert dispatch override injected into cfg at loss time (the
        # ExpertParallel pattern): None defers to cfg.moe_dispatch. Only
        # the meshless "pallas" dataflow composes with the pipeline —
        # _check_moe enforces that at every entry point.
        self.moe_dispatch = moe_dispatch

    # -- shardings ---------------------------------------------------------

    @property
    def batch_divisor(self) -> int:
        # loss_fn splits the global batch into num_microbatches, each sharded
        # over the data axis.
        return self.num_microbatches * self.data_size

    def padded_layers(self, num_layers: int, virtual_stages: int = 1) -> int:
        """Stacked-layer count after padding to a chunk-grid multiple:
        `ceil(L / (S*V)) * S * V`, so every one of the S*V chunks holds the
        same per-chunk layer count (V=1 recovers the old stage multiple)."""
        blocks = self.num_stages * virtual_stages
        return -(-num_layers // blocks) * blocks

    def _check_moe(self, cfg: gpt.GPTConfig) -> None:
        """The curated MoE gate — raised from validate_config (the fit()
        entry point) AND from loss_fn/value_and_grad, so direct strategy
        calls fail just as loudly (ADVICE r5 #1). Round 22: the meshless
        dropless "pallas" dispatch is collective-free, so it composes with
        the pipeline's shard_map (each stage's chunk runs its MoE FFNs on
        whatever micro-batch it holds); the buffer dispatches stay rejected
        BY NAME — "xla"/"a2a" shard tokens over an 'expert' mesh axis the
        pipeline meshes do not carry."""
        if cfg.num_experts == 0:
            return
        dispatch = self.moe_dispatch or cfg.moe_dispatch
        if dispatch != "pallas":
            raise ValueError(
                f"the pipeline schedules support MoE only through the "
                f"meshless dropless dispatch — pass --moe_dispatch pallas "
                f"(got moe_dispatch={dispatch!r}: 'xla'/'a2a' need an "
                f"'expert' mesh axis the pipeline mesh does not carry) — "
                f"or use ExpertParallel (main-moe.py), optionally with a "
                f"data axis"
            )

    def _moe_cfg(self, cfg: gpt.GPTConfig) -> gpt.GPTConfig:
        """Inject the strategy's dispatch into the config at loss time (the
        ExpertParallel pattern, shardings.py _dispatch_cfg) — the pallas
        dataflow is meshless, so moe_mesh stays None."""
        if cfg.num_experts == 0:
            return cfg
        return cfg.replace(
            moe_dispatch=self.moe_dispatch or cfg.moe_dispatch, moe_mesh=None
        )

    def _check_interleave(self, cfg: gpt.GPTConfig) -> None:
        """Validation matrix for cfg.virtual_stages (round 22)."""
        v = cfg.virtual_stages
        if v == 1:
            return
        if not self.supports_interleave:
            raise ValueError(
                f"virtual_stages={v} needs the 1f1b schedule "
                f"(--pipeline_schedule 1f1b / Pipeline1F1B) — the GPipe "
                f"schedule runs one contiguous layer block per stage and "
                f"cannot interleave chunks"
            )
        if v * self.num_stages > cfg.num_layers:
            raise ValueError(
                f"virtual_stages={v} x {self.num_stages} stages = "
                f"{v * self.num_stages} chunks exceeds num_layers="
                f"{cfg.num_layers} — every chunk needs at least one real "
                f"layer, so the maximum virtual_stages here is "
                f"{cfg.num_layers // self.num_stages}"
            )

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        self._validate_comm_dtype(cfg)
        if cfg.num_layers < 1:
            raise ValueError(f"num_layers must be >= 1, got {cfg.num_layers}")
        self._check_moe(cfg)
        self._check_interleave(cfg)

    def _vocab_spec(self, names: tuple, shape: tuple) -> P | None:
        """Single source of truth for vocab-over-stage placement. Both
        `state_sharding` and the schedule's shard_map in_specs call this —
        they MUST agree, or the in_specs would mismatch the actual array
        layout at the shard_map boundary. Returns None for leaves that stay
        replicated (including the fallback when the padded vocab does not
        divide the stage count)."""
        if "token" in names and len(shape) == 2 and shape[0] % self.num_stages == 0:
            return P("stage", None)
        if (
            "lm_head" in names
            and names
            and names[-1] == "kernel"
            and shape[-1] % self.num_stages == 0
        ):
            return P(None, "stage")
        return None

    def prepare_params(self, params, cfg: gpt.GPTConfig):
        """Pad the stacked layers to `ceil(L/S)*S` with identity layers.

        Padding layers are all-zero: zero attn-out and ffn-down projections
        make the residual block an exact identity, so a plain `gpt.forward`
        over the padded stack (the generation path) equals the L-layer
        model bit-for-bit; inside the pipeline schedule the padded slots are
        additionally gated off so their parameters get zero gradient (and
        AdamW's decay of an exactly-zero parameter is zero — they stay
        identity forever). This is the twin of the reference's uneven stage
        arithmetic (main-pipe.py:52-68): L=10 on 4 stages runs 3/3/3/1 real
        layers per stage.

        Interleaved layouts (cfg.virtual_stages = V > 1, round 22): the
        padded stack is additionally PERMUTED so that the plain
        `P("stage")` sharding hands device d its V non-contiguous chunks
        d, d+S, ..., d+(V-1)S as one local slab — stacked row
        (d*V + c)*p + j holds natural layer (c*S + d)*p + j (p layers per
        chunk). V=1 is the identity permutation, so the path below only
        fires for V > 1 and dense checkpoints keep their natural order.
        `inference_params` is the inverse (the generation path runs the
        sequential `gpt.forward`, which needs natural order)."""
        v = cfg.virtual_stages
        padded = self.padded_layers(cfg.num_layers, v)
        pad = padded - cfg.num_layers
        if pad == 0 and v == 1:
            return params

        layers = params["layers"]
        if pad:

            def pad_leaf(leaf):
                return jnp.concatenate(
                    [leaf, jnp.zeros((pad, *leaf.shape[1:]), leaf.dtype)], axis=0
                )

            layers = jax.tree.map(pad_leaf, layers)
        if v > 1:
            perm = jnp.asarray(self._chunk_perm(padded, v))
            layers = jax.tree.map(lambda leaf: leaf[perm], layers)
        return {**params, "layers": layers}

    def _chunk_perm(self, padded: int, virtual_stages: int) -> list:
        """Row order of the interleaved stacked-layer layout: stacked row
        (d*V + c)*p + j <- natural layer (c*S + d)*p + j. Identity at
        V=1."""
        per_chunk = padded // (self.num_stages * virtual_stages)
        perm = []
        for d in range(self.num_stages):
            for c in range(virtual_stages):
                g = c * self.num_stages + d
                perm.extend(range(g * per_chunk, (g + 1) * per_chunk))
        return perm

    def inference_params(self, params, cfg: gpt.GPTConfig):
        """Undo the interleaved chunk permutation so the plain sequential
        `gpt.forward` (generation/eval outside the schedule) applies layers
        in natural order. Identity-padded layers are order-safe, but the
        V > 1 permutation is not — generate_samples routes every strategy's
        replicated params through this hook (tpukit/train.py)."""
        v = cfg.virtual_stages
        if v == 1:
            return params
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        perm = self._chunk_perm(stack, v)
        inv = [0] * len(perm)
        for i, k in enumerate(perm):
            inv[k] = i
        inv = jnp.asarray(inv)
        return {
            **params,
            "layers": jax.tree.map(lambda leaf: leaf[inv], params["layers"]),
        }

    def state_sharding(self, state_shapes):
        """Layer params shard over `stage`; the token embedding and lm_head
        (and their Adam state, which shares these paths) shard their vocab
        dimension over `stage` too (VERDICT r2 #3) — the reference's
        stage-placement of embeddings/head (main-pipe.py:53-55,75-77) as
        *memory layout*, not just compute gating. The tiny position table
        and norms stay replicated. Vocab sharding needs the padded vocab to
        divide the stage count (the default 128-multiple padding divides
        every power-of-two stage count); otherwise those leaves fall back
        to replicated — the same condition loss_fn uses."""
        from jax.sharding import NamedSharding

        def spec(path, leaf):
            if _is_layers_path(path):
                if leaf.shape[0] % self.num_stages:
                    raise ValueError(
                        f"stacked layer axis {leaf.shape[0]} must be a "
                        f"multiple of {self.num_stages} stages — initialize "
                        f"through create_train_state(..., strategy=pipeline) "
                        f"(or pipeline.prepare_params) so uneven layer "
                        f"counts are identity-padded"
                    )
                return NamedSharding(self.mesh, P("stage"))
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return NamedSharding(self.mesh, vocab if vocab is not None else P())

        return jax.tree_util.tree_map_with_path(spec, state_shapes)

    def batch_spec(self) -> P:
        return P("data") if "data" in self.mesh.axis_names else P()

    # -- the schedule ------------------------------------------------------

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        # Direct `strategy.loss_fn`/`value_and_grad` calls on an illegal
        # MoE or interleave config hit the curated errors below, not an
        # opaque shape mismatch (ADVICE r5 #1).
        self._check_moe(cfg)
        self._check_interleave(cfg)
        cfg = self._moe_cfg(cfg)
        # MoE aux channel (round 22): collect the per-(stage, tick) summed
        # load-balance aux in the scan carry, gated to valid micros, and
        # append its (micro, data-shard) mean — the Switch per-micro-batch
        # objective. Python-gated so dense traces are untouched.
        moe_aux = cfg.num_experts > 0 and aux_out is not None
        num_stages, num_micro = self.num_stages, self.num_microbatches
        padded = self.padded_layers(cfg.num_layers)
        per_stage = padded // num_stages
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        if stack != padded:
            raise ValueError(
                f"stacked layer axis is {stack} but num_layers="
                f"{cfg.num_layers} on {num_stages} stages needs {padded} "
                f"(identity-padded) — initialize through "
                f"create_train_state(..., strategy=pipeline) or pass params "
                f"through pipeline.prepare_params"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {num_micro} microbatches "
                f"x {self.data_size} data shards"
            )
        micro = global_batch // num_micro
        seq = batch["input_ids"].shape[1]

        def split(x):
            return x.reshape(num_micro, micro, *x.shape[1:])

        inputs = split(batch["input_ids"])
        positions = split(batch["position_ids"])
        masks = split(batch["mask"])
        tgts = split(targets)

        # Specs: layer params split over stage; the token table and lm_head
        # kernel split their vocab dim over stage (memory placement,
        # VERDICT r2 #3) when it divides; position/norms replicated;
        # micro-batch rows split over data (if present).
        data = "data" if "data" in self.mesh.axis_names else None
        batch_spec = P(None, data)
        layers = params["layers"]
        rest = {k: v for k, v in params.items() if k != "layers"}

        v_pad = cfg.padded_vocab_size
        # Derived from the same predicate state_sharding uses, so the
        # in_specs below always match the arrays' actual placement.
        shard_vocab = (
            self._vocab_spec(
                ("embeddings", "token"), rest["embeddings"]["token"].shape
            )
            is not None
        )
        v_local = v_pad // num_stages if shard_vocab else v_pad

        def rest_spec(path, leaf):
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return vocab if vocab is not None else P()

        rest_specs = jax.tree_util.tree_map_with_path(rest_spec, rest)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P("stage"), rest_specs, batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(),) * (4 if moe_aux else 3),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = num_stages - 1
            mb_local = inputs.shape[1]

            x0 = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            # The three accumulators are carried (and returned) as shape
            # (1,), not scalars: older jax (0.4.x) shard_map partial-eval
            # mishandles rank-0 autodiff residuals that forward to other
            # residual slots (structural _SpecError in the transpose; fixed
            # upstream). Rank-1 costs nothing and sidesteps the bug on the
            # pinned-jax deployment image.
            carry0 = (
                x0,
                jnp.zeros((mb_local, seq), jnp.bool_),  # threaded pad mask
                jnp.zeros((mb_local, seq), jnp.int32),  # threaded targets
                jnp.zeros((1,), jnp.float32),  # loss sum
                jnp.zeros((1,), jnp.float32),  # valid-token count
                jnp.zeros((1,), jnp.float32),  # correct count
            )

            def step(carry, t):
                if moe_aux:
                    x, mask_c, tgt_c, loss_sum, count, correct, aux_sum = carry
                else:
                    x, mask_c, tgt_c, loss_sum, count, correct = carry
                idx = jnp.clip(t, 0, num_micro - 1)

                # Stage 0 ingests a fresh micro-batch through the embeddings
                # (embeddings live on the first stage, main-pipe.py:53,67,75).
                if shard_vocab:
                    # Vocab-sharded table: every stage contributes its slice
                    # of the lookup (each token id hits exactly one slice, so
                    # the psum is an exact select) and stage 0 ingests the
                    # result. psum_bcast: the cotangent arrives only on
                    # stage 0's path, so the transpose must psum it back to
                    # every stage's table slice.
                    tok_tab = rest_params["embeddings"]["token"]
                    pos_tab = rest_params["embeddings"]["position"]
                    rel = inputs[idx] - stage * v_local
                    ok = (rel >= 0) & (rel < v_local)
                    part = jnp.where(
                        ok[..., None],
                        jnp.take(tok_tab, jnp.where(ok, rel, 0), axis=0),
                        0.0,
                    )
                    emb = psum_bcast(part, "stage") + jnp.take(
                        pos_tab, positions[idx], axis=0
                    )
                    emb = emb.astype(cfg.compute_dtype)
                    is0 = stage == 0
                    x_in = jnp.where(is0, emb, x)
                    mask_in = jnp.where(is0, masks[idx], mask_c)
                    tgt_in = jnp.where(is0, tgts[idx], tgt_c)
                else:

                    def ingest(_):
                        emb = gpt.apply_embeddings(rest_params, cfg, inputs[idx], positions[idx])
                        return emb, masks[idx], tgts[idx]

                    def passthrough(_):
                        return x, mask_c, tgt_c

                    x_in, mask_in, tgt_in = jax.lax.cond(
                        stage == 0, ingest, passthrough, None
                    )

                if rng is None:
                    step_rng = None
                else:
                    # independent dropout per (stage, schedule step, and data
                    # shard if present): fold a linearized index into the key
                    lin = stage * (num_micro + num_stages) + t
                    if data is not None:
                        lin = lin * self.data_size + jax.lax.axis_index(data)
                    step_rng = jax.random.fold_in(rng, lin)
                # Uneven layers: slots past the real layer count are
                # identity-padded AND gated off so they take zero gradient
                # (real layers fill the stack front-to-back, so the last
                # stage holds any inactive slots).
                if padded == cfg.num_layers:
                    active = None
                else:
                    active = (
                        stage * per_stage + jnp.arange(per_stage)
                    ) < cfg.num_layers
                if moe_aux:
                    # The aux from fill/drain ticks is garbage (the stage
                    # trunk runs on zeros there) — gate it to the ticks
                    # where this stage holds a real micro: stage s sees
                    # micro t - s, valid while 0 <= t - s < M. The CE path
                    # needs no such gate (garbage work never flows into an
                    # emitted loss), but aux is accumulated directly.
                    al: list = []
                    y = gpt.apply_decoder_layers(
                        local_layers, cfg, x_in, mask_in,
                        rng=step_rng, deterministic=step_rng is None,
                        active=active, aux_out=al,
                    )
                    stage_valid = (t >= stage) & (t - stage < num_micro)
                    aux_t = jnp.where(stage_valid, al[0], 0.0)
                else:
                    y = gpt.apply_decoder_layers(
                        local_layers, cfg, x_in, mask_in,
                        rng=step_rng, deterministic=step_rng is None,
                        active=active,
                    )

                # Head + loss on micro-batch m = t - (S-1) (norm+lm_head on
                # the last stage, main-pipe.py:55,68,77; loss on the last
                # stage's output, main-pipe.py:162-165).
                if shard_vocab:
                    # Vocab-parallel head: broadcast the last stage's
                    # activations/targets, each stage computes its vocab
                    # slice of the logits and the collective CE. Every stage
                    # accumulates the SAME totals; the final psum over the
                    # stage axis scales numerator and denominator alike, so
                    # the loss/accuracy ratios are exact.
                    #
                    # The whole block — including the activation psum_bcast —
                    # is gated behind `emit` (VERDICT r3 #7): during the S-1
                    # warm-up steps no micro-batch has reached the last stage
                    # yet, so broadcasting + head compute there is pure
                    # waste (and its backward too). `emit` depends only on t,
                    # so every device takes the same cond branch and the
                    # collectives inside stay globally matched.
                    def head_loss(_):
                        y_last = psum_bcast(
                            jnp.where(stage == last, y, jnp.zeros_like(y)),
                            "stage",
                        )
                        tgt_last = jax.lax.psum(
                            jnp.where(stage == last, tgt_in, 0), "stage"
                        )
                        offset = stage * v_local
                        # no f32 [micro, S, V] anywhere: each stage holds V/S
                        # columns, CE backward is local (vocab_parallel_ce)
                        (l_sum, cnt), local_logits = _vocab_slice_ce(
                            rest_params["norm_out"],
                            rest_params["lm_head"]["kernel"],
                            y_last, tgt_last, offset, v_local, cfg,
                        )
                        if with_accuracy:
                            lf = local_logits.astype(jnp.float32)
                            lmax = jnp.max(lf, axis=-1)
                            larg = jnp.argmax(lf, axis=-1) + offset
                            gmax = jax.lax.pmax(lmax, "stage")
                            # global argmax, first-index tie-break like argmax
                            preds = jax.lax.pmin(
                                jnp.where(lmax >= gmax, larg, v_pad), "stage"
                            )
                            valid = tgt_last != -100
                            corr = jnp.sum(
                                jnp.where(valid, preds == tgt_last, False)
                            ).astype(jnp.float32)
                        else:
                            corr = jnp.float32(0)
                        return l_sum, cnt, corr

                    def no_loss(_):
                        return jnp.float32(0), jnp.float32(0), jnp.float32(0)

                    emit = t >= num_stages - 1  # uniform across stages
                    l_sum, cnt, corr = jax.lax.cond(emit, head_loss, no_loss, None)
                else:

                    def head_loss(_):
                        logits = gpt.apply_head(rest_params, cfg, y)
                        # custom-VJP sum: no f32 [micro, S, V] tensor in
                        # either direction (ops/layers.py cross_entropy_sum)
                        l_sum, cnt = cross_entropy_sum(logits, tgt_in)
                        if with_accuracy:
                            valid = tgt_in != -100
                            preds = jnp.argmax(logits, axis=-1)
                            corr = jnp.sum(
                                jnp.where(valid, preds == tgt_in, False)
                            ).astype(jnp.float32)
                        else:
                            corr = jnp.float32(0)
                        return l_sum, cnt, corr

                    def no_loss(_):
                        return jnp.float32(0), jnp.float32(0), jnp.float32(0)

                    emit = jnp.logical_and(stage == last, t >= num_stages - 1)
                    l_sum, cnt, corr = jax.lax.cond(emit, head_loss, no_loss, None)

                # Ship activations (and the threaded mask/targets — the twin
                # of the reference's (x, mask) tuple threading) to the next
                # stage over ICI.
                perm = [(i, i + 1) for i in range(num_stages - 1)]
                x_next = jax.lax.ppermute(y, "stage", perm)
                mask_next = jax.lax.ppermute(mask_in, "stage", perm)
                tgt_next = jax.lax.ppermute(tgt_in, "stage", perm)

                out = (
                    x_next, mask_next, tgt_next,
                    loss_sum + l_sum, count + cnt, correct + corr,
                )
                if moe_aux:
                    out = out + (aux_sum + aux_t,)
                return out, None

            if moe_aux:
                carry0 = carry0 + (jnp.zeros((1,), jnp.float32),)
            total_steps = num_micro + num_stages - 1
            final, _ = jax.lax.scan(step, carry0, jnp.arange(total_steps))
            loss_sum, count, correct = final[3:6]
            aux_sum = final[6] if moe_aux else None

            # Vocab-sharded path: every stage accumulated identical totals
            # from the collective CE, so this psum multiplies numerator and
            # denominator by num_stages alike — the loss/accuracy ratios are
            # exact, and vocab_parallel_ce's backward psums its incoming
            # cotangent over `stage` to undo the same inflation.
            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            count = jax.lax.psum(count, axes)
            correct = jax.lax.psum(correct, axes)
            if moe_aux:
                # psum over stage sums the per-chunk aux (each stage's
                # layers are distinct), over data the per-shard stats.
                return loss_sum, count, correct, jax.lax.psum(aux_sum, axes)
            return loss_sum, count, correct  # each shape (1,), see carry0

        outs = tuple(
            x[0] for x in schedule(layers, rest, inputs, positions, masks, tgts)
        )
        loss_sum, count, correct = outs[:3]
        if moe_aux:
            # The per-micro objective: mean over micro-batches and data
            # shards of each micro's summed layer aux (base value_and_grad
            # adds cfg.moe_aux_weight * this to the differentiated total).
            aux_out.append(outs[3] / (num_micro * self.data_size))
        denom = jnp.maximum(count, 1.0)
        loss = loss_sum / denom
        accuracy = correct / denom * 100.0
        return loss, accuracy


class Pipeline1F1B(Pipeline):
    """1F1B pipeline schedule: activation memory bounded by the STAGE count.

    The GPipe parent differentiates its whole schedule with autodiff, so
    residuals for every scheduled step stay live until the backward — temp
    memory grows linearly with the micro-batch count (measured in
    docs/DESIGN.md). Here the training gradient is built EXPLICITLY inside
    the tick loop: each tick, every stage runs one primal forward (sending
    its activation on) and one remat-style `jax.vjp` backward for the
    oldest outstanding micro-batch (recomputing the stage trunk from the
    saved stage INPUT, then transposing with the cotangent that arrived
    from the next stage). The scan itself is never differentiated, so each
    tick's internals are freed by XLA as it retires; the only persistent
    activation state is a depth-2S ring buffer of stage inputs —
    independent of the micro-batch count.

    Scheduling is correct-by-dataflow: validity flags travel with the
    forward activations and backward cotangents, invalid work is computed
    but masked to zero (a vjp is linear in its cotangent, so a zero
    cotangent contributes exactly zero gradient), and per-stage counters
    pace the in-order micro-batch streams. The last stage triggers its own
    backward the same tick as its forward — the 1F1B interleave. Ticks:
    num_micro + 2*num_stages - 2 (the bubble is the standard 1F1B one;
    the win is memory, not bubble).

    Embeddings and lm_head shard their VOCAB dimension over `stage`
    exactly like the parent (VERDICT r4 #4): the per-stage vjp covers only
    the trunk (collective-free, so stages may replay *different* micros
    the same tick), while the two vocab-collective computations run at
    TICK level where their micro index is a uniform function of the tick —
    stage 0 ingests micro `t`, the last stage's head+CE serves micro
    `t-(S-1)` — so every stage participates in the same psum for the same
    logical micro-batch and the collectives stay globally matched:

      - ingest: each stage gathers its vocab slice of the lookup, one
        psum assembles the embedding, stage 0 consumes it (the saved
        stage input is POST-ingest, so the trunk replay never re-embeds);
      - head: `jax.vjp` of (layer_norm -> local logits -> collective
        vocab_parallel_ce) at micro `t-(S-1)`, whose primal output is the
        loss contribution and whose pullback yields the lm_head/norm
        grads plus the cotangent the last stage's trunk backward consumes
        the SAME tick (the 1F1B self-trigger);
      - the embedding-table transpose: the cotangent of stage 0's trunk
        input IS d(embedding) for the micro stage 0 is retiring — also a
        uniform function of the tick, `t-(2S-2)` — so one psum broadcasts
        it and every stage scatter-adds its own vocab slice.

    With the replicated fallback (padded vocab not divisible by the stage
    count), ingest / head / table-transpose are instead `lax.cond`-gated
    to the stages that need them (no collectives inside, so the
    non-uniform predicate is safe) — stages no longer compute-and-discard
    the embedding gather every tick (VERDICT r4 #5).

    Eval reuses the parent's forward-only schedule (loss_fn). Dropout
    keys derive from (stage, micro) — not the tick — so the backward's
    recompute sees exactly the forward's mask.
    """

    name = "pipe-1f1b"
    supports_interleave = True

    def value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        """(loss, grads) for one global batch — the hook make_step_fns uses
        instead of jax.value_and_grad (tpukit/train.py).

        Dispatch (round 22): the dense V=1 case runs the ORIGINAL flat tick
        scan below, untouched — its compiled HLO is byte-identical to
        before interleaving existed. virtual_stages > 1 and/or MoE configs
        run the unrolled interleaved machine (which handles V=1 too; MoE
        needs its aux cotangent channel, so V=1 MoE also routes there
        rather than growing the scan)."""
        self._check_moe(cfg)  # fail loudly from any entry point (ADVICE r5 #1)
        self._check_interleave(cfg)
        if cfg.virtual_stages == 1 and cfg.num_experts == 0:
            return self._flat_value_and_grad(params, cfg, batch, targets, rng)
        return self._interleaved_value_and_grad(params, cfg, batch, targets, rng)

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        """Eval: V=1 reuses the parent's forward-only GPipe schedule; V > 1
        params live in the interleaved chunk layout the GPipe scan cannot
        walk, so eval runs the forward-only interleaved tick program."""
        self._check_moe(cfg)
        self._check_interleave(cfg)
        if cfg.virtual_stages == 1:
            return super().loss_fn(
                params, cfg, batch, targets,
                with_accuracy=with_accuracy, rng=rng, aux_out=aux_out,
            )
        return self._interleaved_eval(
            params, cfg, batch, targets,
            with_accuracy=with_accuracy, rng=rng, aux_out=aux_out,
        )

    def _flat_value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        """The original flat 1F1B tick scan (every tick runs both phases;
        bubble (2S-2)/(M+2S-2)) — the `--virtual_stages 1` dense path."""
        num_stages, num_micro = self.num_stages, self.num_microbatches
        padded = self.padded_layers(cfg.num_layers)
        per_stage = padded // num_stages
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        if stack != padded:
            raise ValueError(
                f"stacked layer axis is {stack} but num_layers="
                f"{cfg.num_layers} on {num_stages} stages needs {padded} — "
                f"initialize through create_train_state(..., strategy=...)"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {num_micro} "
                f"microbatches x {self.data_size} data shards"
            )
        micro = global_batch // num_micro
        seq = batch["input_ids"].shape[1]

        def split(x):
            return x.reshape(num_micro, micro, *x.shape[1:])

        inputs = split(batch["input_ids"])
        positions = split(batch["position_ids"])
        masks = split(batch["mask"])
        tgts = split(targets)

        data = "data" if "data" in self.mesh.axis_names else None
        batch_spec = P(None, data)
        layers = params["layers"]
        rest = {k: v for k, v in params.items() if k != "layers"}

        v_pad = cfg.padded_vocab_size
        # Same predicate as state_sharding/loss_fn, so the in/out specs
        # below always match the arrays' actual placement.
        shard_vocab = (
            self._vocab_spec(
                ("embeddings", "token"), rest["embeddings"]["token"].shape
            )
            is not None
        )
        v_local = v_pad // num_stages if shard_vocab else v_pad

        def rest_spec(path, leaf):
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return vocab if vocab is not None else P()

        rest_specs = jax.tree_util.tree_map_with_path(rest_spec, rest)
        # Gradients of vocab-sharded leaves stay stage-local (each stage
        # owns its slice); replicated leaves' contributions are gated to
        # one stage and psum'd. Derived from rest_specs (single source of
        # truth) — decided OUTSIDE shard_map, which needs global shapes.
        rest_sharded = jax.tree.map(
            lambda spec: spec != P(), rest_specs,
            is_leaf=lambda x: isinstance(x, P),
        )

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P("stage"), rest_specs, batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), P(), P("stage"), rest_specs),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = num_stages - 1
            depth = 2 * num_stages  # ring depth: in-flight micros < 2S - 1
            mb_local = inputs.shape[1]
            # micro m forwards at stage s on tick m+s; the last stage
            # backwards it the same tick; the cotangent reaches stage 0 at
            # tick (m + S - 1) + (S - 1) — so the last backward retires at
            # tick M + 2S - 3, i.e. M + 2S - 2 ticks total.
            ticks = num_micro + 2 * num_stages - 2

            if padded == cfg.num_layers:
                active = None
            else:
                active = (
                    stage * per_stage + jnp.arange(per_stage)
                ) < cfg.num_layers

            def key_for(mi):
                if rng is None:
                    return None
                lin = stage * num_micro + mi
                if data is not None:
                    lin = lin * self.data_size + jax.lax.axis_index(data)
                return jax.random.fold_in(rng, lin)

            def stage_trunk(lp, x_in, mask_in, mi):
                """One stage's trunk slice for micro `mi` — collective-free,
                so its vjp can replay a DIFFERENT micro per stage."""
                k = key_for(mi)
                return gpt.apply_decoder_layers(
                    lp, cfg, x_in, mask_in,
                    rng=k, deterministic=k is None, active=active,
                )

            def sharded_ingest(mi):
                """Distributed lookup: every stage contributes its vocab
                slice, one exact psum assembles the embedding. `mi` must be
                tick-uniform (the psum is collective)."""
                rel = inputs[mi] - stage * v_local
                ok = (rel >= 0) & (rel < v_local)
                part = jnp.where(
                    ok[..., None],
                    jnp.take(
                        rest_params["embeddings"]["token"],
                        jnp.where(ok, rel, 0),
                        axis=0,
                    ),
                    0.0,
                )
                emb = jax.lax.psum(part, "stage") + jnp.take(
                    rest_params["embeddings"]["position"], positions[mi], axis=0
                )
                return emb.astype(cfg.compute_dtype)

            def zeros_rest():
                return jax.tree.map(jnp.zeros_like, rest_params)

            def add_emb_grads(grp, d_tok, d_pos):
                return {
                    **grp,
                    "embeddings": {
                        "token": grp["embeddings"]["token"] + d_tok,
                        "position": grp["embeddings"]["position"] + d_pos,
                    },
                }

            perm_f = [(i, i + 1) for i in range(num_stages - 1)]
            perm_b = [(i + 1, i) for i in range(num_stages - 1)]

            def tick(carry, t):
                (x_fwd, mask_fwd, fvalid, dy_bwd, bvalid, xbuf, maskbuf,
                 fcnt, bcnt, glp, grp, loss_sum, cnt_sum) = carry
                is0 = stage == 0
                at_last = stage == last

                # ---- forward unit: one primal trunk step of micro `fcnt`.
                # Stage 0 ingests through the embeddings; the saved stage
                # input is POST-ingest, so backward replay never re-embeds.
                okf = jnp.where(is0, fcnt < num_micro, fvalid)
                mi_f = jnp.clip(fcnt, 0, num_micro - 1)
                mask_in = jnp.where(is0, masks[mi_f], mask_fwd)
                if shard_vocab:
                    # stage 0's forward micro is `t` (its fcnt advances every
                    # tick until exhausted), a tick-uniform index — so every
                    # stage participates in the ingest psum for the same
                    # logical micro. The predicate is tick-uniform too, so
                    # the 2S-2 drain ticks skip the gather + psum entirely
                    # (collectives inside a uniform cond stay matched).
                    x_eff = jax.lax.cond(
                        t < num_micro,
                        lambda: jnp.where(is0, sharded_ingest(t), x_fwd),
                        lambda: x_fwd,
                    )
                else:
                    x_eff = jax.lax.cond(
                        is0,
                        lambda: gpt.apply_embeddings(
                            rest_params, cfg, inputs[mi_f], positions[mi_f]
                        ),
                        lambda: x_fwd,
                    )
                y = stage_trunk(local_layers, x_eff, mask_in, mi_f)
                slot = fcnt % depth
                # gate the single written slot, not a select over the whole
                # depth-2S buffer (keeps the carry update in place)
                xbuf = xbuf.at[slot].set(jnp.where(okf, x_eff, xbuf[slot]))
                maskbuf = maskbuf.at[slot].set(
                    jnp.where(okf, mask_in, maskbuf[slot])
                )
                fcnt = fcnt + okf.astype(fcnt.dtype)

                # ---- head + CE for the micro reaching the last stage this
                # tick. Its primal output is the loss contribution; its
                # pullback yields the head grads AND the trunk cotangent the
                # last stage consumes the same tick (the 1F1B self-trigger).
                okb_last = bcnt < fcnt  # last stage's backward validity
                if shard_vocab:
                    # tick-uniform micro t-(S-1): collectives inside match.
                    idx_h = t - (num_stages - 1)
                    okh = (idx_h >= 0) & (idx_h < num_micro)
                    mi_h = jnp.clip(idx_h, 0, num_micro - 1)

                    def head_block(_):
                        y_b = jax.lax.psum(
                            jnp.where(at_last, y, jnp.zeros_like(y)), "stage"
                        )
                        tgt_h = tgts[mi_h]
                        offset = stage * v_local

                        def f(norm_p, lm_k, yy):
                            (l, c), _ = _vocab_slice_ce(
                                norm_p, lm_k, yy, tgt_h, offset, v_local, cfg
                            )
                            return l, c

                        (l_s, c_s), pull_h = jax.vjp(
                            f,
                            rest_params["norm_out"],
                            rest_params["lm_head"]["kernel"],
                            y_b,
                        )
                        # vocab_parallel_ce's backward psums the incoming
                        # cotangent over `stage`; gating it to stage 0 makes
                        # that psum recover exactly 1.
                        dl = jnp.where(is0, 1.0, 0.0).astype(jnp.float32)
                        dnorm, dlm, dyb = pull_h((dl, jnp.float32(0)))
                        # f consumed the broadcast y on every stage, so the
                        # true cotangent at the last stage's y is the sum of
                        # every stage's dyb (the psum_bcast transpose).
                        dy_l = jax.lax.psum(dyb, "stage")
                        return l_s, c_s, dnorm, dlm, dy_l

                    def no_head(_):
                        return (
                            jnp.float32(0), jnp.float32(0),
                            jax.tree.map(jnp.zeros_like, rest_params["norm_out"]),
                            jnp.zeros_like(rest_params["lm_head"]["kernel"]),
                            jnp.zeros_like(y),
                        )

                    l_s, c_s, dnorm, dlm, dy_head = jax.lax.cond(
                        okh, head_block, no_head, None
                    )
                    # l_s/c_s are replicated (collective CE); accumulate on
                    # stage 0 only so the final all-axes psum counts them once
                    # per data shard.
                    loss_sum = loss_sum + jnp.where(okh & is0, l_s, 0.0)
                    cnt_sum = cnt_sum + jnp.where(okh & is0, c_s, 0.0)
                    grp = {
                        **grp,
                        "norm_out": jax.tree.map(
                            jnp.add, grp["norm_out"], dnorm
                        ),
                        "lm_head": {
                            "kernel": grp["lm_head"]["kernel"] + dlm
                        },
                    }
                else:
                    mi_b_last = jnp.clip(bcnt, 0, num_micro - 1)

                    def head_block(_):
                        def f(rp, yy):
                            logits = gpt.apply_head(rp, cfg, yy)
                            return cross_entropy_sum(logits, tgts[mi_b_last])

                        (l_s, c_s), pull_h = jax.vjp(f, rest_params, y)
                        dl = jnp.where(okb_last, 1.0, 0.0).astype(jnp.float32)
                        drp, dy_l = pull_h((dl, jnp.float32(0)))
                        return (
                            jnp.where(okb_last, l_s, 0.0),
                            jnp.where(okb_last, c_s, 0.0),
                            drp, dy_l,
                        )

                    def no_head(_):
                        return (
                            jnp.float32(0), jnp.float32(0),
                            zeros_rest(), jnp.zeros_like(y),
                        )

                    # no collectives inside -> the non-uniform predicate is
                    # safe; only the last stage pays the head compute.
                    l_s, c_s, drp_head, dy_head = jax.lax.cond(
                        at_last, head_block, no_head, None
                    )
                    loss_sum = loss_sum + l_s
                    cnt_sum = cnt_sum + c_s
                    grp = jax.tree.map(jnp.add, grp, drp_head)

                # ---- backward unit: remat vjp of the trunk for micro
                # `bcnt` (the last stage self-triggers: its cotangent is
                # dy_head from this very tick).
                okb = jnp.where(at_last, okb_last, bvalid)
                mi_b = jnp.clip(bcnt, 0, num_micro - 1)
                slot_b = bcnt % depth
                f = lambda lp, x: stage_trunk(lp, x, maskbuf[slot_b], mi_b)
                _, pull = jax.vjp(f, local_layers, xbuf[slot_b])
                dy_eff = jnp.where(
                    okb, jnp.where(at_last, dy_head, dy_bwd), 0
                ).astype(cfg.compute_dtype)
                dlp, dx = pull(dy_eff)
                glp = jax.tree.map(jnp.add, glp, dlp)
                bcnt = bcnt + okb.astype(bcnt.dtype)

                # ---- embedding-table transpose: stage 0's trunk-input
                # cotangent IS d(embedding) for the micro stage 0 retires.
                dx_gated = jnp.where(okb & is0, dx, 0).astype(jnp.float32)
                if shard_vocab:
                    # stage 0 retires micro t-(2S-2) — tick-uniform, so one
                    # psum broadcasts d(emb) and every stage scatter-adds its
                    # own vocab slice of the table gradient.
                    idx_b0 = t - (2 * num_stages - 2)
                    mi_e = jnp.clip(idx_b0, 0, num_micro - 1)
                    d_emb = jax.lax.psum(dx_gated, "stage")
                    rel = inputs[mi_e] - stage * v_local
                    ok = (rel >= 0) & (rel < v_local)
                    d_tok = (
                        jnp.zeros_like(grp["embeddings"]["token"])
                        .at[jnp.where(ok, rel, v_local)]
                        .add(
                            jnp.where(ok[..., None], d_emb, 0.0),
                            mode="drop",
                        )
                    )
                    d_pos = (
                        jnp.zeros_like(grp["embeddings"]["position"])
                        .at[positions[mi_e]]
                        .add(d_emb)
                    )
                    # position table is replicated (final psum over stage):
                    # count its contribution once.
                    grp = add_emb_grads(
                        grp, d_tok, jnp.where(is0, d_pos, 0.0)
                    )
                else:

                    def emb_bwd(_):
                        d_tok = (
                            jnp.zeros_like(grp["embeddings"]["token"])
                            .at[inputs[mi_b]]
                            .add(dx_gated)
                        )
                        d_pos = (
                            jnp.zeros_like(grp["embeddings"]["position"])
                            .at[positions[mi_b]]
                            .add(dx_gated)
                        )
                        return d_tok, d_pos

                    def no_emb(_):
                        return (
                            jnp.zeros_like(grp["embeddings"]["token"]),
                            jnp.zeros_like(grp["embeddings"]["position"]),
                        )

                    d_tok, d_pos = jax.lax.cond(is0, emb_bwd, no_emb, None)
                    grp = add_emb_grads(grp, d_tok, d_pos)

                # ---- ship: activations forward, cotangents backward ----
                x_next = jax.lax.ppermute(y, "stage", perm_f)
                mask_next = jax.lax.ppermute(mask_in, "stage", perm_f)
                fvalid_next = jax.lax.ppermute(okf, "stage", perm_f)
                dy_next = jax.lax.ppermute(dx, "stage", perm_b)
                bvalid_next = jax.lax.ppermute(okb, "stage", perm_b)
                return (
                    (x_next, mask_next, fvalid_next, dy_next, bvalid_next,
                     xbuf, maskbuf, fcnt, bcnt, glp, grp, loss_sum, cnt_sum),
                    None,
                )

            zeros_x = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            carry0 = (
                zeros_x,
                jnp.zeros((mb_local, seq), jnp.bool_),
                jnp.bool_(False),
                zeros_x,
                jnp.bool_(False),
                jnp.zeros((depth, mb_local, seq, cfg.dim), cfg.compute_dtype),
                jnp.zeros((depth, mb_local, seq), jnp.bool_),
                jnp.int32(0),
                jnp.int32(0),
                jax.tree.map(jnp.zeros_like, local_layers),
                jax.tree.map(jnp.zeros_like, rest_params),
                jnp.float32(0),
                jnp.float32(0),
            )
            final_carry, _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
            glp, grp, loss_sum, cnt_sum = final_carry[-4:]

            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            cnt_sum = jax.lax.psum(cnt_sum, axes)
            # layer grads are stage-local; sum row-shards over `data`.
            # Vocab-sharded leaves (token table / lm_head kernel) likewise
            # stay stage-local; replicated rest leaves were gated to a
            # single stage's contribution and psum over every axis.
            if data is not None:
                glp = jax.tree.map(lambda g: jax.lax.psum(g, data), glp)

            def reduce_rest(g, is_sharded):
                if is_sharded:
                    return jax.lax.psum(g, data) if data is not None else g
                return jax.lax.psum(g, axes)

            grp = jax.tree.map(reduce_rest, grp, rest_sharded)
            return loss_sum, cnt_sum, glp, grp

        loss_sum, count, glp, grp = schedule(
            layers, rest, inputs, positions, masks, tgts
        )
        denom = jnp.maximum(count, 1.0)
        grads = {**grp, "layers": glp}
        grads = jax.tree.map(lambda g: (g / denom).astype(g.dtype), grads)
        return loss_sum / denom, grads

    # -- interleaved virtual stages (round 22, ROADMAP #5) -----------------
    #
    # cfg.virtual_stages = V > 1: device d owns V non-contiguous chunks
    # d, d+S, ..., d+(V-1)S of the layer stack (prepare_params lays the
    # stack out so P("stage") hands each device its chunks as one slab).
    # The tick program comes from tpukit/pipeline_schedule.py — a STATIC
    # per-tick, per-device job table the machine UNROLLS (no scan): each
    # tick traces only the phases it actually runs, so pure-forward
    # warm-up and pure-backward drain ticks cost one phase, and the
    # schedule's idle-work accounting (bench.py `pipe_interleave`) prices
    # exactly what the compiled program executes. Static tables also mean
    # validity is compile-time — no ok-flags ship with the payloads, and
    # the ONLY collectives are one forward ppermute per shipping tick, one
    # backward ppermute per shipping tick, and the vocab-sharded
    # ingest/head/emb psums at their (static) ticks, so the closed-form
    # comm plan (`pipe_comm`) counts the HLO exactly.

    def _interleave_prelude(self, params, cfg: gpt.GPTConfig, batch, targets):
        """Shared shape/spec plumbing for the interleaved machines —
        mirrors the flat machine's prelude with the V-aware stack check."""
        S, M = self.num_stages, self.num_microbatches
        V = cfg.virtual_stages
        padded = self.padded_layers(cfg.num_layers, V)
        per_chunk = padded // (S * V)
        stack = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        if stack != padded:
            raise ValueError(
                f"stacked layer axis is {stack} but num_layers="
                f"{cfg.num_layers} with virtual_stages={V} on {S} stages "
                f"needs {padded} (identity-padded, chunk-permuted) — "
                f"initialize through create_train_state(..., strategy=...)"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {M} "
                f"microbatches x {self.data_size} data shards"
            )
        micro = global_batch // M

        def split(x):
            return x.reshape(M, micro, *x.shape[1:])

        data = "data" if "data" in self.mesh.axis_names else None
        rest = {k: v for k, v in params.items() if k != "layers"}
        v_pad = cfg.padded_vocab_size
        shard_vocab = (
            self._vocab_spec(
                ("embeddings", "token"), rest["embeddings"]["token"].shape
            )
            is not None
        )

        def rest_spec(path, leaf):
            vocab = self._vocab_spec(_path_names(path), leaf.shape)
            return vocab if vocab is not None else P()

        rest_specs = jax.tree_util.tree_map_with_path(rest_spec, rest)
        rest_sharded = jax.tree.map(
            lambda spec: spec != P(), rest_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        return dict(
            S=S, V=V, M=M, padded=padded, per_chunk=per_chunk,
            seq=batch["input_ids"].shape[1],
            inputs=split(batch["input_ids"]),
            positions=split(batch["position_ids"]),
            masks=split(batch["mask"]),
            tgts=split(targets),
            data=data, batch_spec=P(None, data),
            layers=params["layers"], rest=rest,
            rest_specs=rest_specs, rest_sharded=rest_sharded,
            shard_vocab=shard_vocab,
            v_local=v_pad // S if shard_vocab else v_pad,
            v_pad=v_pad,
        )

    def _interleaved_value_and_grad(
        self, params, cfg: gpt.GPTConfig, batch, targets, rng=None
    ):
        """The unrolled interleaved-1F1B machine: explicit-vjp training
        over the static tick table, V >= 1, dense or MoE (pallas
        dispatch). Same contract as _flat_value_and_grad."""
        cfg = self._moe_cfg(cfg)
        env = self._interleave_prelude(params, cfg, batch, targets)
        S, V, M = env["S"], env["V"], env["M"]
        per_chunk, seq = env["per_chunk"], env["seq"]
        data, shard_vocab = env["data"], env["shard_vocab"]
        v_local = env["v_local"]
        inputs_a, positions_a = env["inputs"], env["positions"]
        masks_a, tgts_a = env["masks"], env["tgts"]
        rest_specs, rest_sharded = env["rest_specs"], env["rest_sharded"]
        moe = cfg.num_experts > 0
        sched = cached_schedule(S, V, M)
        depth = sched.depth
        padded = env["padded"]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(
                P("stage"), rest_specs,
                env["batch_spec"], env["batch_spec"],
                env["batch_spec"], env["batch_spec"],
            ),
            out_specs=(P(), P(), P("stage"), rest_specs),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = S - 1
            is0 = stage == 0
            at_last = stage == last
            mb_local = inputs.shape[1]

            # Device-local chunk stack: [V, per_chunk, ...]. Chunk c on
            # this device is global chunk c*S + stage, covering natural
            # layers [(c*S + stage)*per_chunk, +per_chunk).
            chunks = jax.tree.map(
                lambda l: l.reshape(V, per_chunk, *l.shape[1:]), local_layers
            )
            if padded == cfg.num_layers:
                active_all = None
            else:
                g_of_c = jnp.arange(V) * S + stage  # [V]
                layer_idx = (
                    g_of_c[:, None] * per_chunk + jnp.arange(per_chunk)[None, :]
                )
                active_all = layer_idx < cfg.num_layers  # [V, per_chunk]

            def key_for(c, mi):
                # keyed by the GLOBAL chunk id and micro, so the backward
                # replay of (g, m) sees exactly the forward's dropout mask
                # (and V=1 reproduces the flat machine's keys: g == stage)
                if rng is None:
                    return None
                lin = (c * S + stage) * M + mi
                if data is not None:
                    lin = lin * self.data_size + jax.lax.axis_index(data)
                return jax.random.fold_in(rng, lin)

            def chunk_call(c, x_in, mi, want_aux):
                """One chunk's trunk (collective-free). `c`/`mi` are
                traced per-device scalars from the tick table."""
                lp = jax.tree.map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, c, 0, keepdims=False
                    ),
                    chunks,
                )
                act = (
                    None
                    if active_all is None
                    else jax.lax.dynamic_index_in_dim(
                        active_all, c, 0, keepdims=False
                    )
                )
                k = key_for(c, mi)
                al: list = [] if want_aux else None
                y = gpt.apply_decoder_layers(
                    lp, cfg, x_in, masks[mi],
                    rng=k, deterministic=k is None, active=act, aux_out=al,
                )
                if want_aux:
                    return y, (al[0] if al else jnp.float32(0))
                return y

            def chunk_vjp(c, x_in, mi, dy, d_aux):
                """Remat backward of chunk `c` micro `mi`: recompute the
                trunk from the saved chunk input, transpose with the
                arrived cotangent (plus the aux cotangent for MoE)."""
                lp = jax.tree.map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, c, 0, keepdims=False
                    ),
                    chunks,
                )
                act = (
                    None
                    if active_all is None
                    else jax.lax.dynamic_index_in_dim(
                        active_all, c, 0, keepdims=False
                    )
                )
                k = key_for(c, mi)

                if moe:

                    def f(lp_, x_):
                        al: list = []
                        y = gpt.apply_decoder_layers(
                            lp_, cfg, x_, masks[mi],
                            rng=k, deterministic=k is None, active=act,
                            aux_out=al,
                        )
                        return y, al[0] if al else jnp.float32(0)

                    _, pull = jax.vjp(f, lp, x_in)
                    return pull((dy, d_aux))

                def f(lp_, x_):
                    return gpt.apply_decoder_layers(
                        lp_, cfg, x_, masks[mi],
                        rng=k, deterministic=k is None, active=act,
                    )

                _, pull = jax.vjp(f, lp, x_in)
                return pull(dy)

            def sharded_ingest(mi):
                # mi is a STATIC micro index (tick.ingest) — every device
                # participates in the psum for the same logical micro.
                rel = inputs[mi] - stage * v_local
                ok = (rel >= 0) & (rel < v_local)
                part = jnp.where(
                    ok[..., None],
                    jnp.take(
                        rest_params["embeddings"]["token"],
                        jnp.where(ok, rel, 0),
                        axis=0,
                    ),
                    0.0,
                )
                emb = jax.lax.psum(part, "stage") + jnp.take(
                    rest_params["embeddings"]["position"], positions[mi], axis=0
                )
                return emb.astype(cfg.compute_dtype)

            def zeros_rest():
                return jax.tree.map(jnp.zeros_like, rest_params)

            def add_emb_grads(g, d_tok, d_pos):
                return {
                    **g,
                    "embeddings": {
                        "token": g["embeddings"]["token"] + d_tok,
                        "position": g["embeddings"]["position"] + d_pos,
                    },
                }

            def dev_i32(entries, pos):
                return jnp.asarray(
                    [0 if e is None else e[pos] for e in entries], jnp.int32
                )[stage]

            def dev_ok(entries):
                return jnp.asarray([e is not None for e in entries])[stage]

            ring_f = [(i, (i + 1) % S) for i in range(S)]
            ring_b = [(i, (i - 1) % S) for i in range(S)]

            xbuf = jnp.zeros(
                (V, depth, mb_local, seq, cfg.dim), cfg.compute_dtype
            )
            dybuf = jnp.zeros_like(xbuf)
            glp = jax.tree.map(jnp.zeros_like, chunks)
            grp = zeros_rest()
            loss_sum = jnp.float32(0)
            cnt_sum = jnp.float32(0)
            y_wire = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            dx_wire = jnp.zeros_like(y_wire)

            if moe:
                # Aux cotangent: the objective is CE_sum/denom +
                # aw * sum_{g,m} aux / (M * data_size); grads accumulate
                # raw and divide by denom once at the end, so the aux seed
                # is aw * denom / (M * data_size). denom is known up
                # front: the head counts every valid target exactly once.
                cnt_local = jnp.sum(tgts != -100).astype(jnp.float32)
                cnt_global = (
                    jax.lax.psum(cnt_local, data) if data else cnt_local
                )
                alpha = (
                    cfg.moe_aux_weight
                    * jnp.maximum(cnt_global, 1.0)
                    / (M * self.data_size)
                )

            for tk in sched.ticks:
                # -- arrivals: payloads shipped at the end of the previous
                # tick land in their pre-assigned slots (static targets;
                # devices without an arrival write nothing).
                if any(e is not None for e in tk.recv_fwd):
                    c_r, s_r = dev_i32(tk.recv_fwd, 0), dev_i32(tk.recv_fwd, 1)
                    ok_r = dev_ok(tk.recv_fwd)
                    xbuf = xbuf.at[c_r, s_r].set(
                        jnp.where(ok_r, y_wire, xbuf[c_r, s_r])
                    )
                if any(e is not None for e in tk.recv_bwd):
                    c_r, s_r = dev_i32(tk.recv_bwd, 0), dev_i32(tk.recv_bwd, 1)
                    ok_r = dev_ok(tk.recv_bwd)
                    dybuf = dybuf.at[c_r, s_r].set(
                        jnp.where(ok_r, dx_wire, dybuf[c_r, s_r])
                    )

                # -- forward phase (traced only for forward-phase ticks) --
                if tk.has_fwd:
                    if tk.ingest >= 0:
                        slot0 = tk.fwd[0][2]  # device 0's job, static
                        if shard_vocab:
                            emb = sharded_ingest(tk.ingest)
                        else:
                            emb = jax.lax.cond(
                                is0,
                                lambda m=tk.ingest: gpt.apply_embeddings(
                                    rest_params, cfg, inputs[m], positions[m]
                                ),
                                lambda: jnp.zeros(
                                    (mb_local, seq, cfg.dim),
                                    cfg.compute_dtype,
                                ),
                            )
                        xbuf = xbuf.at[0, slot0].set(
                            jnp.where(is0, emb, xbuf[0, slot0])
                        )
                    fc, fm = dev_i32(tk.fwd, 0), dev_i32(tk.fwd, 1)
                    fs = dev_i32(tk.fwd, 2)
                    y = chunk_call(fc, xbuf[fc, fs], fm, want_aux=False)

                    if tk.head >= 0:
                        # the last device's job this tick IS chunk G-1 of
                        # micro tk.head; its head cotangent stashes at the
                        # (static) head_slot for the same-tick or later
                        # backward (the 1F1B self-trigger).
                        if shard_vocab:
                            y_b = jax.lax.psum(
                                jnp.where(at_last, y, jnp.zeros_like(y)),
                                "stage",
                            )
                            tgt_h = tgts[tk.head]
                            offset = stage * v_local

                            def f(norm_p, lm_k, yy):
                                (l, c), _ = _vocab_slice_ce(
                                    norm_p, lm_k, yy, tgt_h, offset,
                                    v_local, cfg,
                                )
                                return l, c

                            (l_s, c_s), pull_h = jax.vjp(
                                f,
                                rest_params["norm_out"],
                                rest_params["lm_head"]["kernel"],
                                y_b,
                            )
                            dl = jnp.where(is0, 1.0, 0.0).astype(jnp.float32)
                            dnorm, dlm, dyb = pull_h((dl, jnp.float32(0)))
                            dy_head = jax.lax.psum(dyb, "stage")
                            loss_sum = loss_sum + jnp.where(is0, l_s, 0.0)
                            cnt_sum = cnt_sum + jnp.where(is0, c_s, 0.0)
                            grp = {
                                **grp,
                                "norm_out": jax.tree.map(
                                    jnp.add, grp["norm_out"], dnorm
                                ),
                                "lm_head": {
                                    "kernel": grp["lm_head"]["kernel"] + dlm
                                },
                            }
                        else:

                            def head_block(_):
                                def f(rp, yy):
                                    logits = gpt.apply_head(rp, cfg, yy)
                                    return cross_entropy_sum(
                                        logits, tgts[tk.head]
                                    )

                                (l_s, c_s), pull_h = jax.vjp(
                                    f, rest_params, y
                                )
                                drp, dy_l = pull_h(
                                    (jnp.float32(1), jnp.float32(0))
                                )
                                return l_s, c_s, drp, dy_l

                            def no_head(_):
                                return (
                                    jnp.float32(0), jnp.float32(0),
                                    zeros_rest(), jnp.zeros_like(y),
                                )

                            l_s, c_s, drp_head, dy_head = jax.lax.cond(
                                at_last, head_block, no_head, None
                            )
                            loss_sum = loss_sum + l_s
                            cnt_sum = cnt_sum + c_s
                            grp = jax.tree.map(jnp.add, grp, drp_head)
                        dybuf = dybuf.at[V - 1, tk.head_slot].set(
                            jnp.where(
                                at_last,
                                dy_head.astype(dybuf.dtype),
                                dybuf[V - 1, tk.head_slot],
                            )
                        )

                    if tk.ship_fwd:
                        y_wire = jax.lax.ppermute(y, "stage", ring_f)

                # -- backward phase (traced only for backward-phase ticks)
                if tk.has_bwd:
                    bc, bm = dev_i32(tk.bwd, 0), dev_i32(tk.bwd, 1)
                    bs = dev_i32(tk.bwd, 2)
                    bok = dev_ok(tk.bwd)
                    dy_eff = jnp.where(bok, dybuf[bc, bs], 0).astype(
                        cfg.compute_dtype
                    )
                    if moe:
                        d_aux = jnp.where(bok, alpha, 0.0)
                        dlp, dx = chunk_vjp(bc, xbuf[bc, bs], bm, dy_eff, d_aux)
                    else:
                        dlp, dx = chunk_vjp(bc, xbuf[bc, bs], bm, dy_eff, None)
                    # a zero cotangent makes dlp exactly zero (a vjp is
                    # linear), so jobless devices scatter nothing real
                    glp = jax.tree.map(
                        lambda g, d: g.at[bc].add(d), glp, dlp
                    )

                    if tk.emb >= 0:
                        # device 0's backward this tick is (chunk 0, micro
                        # tk.emb): its input cotangent IS d(embedding).
                        dx_gated = jnp.where(bok & is0, dx, 0).astype(
                            jnp.float32
                        )
                        e = tk.emb
                        if shard_vocab:
                            d_emb = jax.lax.psum(dx_gated, "stage")
                            rel = inputs[e] - stage * v_local
                            ok = (rel >= 0) & (rel < v_local)
                            d_tok = (
                                jnp.zeros_like(grp["embeddings"]["token"])
                                .at[jnp.where(ok, rel, v_local)]
                                .add(
                                    jnp.where(ok[..., None], d_emb, 0.0),
                                    mode="drop",
                                )
                            )
                            d_pos = (
                                jnp.zeros_like(grp["embeddings"]["position"])
                                .at[positions[e]]
                                .add(d_emb)
                            )
                            grp = add_emb_grads(
                                grp, d_tok, jnp.where(is0, d_pos, 0.0)
                            )
                        else:

                            def emb_bwd(_):
                                d_tok = (
                                    jnp.zeros_like(
                                        grp["embeddings"]["token"]
                                    )
                                    .at[inputs[e]]
                                    .add(dx_gated)
                                )
                                d_pos = (
                                    jnp.zeros_like(
                                        grp["embeddings"]["position"]
                                    )
                                    .at[positions[e]]
                                    .add(dx_gated)
                                )
                                return d_tok, d_pos

                            def no_emb(_):
                                return (
                                    jnp.zeros_like(
                                        grp["embeddings"]["token"]
                                    ),
                                    jnp.zeros_like(
                                        grp["embeddings"]["position"]
                                    ),
                                )

                            d_tok, d_pos = jax.lax.cond(
                                is0, emb_bwd, no_emb, None
                            )
                            grp = add_emb_grads(grp, d_tok, d_pos)

                    if tk.ship_bwd:
                        dx_wire = jax.lax.ppermute(
                            dx.astype(cfg.compute_dtype), "stage", ring_b
                        )

            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            cnt_sum = jax.lax.psum(cnt_sum, axes)
            glp = jax.tree.map(
                lambda g: g.reshape(V * per_chunk, *g.shape[2:]), glp
            )
            if data is not None:
                glp = jax.tree.map(lambda g: jax.lax.psum(g, data), glp)

            def reduce_rest(g, is_sharded):
                if is_sharded:
                    return jax.lax.psum(g, data) if data is not None else g
                return jax.lax.psum(g, axes)

            grp = jax.tree.map(reduce_rest, grp, rest_sharded)
            return loss_sum, cnt_sum, glp, grp

        loss_sum, count, glp, grp = schedule(
            env["layers"], env["rest"], inputs_a, positions_a, masks_a, tgts_a
        )
        denom = jnp.maximum(count, 1.0)
        grads = {**grp, "layers": glp}
        grads = jax.tree.map(lambda g: (g / denom).astype(g.dtype), grads)
        return loss_sum / denom, grads

    def _interleaved_eval(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        """Forward-only interleaved schedule (eval at V > 1): the same
        tick skeleton with include_backward=False — fwd + head units only,
        with the parent's global-argmax accuracy idioms."""
        cfg = self._moe_cfg(cfg)
        env = self._interleave_prelude(params, cfg, batch, targets)
        S, V, M = env["S"], env["V"], env["M"]
        per_chunk, seq = env["per_chunk"], env["seq"]
        data, shard_vocab = env["data"], env["shard_vocab"]
        v_local, v_pad = env["v_local"], env["v_pad"]
        moe_aux = cfg.num_experts > 0 and aux_out is not None
        sched = cached_schedule(S, V, M, include_backward=False)
        depth = sched.depth
        padded = env["padded"]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(
                P("stage"), env["rest_specs"],
                env["batch_spec"], env["batch_spec"],
                env["batch_spec"], env["batch_spec"],
            ),
            out_specs=(P(),) * (4 if moe_aux else 3),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = S - 1
            is0 = stage == 0
            at_last = stage == last
            mb_local = inputs.shape[1]

            chunks = jax.tree.map(
                lambda l: l.reshape(V, per_chunk, *l.shape[1:]), local_layers
            )
            if padded == cfg.num_layers:
                active_all = None
            else:
                g_of_c = jnp.arange(V) * S + stage
                layer_idx = (
                    g_of_c[:, None] * per_chunk + jnp.arange(per_chunk)[None, :]
                )
                active_all = layer_idx < cfg.num_layers

            def key_for(c, mi):
                if rng is None:
                    return None
                lin = (c * S + stage) * M + mi
                if data is not None:
                    lin = lin * self.data_size + jax.lax.axis_index(data)
                return jax.random.fold_in(rng, lin)

            def chunk_call(c, x_in, mi, want_aux):
                lp = jax.tree.map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, c, 0, keepdims=False
                    ),
                    chunks,
                )
                act = (
                    None
                    if active_all is None
                    else jax.lax.dynamic_index_in_dim(
                        active_all, c, 0, keepdims=False
                    )
                )
                k = key_for(c, mi)
                al: list = [] if want_aux else None
                y = gpt.apply_decoder_layers(
                    lp, cfg, x_in, masks[mi],
                    rng=k, deterministic=k is None, active=act, aux_out=al,
                )
                if want_aux:
                    return y, (al[0] if al else jnp.float32(0))
                return y

            def sharded_ingest(mi):
                rel = inputs[mi] - stage * v_local
                ok = (rel >= 0) & (rel < v_local)
                part = jnp.where(
                    ok[..., None],
                    jnp.take(
                        rest_params["embeddings"]["token"],
                        jnp.where(ok, rel, 0),
                        axis=0,
                    ),
                    0.0,
                )
                emb = jax.lax.psum(part, "stage") + jnp.take(
                    rest_params["embeddings"]["position"], positions[mi], axis=0
                )
                return emb.astype(cfg.compute_dtype)

            def dev_i32(entries, pos):
                return jnp.asarray(
                    [0 if e is None else e[pos] for e in entries], jnp.int32
                )[stage]

            def dev_ok(entries):
                return jnp.asarray([e is not None for e in entries])[stage]

            ring_f = [(i, (i + 1) % S) for i in range(S)]

            xbuf = jnp.zeros(
                (V, depth, mb_local, seq, cfg.dim), cfg.compute_dtype
            )
            loss_sum = jnp.float32(0)
            cnt_sum = jnp.float32(0)
            correct = jnp.float32(0)
            aux_sum = jnp.float32(0)
            y_wire = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)

            for tk in sched.ticks:
                if any(e is not None for e in tk.recv_fwd):
                    c_r, s_r = dev_i32(tk.recv_fwd, 0), dev_i32(tk.recv_fwd, 1)
                    ok_r = dev_ok(tk.recv_fwd)
                    xbuf = xbuf.at[c_r, s_r].set(
                        jnp.where(ok_r, y_wire, xbuf[c_r, s_r])
                    )
                if not tk.has_fwd:
                    continue
                if tk.ingest >= 0:
                    slot0 = tk.fwd[0][2]
                    if shard_vocab:
                        emb = sharded_ingest(tk.ingest)
                    else:
                        emb = jax.lax.cond(
                            is0,
                            lambda m=tk.ingest: gpt.apply_embeddings(
                                rest_params, cfg, inputs[m], positions[m]
                            ),
                            lambda: jnp.zeros(
                                (mb_local, seq, cfg.dim), cfg.compute_dtype
                            ),
                        )
                    xbuf = xbuf.at[0, slot0].set(
                        jnp.where(is0, emb, xbuf[0, slot0])
                    )
                fc, fm = dev_i32(tk.fwd, 0), dev_i32(tk.fwd, 1)
                fs = dev_i32(tk.fwd, 2)
                if moe_aux:
                    fok = dev_ok(tk.fwd)
                    y, aux = chunk_call(fc, xbuf[fc, fs], fm, want_aux=True)
                    aux_sum = aux_sum + jnp.where(fok, aux, 0.0)
                else:
                    y = chunk_call(fc, xbuf[fc, fs], fm, want_aux=False)

                if tk.head >= 0:
                    tgt_h = tgts[tk.head]
                    if shard_vocab:
                        y_b = psum_bcast(
                            jnp.where(at_last, y, jnp.zeros_like(y)), "stage"
                        )
                        offset = stage * v_local
                        (l_s, c_s), local_logits = _vocab_slice_ce(
                            rest_params["norm_out"],
                            rest_params["lm_head"]["kernel"],
                            y_b, tgt_h, offset, v_local, cfg,
                        )
                        if with_accuracy:
                            lf = local_logits.astype(jnp.float32)
                            lmax = jnp.max(lf, axis=-1)
                            larg = jnp.argmax(lf, axis=-1) + offset
                            gmax = jax.lax.pmax(lmax, "stage")
                            preds = jax.lax.pmin(
                                jnp.where(lmax >= gmax, larg, v_pad), "stage"
                            )
                            valid = tgt_h != -100
                            corr = jnp.sum(
                                jnp.where(valid, preds == tgt_h, False)
                            ).astype(jnp.float32)
                        else:
                            corr = jnp.float32(0)
                        # collective CE totals are replicated — count them
                        # once per data shard (accumulate on stage 0)
                        loss_sum = loss_sum + jnp.where(is0, l_s, 0.0)
                        cnt_sum = cnt_sum + jnp.where(is0, c_s, 0.0)
                        correct = correct + jnp.where(is0, corr, 0.0)
                    else:

                        def head_loss(_):
                            logits = gpt.apply_head(rest_params, cfg, y)
                            l_s, c_s = cross_entropy_sum(logits, tgt_h)
                            if with_accuracy:
                                valid = tgt_h != -100
                                preds = jnp.argmax(logits, axis=-1)
                                corr = jnp.sum(
                                    jnp.where(valid, preds == tgt_h, False)
                                ).astype(jnp.float32)
                            else:
                                corr = jnp.float32(0)
                            return l_s, c_s, corr

                        def no_loss(_):
                            return (
                                jnp.float32(0), jnp.float32(0),
                                jnp.float32(0),
                            )

                        l_s, c_s, corr = jax.lax.cond(
                            at_last, head_loss, no_loss, None
                        )
                        loss_sum = loss_sum + l_s
                        cnt_sum = cnt_sum + c_s
                        correct = correct + corr

                if tk.ship_fwd:
                    y_wire = jax.lax.ppermute(y, "stage", ring_f)

            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            cnt_sum = jax.lax.psum(cnt_sum, axes)
            correct = jax.lax.psum(correct, axes)
            if moe_aux:
                return loss_sum, cnt_sum, correct, jax.lax.psum(aux_sum, axes)
            return loss_sum, cnt_sum, correct

        outs = schedule(
            env["layers"], env["rest"],
            env["inputs"], env["positions"], env["masks"], env["tgts"],
        )
        loss_sum, count, correct = outs[:3]
        if moe_aux:
            aux_out.append(outs[3] / (M * self.data_size))
        denom = jnp.maximum(count, 1.0)
        return loss_sum / denom, correct / denom * 100.0

    def pipe_comm(self, cfg: gpt.GPTConfig, *, global_batch: int, seq: int,
                  phase: str = "train"):
        """Closed-form schedule-collective plan for one compiled step
        (analysis/plan.py train_comm_plan discovers this hook). The flat
        V=1 dense machine carries its hops inside a scan (one HLO
        instruction regardless of tick count) — no closed form is claimed
        there. The interleaved machine is unrolled with static shipping
        ticks, so the collective-permute count in the compiled HLO is
        exactly the schedule's ship count at activation-sized payloads;
        MoE worlds additionally pin all-to-all to ZERO (the pallas
        dispatch is collective-free — the a2a-free guard hlolint checks).
        `phase="eval"` prices the forward-only schedule (no dx hops).
        """
        if cfg.virtual_stages == 1 and cfg.num_experts == 0:
            return None
        sched = cached_schedule(
            self.num_stages, cfg.virtual_stages, self.num_microbatches,
            include_backward=(phase == "train"),
        )
        mb_local = global_batch // (self.num_microbatches * self.data_size)
        payload = (
            mb_local * seq * cfg.dim * jnp.dtype(cfg.compute_dtype).itemsize
        )
        count = (
            sched.stats["ship_fwd_ticks"] + sched.stats["ship_bwd_ticks"]
        )
        ops = {
            "collective-permute": {"count": count, "bytes": count * payload}
        }
        if cfg.num_experts > 0 and self.data_size == 1:
            # the a2a-free guard: the meshless pallas dispatch adds ZERO
            # all-to-alls, so a surplus one means a buffer dispatch leaked
            # in. Only claimable on a stage-only mesh — with a data axis
            # GSPMD reshards the batch ingest via tiny s32/pred
            # all-to-alls that are not ours to pin.
            ops["all-to-all"] = {"count": 0, "bytes": 0}
        return ops
