"""Pipeline parallelism: a GPipe schedule over a `stage` mesh axis.

TPU-native re-design of the reference's pipeline recipe (main-pipe.py, which
uses the deprecated torch `Pipe` over an `nn.Sequential` of per-GPU stages
with TensorPipe RPC, main-pipe.py:21-28,75-83). Here there is no RPC layer
and no wrapper modules: the decoder's stacked layer parameters are sharded
along their leading `num_layers` axis over the `stage` mesh axis, and a
`shard_map` runs the classic GPipe micro-batch schedule with
`jax.lax.ppermute` (XLA collective-permute over ICI) moving activations
stage-to-stage. Autodiff through `ppermute`/`scan` gives the pipelined
backward for free — the capability torch `Pipe` implements by hand.

Faithful structure (intent of main-pipe.py:52-83, which has syntax errors —
SURVEY §2.9 #3-5):
  - embeddings are applied on stage 0 and the norm+lm_head on the last stage
    (stage layout of main-pipe.py:53-55,67-68,75-77);
  - the padding mask (and here, the targets) are threaded through the
    pipeline alongside the activations — the twin of the `(x, mask)` tuple
    threading every reference stage performs (main-pipe.py:35-37,43-50);
  - the number of micro-batches defaults to the number of stages
    (`chunks=num_stages`, main-pipe.py:83,93).

Documented divergence: the reference balances uneven layer counts across
stages (intent of main-pipe.py:63-68); the scan-based layout requires
`num_layers % num_stages == 0` and raises otherwise. Pad `num_layers` or
choose a dividing stage count.

Loss is computed on the last stage (twin of main-pipe.py:162-165) as a
(sum, count) pair and `psum`-broadcast, so the returned loss equals the
non-pipelined global mean exactly.

The same shard_map serves the 2-D pipeline x data hybrid (`main-pipe-ddp.py`,
a stub in the reference — SURVEY §2.4): with a `(data, stage)` mesh the
micro-batch dimension is sharded over `data` and layer params are replicated
across it; GSPMD adds the data-axis gradient psum. That recipe is exactly
"the pipeline strategy with a second mesh axis".
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpukit import mesh as mesh_lib
from tpukit.model import gpt
from tpukit.ops.layers import cross_entropy_sum
from tpukit.shardings import Strategy


def _is_layers_path(path) -> bool:
    return any(
        isinstance(k, jax.tree_util.DictKey) and k.key == "layers" for k in path
    )


class Pipeline(Strategy):
    """GPipe pipeline strategy. Use mesh axes `("stage",)` or
    `("data", "stage")` for the DDP hybrid."""

    name = "pipe"

    def __init__(
        self, mesh: Mesh | None = None, num_microbatches: int | str | None = None
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"stage": -1})
        if "stage" not in self.mesh.axis_names:
            raise ValueError("Pipeline strategy needs a 'stage' mesh axis")
        self.num_stages = self.mesh.shape["stage"]
        # None -> chunks = num_stages, the reference twin (main-pipe.py:83,93).
        # "4x"-style multipliers scale with the stage count: the GPipe bubble
        # is (S-1)/(M+S-1), so M = 4S cuts it from ~43% to ~16% at S=4 —
        # the recipes default to 4x (documented divergence; --microbatches
        # restores any count including the reference's).
        if isinstance(num_microbatches, str):
            if not num_microbatches.endswith("x"):
                raise ValueError(
                    f"num_microbatches: int, None, or '<k>x', got {num_microbatches!r}"
                )
            self.num_microbatches = int(num_microbatches[:-1]) * self.num_stages
        else:
            self.num_microbatches = num_microbatches or self.num_stages
        if self.num_microbatches < 1:
            raise ValueError(
                f"num_microbatches must be positive, got {self.num_microbatches} "
                f"(from {num_microbatches!r})"
            )
        self.data_size = self.mesh.shape.get("data", 1)

    # -- shardings ---------------------------------------------------------

    @property
    def batch_divisor(self) -> int:
        # loss_fn splits the global batch into num_microbatches, each sharded
        # over the data axis.
        return self.num_microbatches * self.data_size

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        if cfg.num_layers % self.num_stages:
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide evenly into "
                f"{self.num_stages} pipeline stages; pad num_layers or "
                f"choose a dividing stage count"
            )

    def state_sharding(self, state_shapes):
        from jax.sharding import NamedSharding

        def spec(path, leaf):
            if _is_layers_path(path):
                if leaf.shape[0] % self.num_stages:
                    raise ValueError(
                        f"num_layers={leaf.shape[0]} must divide evenly into "
                        f"{self.num_stages} pipeline stages; pad num_layers or "
                        f"choose a dividing stage count"
                    )
                return NamedSharding(self.mesh, P("stage"))
            return NamedSharding(self.mesh, P())

        return jax.tree_util.tree_map_with_path(spec, state_shapes)

    def batch_spec(self) -> P:
        return P("data") if "data" in self.mesh.axis_names else P()

    # -- the schedule ------------------------------------------------------

    def loss_fn(self, params, cfg: gpt.GPTConfig, batch, targets, with_accuracy: bool = False):
        num_stages, num_micro = self.num_stages, self.num_microbatches
        if cfg.num_layers % num_stages:
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide evenly into "
                f"{num_stages} pipeline stages"
            )
        global_batch = batch["input_ids"].shape[0]
        if global_batch % self.batch_divisor:
            raise ValueError(
                f"batch {global_batch} must divide into {num_micro} microbatches "
                f"x {self.data_size} data shards"
            )
        micro = global_batch // num_micro
        seq = batch["input_ids"].shape[1]

        def split(x):
            return x.reshape(num_micro, micro, *x.shape[1:])

        inputs = split(batch["input_ids"])
        positions = split(batch["position_ids"])
        masks = split(batch["mask"])
        tgts = split(targets)

        # Specs: layer params split over stage; everything else replicated
        # across stage; micro-batch rows split over data (if present).
        data = "data" if "data" in self.mesh.axis_names else None
        batch_spec = P(None, data)
        layers = params["layers"]
        rest = {k: v for k, v in params.items() if k != "layers"}

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P("stage"), P(), batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        def schedule(local_layers, rest_params, inputs, positions, masks, tgts):
            stage = jax.lax.axis_index("stage")
            last = num_stages - 1
            mb_local = inputs.shape[1]

            x0 = jnp.zeros((mb_local, seq, cfg.dim), cfg.compute_dtype)
            carry0 = (
                x0,
                jnp.zeros((mb_local, seq), jnp.bool_),  # threaded pad mask
                jnp.zeros((mb_local, seq), jnp.int32),  # threaded targets
                jnp.float32(0),  # loss sum
                jnp.float32(0),  # valid-token count
                jnp.float32(0),  # correct count
            )

            def step(carry, t):
                x, mask_c, tgt_c, loss_sum, count, correct = carry
                idx = jnp.clip(t, 0, num_micro - 1)

                # Stage 0 ingests a fresh micro-batch through the embeddings
                # (embeddings live on the first stage, main-pipe.py:53,67,75).
                def ingest(_):
                    emb = gpt.apply_embeddings(rest_params, cfg, inputs[idx], positions[idx])
                    return emb, masks[idx], tgts[idx]

                def passthrough(_):
                    return x, mask_c, tgt_c

                x_in, mask_in, tgt_in = jax.lax.cond(stage == 0, ingest, passthrough, None)

                y = gpt.apply_decoder_layers(local_layers, cfg, x_in, mask_in)

                # Last stage: head + loss on micro-batch m = t - (S-1)
                # (norm+lm_head live on the last stage, main-pipe.py:55,68,77;
                # loss on the last stage's output, main-pipe.py:162-165).
                def head_loss(_):
                    logits = gpt.apply_head(rest_params, cfg, y)
                    # custom-VJP sum: no f32 [micro, S, V] tensor in either
                    # direction (tpukit/ops/layers.py cross_entropy_sum)
                    l_sum, cnt = cross_entropy_sum(logits, tgt_in)
                    if with_accuracy:
                        valid = tgt_in != -100
                        preds = jnp.argmax(logits, axis=-1)
                        corr = jnp.sum(jnp.where(valid, preds == tgt_in, False)).astype(
                            jnp.float32
                        )
                    else:
                        corr = jnp.float32(0)
                    return l_sum, cnt, corr

                def no_loss(_):
                    return jnp.float32(0), jnp.float32(0), jnp.float32(0)

                emit = jnp.logical_and(stage == last, t >= num_stages - 1)
                l_sum, cnt, corr = jax.lax.cond(emit, head_loss, no_loss, None)

                # Ship activations (and the threaded mask/targets — the twin
                # of the reference's (x, mask) tuple threading) to the next
                # stage over ICI.
                perm = [(i, i + 1) for i in range(num_stages - 1)]
                x_next = jax.lax.ppermute(y, "stage", perm)
                mask_next = jax.lax.ppermute(mask_in, "stage", perm)
                tgt_next = jax.lax.ppermute(tgt_in, "stage", perm)

                return (
                    (x_next, mask_next, tgt_next, loss_sum + l_sum, count + cnt, correct + corr),
                    None,
                )

            total_steps = num_micro + num_stages - 1
            (_, _, _, loss_sum, count, correct), _ = jax.lax.scan(
                step, carry0, jnp.arange(total_steps)
            )

            axes = tuple(self.mesh.axis_names)
            loss_sum = jax.lax.psum(loss_sum, axes)
            count = jax.lax.psum(count, axes)
            correct = jax.lax.psum(correct, axes)
            return loss_sum, count, correct

        loss_sum, count, correct = schedule(layers, rest, inputs, positions, masks, tgts)
        denom = jnp.maximum(count, 1.0)
        loss = loss_sum / denom
        accuracy = correct / denom * 100.0
        return loss, accuracy
