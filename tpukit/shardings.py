"""Parallelism strategies as mesh + sharding rules.

This is the TPU-native re-design of the reference's L1 layer (SURVEY §2.4):
where the reference wraps the model object (`DDP(model)` main-ddp.py:55,
`FSDP(model, ...)` main-fsdp.py:64-69, `Pipe(...)` main-pipe.py:79-83), here
a *strategy object* owns a `Mesh` and emits `NamedSharding`s for the train
state and the batch. `jax.jit` + GSPMD then inserts the collectives the
reference got from NCCL:

  - DataParallel: params/opt-state replicated, batch sharded on the `data`
    axis -> XLA emits a gradient all-reduce over ICI (the twin of DDP's
    bucketed NCCL all-reduce fired by autograd hooks, main-ddp.py:55,124).
  - FSDP: every tensor of params/grads/opt-state >= `min_shard_size` elements
    is sharded along its largest divisible axis -> XLA emits per-tensor
    all-gather (forward/backward) and reduce-scatter (grad) — the twin of
    FullyShardedDataParallel with `size_based_auto_wrap_policy(
    min_num_params=100)` (main-fsdp.py:60-69), where the wrap threshold
    becomes a shard-size threshold. `cpu_offload=True` pins the sharded
    params/opt-state to host memory (twin of `CPUOffload(offload_params=
    True)`, main-fsdp.py:68).
  - ContextParallel: the sequence dimension shards over a `seq` axis and
    attention runs as a ppermute ring (tpukit/ring_attention.py) inside
    shard_map — long-context capability the reference lacks entirely
    (SURVEY §5: its attention materializes S x S on one device).
  - Pipeline strategies live in tpukit/pipeline.py (they need a schedule,
    not just shardings) and subclass `Strategy`.

Every strategy also carries the default loss computation; the pipeline
overrides it with the micro-batched schedule.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpukit import mesh as mesh_lib
from tpukit.model import gpt
from tpukit.ops import quant_comm
from tpukit.ops.layers import (
    IGNORE_INDEX, cross_entropy_loss, cross_entropy_sum, masked_accuracy,
)


def _sharding_tree(mesh: Mesh, spec_fn, tree_shapes):
    """Map `spec_fn(shape) -> PartitionSpec` over a pytree of ShapeDtypeStructs
    (or arrays), returning NamedShardings."""
    return jax.tree.map(lambda leaf: NamedSharding(mesh, spec_fn(leaf.shape)), tree_shapes)



def _fused_head_disabled() -> bool:
    """TPUKIT_FUSED_HEAD=0 routes every strategy back to the unfused XLA
    head+CE (read at use time so it works however late it is set)."""
    return os.environ.get("TPUKIT_FUSED_HEAD", "1") == "0"


def _local_loss_sum(params, cfg, input_ids, position_ids, mask, tgts, rng,
                    fused: bool):
    """Per-shard (loss_sum, valid_count) over local batch rows — the
    shard_map building block of the quantized-comm strategies (the same
    local spelling ContextParallel's block uses): trunk forward on the
    local rows, then the fused head+CE kernel (no logits buffer) or the
    custom-VJP CE sum. Row-local math, so summing across shards equals the
    global loss sum bit-for-modulo-reduction-order."""
    x = gpt.apply_embeddings(params, cfg, input_ids, position_ids)
    x = gpt.apply_decoder_layers(
        params["layers"], cfg, x, mask, rng=rng, deterministic=rng is None,
    )
    if fused:
        from tpukit.ops.fused_head_ce import fused_head_ce
        from tpukit.ops.layers import layer_norm

        h = layer_norm(x, params["norm_out"]).astype(cfg.compute_dtype)
        loss_sum, count, _ = fused_head_ce(
            h.reshape(-1, h.shape[-1]),
            params["lm_head"]["kernel"],
            tgts.reshape(-1),
            cfg.vocab_size,
            with_accuracy=False,
        )
    else:
        logits = gpt.apply_head(params, cfg, x)
        loss_sum, count = cross_entropy_sum(logits, tgts)
    return loss_sum, count


def _n_elems(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _quant_rng(cfg, local_rng):
    """Stochastic-rounding key for the DP quantized grad psum (the one SR
    site outside a custom-vjp backward, so the per-step key make_step_fns
    threads can reach it): fold the step's dropout/comm key. Direct
    callers without a key fall back to round-to-nearest. The FSDP/EP SR
    sites derive their keys from the cotangent data instead
    (quant_comm._fallback_key — still step-varying, just not seed-keyed)."""
    if not cfg.quant_stochastic or local_rng is None:
        return None
    return jax.random.fold_in(local_rng, 0x5151)


def _quantized_dp_grads(strategy, params, cfg, batch, targets, rng):
    """DataParallel value_and_grad with the gradient psum hand-placed and
    compressed (--comm_dtype bf16/int8): the whole loss+backward runs
    inside shard_map over `data`, local grads are exact f32, and the ONLY
    lossy step is the wire — quant_comm.quantized_psum_tree flattens the
    grad tree into one payload and runs the EQuARX two-shot all-reduce
    (int8 reduce-scatter -> f32 accumulate -> int8 all-gather). The loss
    scalar and the global valid-token count psum in full precision.

    --grad_buckets N >= 1 (round 18) replaces the single payload with
    quant_comm.bucketed_psum_tree: N ~equal-byte buckets in layer-
    reversed order, one two-shot exchange each (f32 keeps the two-shot
    shape — the bucket collectives stay auditable and the f32 trajectory
    is bit-identical under any bucket count). Each bucket's exchange
    depends only on its own leaves' backward, so the remaining backward
    compute overlaps the wire — the hlolint `overlap` rule gates it."""
    from tpukit.compat import shard_map

    mesh = strategy.mesh
    world = mesh.shape["data"]
    batch_spec = P("data", None)
    fused = strategy.fused_head and not _fused_head_disabled()

    def block(p, input_ids, position_ids, mask, tgts):
        local_rng = (
            jax.random.fold_in(rng, jax.lax.axis_index("data"))
            if rng is not None
            else None
        )
        gcount = jax.lax.psum(
            jnp.sum(tgts != IGNORE_INDEX).astype(jnp.float32), "data"
        )

        def local_loss(p):
            loss_sum, _ = _local_loss_sum(
                p, cfg, input_ids, position_ids, mask, tgts, local_rng, fused
            )
            return loss_sum / jnp.maximum(gcount, 1.0)

        val, grads = jax.value_and_grad(local_loss)(p)
        loss = jax.lax.psum(val, "data")
        if cfg.grad_buckets > 0:
            grads = quant_comm.bucketed_psum_tree(
                grads, "data", world, cfg.grad_buckets, cfg.comm_dtype,
                rng=_quant_rng(cfg, local_rng),
            )
        else:
            grads = quant_comm.quantized_psum_tree(
                grads, "data", world, cfg.comm_dtype,
                rng=_quant_rng(cfg, local_rng),
            )
        return loss, grads

    return shard_map(
        block,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )(params, batch["input_ids"], batch["position_ids"], batch["mask"], targets)


def _quantized_fsdp_grads(strategy, params, cfg, batch, targets, rng):
    """FSDP value_and_grad with the gradient reduce-scatter hand-placed
    and compressed, params-at-use full precision ("grads-only first"):
    inside shard_map each sharded leaf gathers through
    quant_comm.all_gather_qgrad — a FULL-PRECISION lax.all_gather whose
    custom vjp compresses the cotangent through the quantized
    reduce-scatter, landing grads directly in the FSDP shard layout.
    Replicated (sub-threshold) leaves ride psum_grad: identity forward,
    full-precision grad psum.

    --grad_buckets N >= 1 (round 18): the sharded leaves partition into
    N ~equal-byte, layer-reversed buckets and each bucket gathers through
    ONE quant_comm.bucket_gather_qgrad — forward per-leaf full-precision
    gathers unchanged, backward ONE packed reduce-scatter a2a per BUCKET
    instead of one per leaf. The bucket vjp fires when its last
    (earliest-layer) cotangent lands, so each wire launch interleaves
    with the remaining backward. Replicated leaves stay on the f32 psum
    path regardless of bucketing (compressing or batching them buys
    noise, not bandwidth)."""
    from tpukit.compat import shard_map

    mesh = strategy.mesh
    world = mesh.shape["data"]
    batch_spec = P("data", None)
    fused = strategy.fused_head and not _fused_head_disabled()
    leaves, treedef = jax.tree_util.tree_flatten(params)
    spec_list = [strategy.param_spec(l.shape) for l in leaves]
    spec_tree = jax.tree_util.tree_unflatten(treedef, spec_list)
    dim_list = [
        next((i for i, ax in enumerate(spec) if ax == "data"), None)
        for spec in spec_list
    ]
    buckets = []
    if cfg.grad_buckets > 0:
        sharded = {i for i, d in enumerate(dim_list) if d is not None}
        buckets = quant_comm.grad_bucket_plan(
            params, cfg.grad_buckets, include=sharded
        )

    def block(p_shards, input_ids, position_ids, mask, tgts):
        local_rng = (
            jax.random.fold_in(rng, jax.lax.axis_index("data"))
            if rng is not None
            else None
        )
        gcount = jax.lax.psum(
            jnp.sum(tgts != IGNORE_INDEX).astype(jnp.float32), "data"
        )

        def local_loss(ps):
            flat, td = jax.tree_util.tree_flatten(ps)
            full = [None] * len(flat)
            for i, (leaf, dim) in enumerate(zip(flat, dim_list)):
                if dim is None:
                    full[i] = quant_comm.psum_grad(leaf, "data")
            if buckets:
                for idxs in buckets:
                    gathered = quant_comm.bucket_gather_qgrad(
                        tuple(flat[i] for i in idxs), "data", world,
                        tuple(dim_list[i] for i in idxs), cfg.comm_dtype,
                        quant_comm.DEFAULT_BLOCK, cfg.quant_stochastic,
                    )
                    for i, g in zip(idxs, gathered):
                        full[i] = g
            else:
                for i, (leaf, dim) in enumerate(zip(flat, dim_list)):
                    if dim is not None:
                        full[i] = quant_comm.all_gather_qgrad(
                            leaf, "data", world, dim, cfg.comm_dtype,
                            quant_comm.DEFAULT_BLOCK, cfg.quant_stochastic,
                        )
            loss_sum, _ = _local_loss_sum(
                td.unflatten(full), cfg, input_ids, position_ids, mask,
                tgts, local_rng, fused,
            )
            return loss_sum / jnp.maximum(gcount, 1.0)

        val, grads = jax.value_and_grad(local_loss)(p_shards)
        return jax.lax.psum(val, "data"), grads

    return shard_map(
        block,
        mesh=mesh,
        in_specs=(spec_tree, batch_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(P(), spec_tree),
        check_vma=False,
    )(params, batch["input_ids"], batch["position_ids"], batch["mask"], targets)


class Strategy:
    """Base: single-device (twin of main-single.py: plain `.to(device)`,
    main-single.py:21,33 — here, a trivial 1-device mesh)."""

    name = "single"
    # Compute the loss through the fused head+CE kernel (no [B*S, V] logits
    # buffer — ops/fused_head_ce.py). TensorParallel turns this off: its
    # vocab-sharded head wants the GSPMD matmul path. TPUKIT_FUSED_HEAD=0
    # (checked at use time, never forces the kernel ON) is the operational
    # escape hatch back to the unfused XLA path.
    fused_head = True
    # HLO collective kinds this strategy is EXPECTED to emit in its compiled
    # train step (tpukit/obs/xla.py COLLECTIVE_OPS names). Telemetry
    # (`fit()`'s kind="xla" record, tools/report.py) reports the measured
    # per-kind comm bytes from the compiled module and flags kinds outside
    # this set — a sharding regression (say, FSDP silently all-gathering
    # the whole state per step) shows up as a surprise entry, not a hunch.
    comm_ops: tuple[str, ...] = ()
    # Strategies with hand-wired quantized collectives (--comm_dtype,
    # round 12: ops/quant_comm.py) set this True. Everything else rejects
    # a non-f32 comm_dtype at validate_config — a flag that silently does
    # nothing would read as a 4x win that never happened.
    quantized_comm = False

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh(None)

    # -- sharding rules ----------------------------------------------------

    def param_spec(self, shape: tuple[int, ...]) -> P:
        return P()

    def batch_spec(self) -> P:
        return P()

    def state_sharding(self, state_shapes):
        """The train state's placement on this strategy's mesh. Besides
        feeding the jitted step's in/out shardings, this tree is the
        TARGET spec of an elastic restore (tpukit/reshard.py): a
        checkpoint saved under ANY strategy/world reshards onto whatever
        this returns for the current mesh — which is why the rules here
        must be pure functions of (shape, mesh), never of the saving
        world (FSDP's min_shard_size threshold and divisibility checks
        re-derive per world for free under that discipline)."""
        return _sharding_tree(self.mesh, self.param_spec, state_shapes)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def to_compute(self, state):
        """Hook run at the top of each jitted step: move offloaded state into
        device memory. Identity unless a strategy offloads (FSDP
        cpu_offload)."""
        return state

    def prepare_params(self, params, cfg: gpt.GPTConfig):
        """Hook run once at init: adapt freshly-initialized parameters to the
        strategy's layout. Identity for every strategy except Pipeline, which
        pads the stacked layers to a stage multiple (see
        Pipeline.prepare_params)."""
        return params

    def host_batch_fn(self, cfg: gpt.GPTConfig):
        """Optional host-side per-batch transform, applied by the trainer to
        the numpy batch BEFORE device placement. None (default) for every
        strategy except ContextParallel, whose zigzag sequence permutation
        would otherwise be a cross-shard reshard collective inside every
        jitted step (ADVICE r4)."""
        return None

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        """Raise a clear error before any tracing when the model shape cannot
        map onto this strategy's mesh (divisibility constraints)."""
        self._validate_comm_dtype(cfg)

    def _validate_comm_dtype(self, cfg: gpt.GPTConfig) -> None:
        """The --comm_dtype / --grad_buckets gate every validate_config
        override must also call: a quantized comm dtype or a bucket
        schedule on a strategy without hand-wired collectives is a no-op
        masquerading as a wire win."""
        if cfg.comm_dtype != "f32" and not self.quantized_comm:
            raise ValueError(
                f"--comm_dtype {cfg.comm_dtype}: the {self.name} strategy "
                f"has no wired quantized collectives — supported on ddp "
                f"(grad all-reduce), fsdp (grad reduce-scatter) and ep "
                f"(a2a dispatch payload)"
            )
        if cfg.grad_buckets > 0 and not self.quantized_comm:
            raise ValueError(
                f"--grad_buckets {cfg.grad_buckets}: the {self.name} "
                f"strategy has no hand-placed grad wire to bucket — "
                f"supported on ddp (bucketed two-shot all-reduce), fsdp "
                f"(bucketed grad reduce-scatter) and ep (the per-layer "
                f"a2a pairs are already bucket-granular)"
            )

    def _hand_placed(self, cfg: gpt.GPTConfig) -> bool:
        """True when a quantized-comm strategy's value_and_grad must run
        its hand-placed shard_map grad block instead of leaving the
        collectives to GSPMD: a quantized wire, or any bucket schedule
        (bucketed f32 keeps exact math but hand-places the exchanges so
        they stay auditable). ONE spelling — DDP and FSDP branching on
        different predicates here would silently run different
        schedules."""
        return cfg.comm_dtype != "f32" or cfg.grad_buckets > 0

    def grad_comm(self, cfg: gpt.GPTConfig, param_shapes,
                  backend: str | None = None) -> dict | None:
        """Closed-form expected {op: {count, bytes}} of THIS strategy's
        quantized gradient collectives for one train step, or None when
        nothing is compressed. The audit number fit()'s xla record, the
        multichip dryrun and tests compare against the compiled HLO —
        hand-compressing a collective means being able to predict its
        bytes (the round-10 dispatch-audit discipline, applied to grads).
        Round 16: consumed through `analysis.plan.train_comm_plan`, which
        folds this and `dispatch_comm` into one CommPlan the rule engine
        diffs (DESIGN.md §15) — new strategies declare here, the engine
        audits everywhere."""
        return None

    def overlap_comm(self, cfg: gpt.GPTConfig, param_shapes) -> dict | None:
        """Declared overlap expectation of this strategy's train step
        (round 18, ROADMAP #5): {op: K} meaning at least K collectives of
        that HLO kind must each have independent compute the scheduler
        can hide them behind — the promoted hlolint `overlap` rule gates
        it (analysis/rules.py). None when the schedule is serial (no
        bucket wire declared). Only bucketed worlds declare: a 1-bucket
        payload after the whole backward has nothing to overlap with,
        and claiming otherwise would make the gate a lie."""
        return None

    def comm_ops_for(self, cfg: gpt.GPTConfig) -> tuple[str, ...]:
        """The expected-collective-kinds set for THIS config — `comm_ops`
        unless the config reshapes the schedule (DP/FSDP under a quantized
        comm dtype replace their GSPMD grad collective with the packed
        a2a + all-gather pair). A pure function of cfg, never a mutation:
        one strategy instance must audit an f32 run correctly after
        validating an int8 config."""
        return self.comm_ops

    def inference_params(self, params, cfg: gpt.GPTConfig):
        """Params as the plain sequential `gpt.forward` expects them.
        Identity for every strategy whose training layout IS the natural
        layout; the interleaved pipeline (cfg.virtual_stages > 1) stores
        the layer stack chunk-permuted and overrides this to restore
        natural layer order before generation/decode (train.py's
        generate_samples calls it after replication)."""
        return params

    @property
    def batch_divisor(self) -> int:
        """Every global batch fed to this strategy must be a multiple of this.
        The loader pads the final batch by wrapping to satisfy it (torch
        `Pipe` handles uneven chunks internally; here the divisor is explicit
        so every step keeps one static, compiled shape)."""
        return self.mesh.shape.get("data", 1)

    def replicated(self):
        return NamedSharding(self.mesh, P())

    # -- loss --------------------------------------------------------------

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        """Default forward + masked CE (+ masked accuracy for eval).

        Under a sharded batch this single jitted function IS the distributed
        step: the mean over the global batch is the twin of DDP's gradient
        all-reduce and of the explicit eval `dist.all_reduce(..., AVG)`
        (main-ddp.py:159-160) — GSPMD inserts the psum.

        `rng` is the per-step dropout key (None = deterministic, the eval
        path). Under GSPMD the global mask is generated once and sharded
        (threefry is partitionable), so dropout is consistent across DP/FSDP
        shards — the twin of torch dropout running under DDP.

        `aux_out` (MoE configs): list receiving the summed load-balance aux
        loss; value_and_grad passes it so training optimizes
        CE + moe_aux_weight * aux while eval metrics stay pure CE.

        The head + cross-entropy run through the fused Pallas kernel
        (ops/fused_head_ce.py) unless the strategy opts out: no logits
        buffer in HBM, which is both the long-context perf win and what
        lets batch sizes the unfused logits tensor would OOM.
        """
        if self.fused_head and not _fused_head_disabled():
            from tpukit.ops.fused_head_ce import fused_head_ce

            h = gpt.forward_hidden(
                params, cfg, batch["input_ids"], batch["position_ids"],
                batch["mask"], rng=rng, deterministic=rng is None,
                aux_out=aux_out,
            )
            loss_sum, count, correct = fused_head_ce(
                h.reshape(-1, h.shape[-1]),
                params["lm_head"]["kernel"],
                targets.reshape(-1),
                cfg.vocab_size,
                with_accuracy=with_accuracy,
            )
            denom = jnp.maximum(count, 1.0)
            return loss_sum / denom, correct / denom * 100.0
        logits = gpt.forward(
            params, cfg, batch["input_ids"], batch["position_ids"], batch["mask"],
            rng=rng, deterministic=rng is None, aux_out=aux_out,
        )
        loss = cross_entropy_loss(logits, targets)
        accuracy = masked_accuracy(logits, targets) if with_accuracy else jnp.float32(0)
        return loss, accuracy

    def value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        """Loss and parameter gradients for one global batch — the training
        half of the strategy contract (make_step_fns calls this). Default:
        autodiff over `loss_fn`. Schedules that must build their gradient
        explicitly (Pipeline1F1B's per-stage vjps) override it.

        MoE configs train on CE + moe_aux_weight * load-balance aux (the
        Switch objective); the RETURNED loss is the pure CE, so the train
        bar and eval report the same quantity."""

        if cfg.num_experts == 0:

            def loss_of(p):
                loss, _ = self.loss_fn(p, cfg, batch, targets, rng=rng)
                return loss

            return jax.value_and_grad(loss_of)(params)

        def loss_of_moe(p):
            aux_list: list = []
            loss, _ = self.loss_fn(
                p, cfg, batch, targets, rng=rng, aux_out=aux_list
            )
            total = loss
            for aux in aux_list:
                total = total + cfg.moe_aux_weight * aux
            return total, loss

        (_, loss), grads = jax.value_and_grad(loss_of_moe, has_aux=True)(params)
        return loss, grads

    def describe(self) -> str:
        return f"{self.name} over mesh {dict(zip(self.mesh.axis_names, self.mesh.devices.shape))}"


class SingleDevice(Strategy):
    name = "single"


class DataParallel(Strategy):
    """Twin of the DDP recipe's parallelism (main-ddp.py:55): batch sharded
    over `data`, params replicated. With the default comm_dtype the gradient
    psum is emitted by XLA from the replicated-param + sharded-batch specs;
    with --comm_dtype bf16/int8 (round 12) value_and_grad hand-places it as
    the EQuARX two-shot quantized all-reduce of ops/quant_comm.py instead —
    one packed all_to_all (the reduce-scatter phase) plus one packed
    all_gather carrying ~1/4 of the f32 bytes, f32 accumulation, loss and
    token-count psums untouched."""

    name = "ddp"
    comm_ops = ("all-reduce",)  # the grad psum
    quantized_comm = True

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"data": -1})

    def batch_spec(self) -> P:
        return P("data")

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        if (cfg.comm_dtype != "f32" or cfg.grad_buckets > 0) and cfg.num_experts > 0:
            raise ValueError(
                f"--comm_dtype {cfg.comm_dtype} / --grad_buckets "
                f"{cfg.grad_buckets} under DataParallel requires a dense "
                f"model: the MoE aux-loss statistics are not psummed by "
                f"the hand-placed grad block — use ExpertParallel "
                f"(main-moe.py) for MoE comm"
            )

    def comm_ops_for(self, cfg: gpt.GPTConfig) -> tuple[str, ...]:
        if self._hand_placed(cfg):
            # the hand-placed two-shot replaces the GSPMD grad all-reduce
            # with a packed a2a + all-gather; scalar loss/count psums keep
            # "all-reduce" in the expected set
            return ("all-gather", "all-reduce", "all-to-all")
        return self.comm_ops

    def value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        if not self._hand_placed(cfg):
            return super().value_and_grad(params, cfg, batch, targets, rng=rng)
        if cfg.num_experts > 0:
            raise ValueError(
                "--comm_dtype bf16/int8 / --grad_buckets under DataParallel "
                "requires a dense model (see DataParallel.validate_config)"
            )
        return _quantized_dp_grads(self, params, cfg, batch, targets, rng)

    def grad_comm(self, cfg: gpt.GPTConfig, param_shapes,
                  backend: str | None = None) -> dict | None:
        """Expected payload of the hand-placed grad wire. Serial
        (grad_buckets 0): the whole grad tree flattens into ONE two-shot
        exchange (quant_comm.expected_all_reduce — one packed a2a + one
        packed all-gather, [world, row] each). Bucketed: one two-shot
        pair per grad_bucket_plan bucket, priced at the bucket payload
        dtype (f32 included — the bucket schedule is always hand-placed
        and therefore always predicted)."""
        if cfg.grad_buckets > 0:
            buckets = quant_comm.grad_bucket_plan(param_shapes, cfg.grad_buckets)
            leaves = jax.tree_util.tree_leaves(param_shapes)
            sizes = [
                sum(_n_elems(leaves[i].shape) for i in idxs)
                for idxs in buckets
            ]
            return quant_comm.expected_bucketed_all_reduce(
                sizes, self.mesh.shape["data"], cfg.comm_dtype,
                backend=backend,
            )
        if cfg.comm_dtype == "f32":
            return None
        n = sum(
            _n_elems(l.shape) for l in jax.tree_util.tree_leaves(param_shapes)
        )
        return quant_comm.expected_all_reduce(
            n, self.mesh.shape["data"], cfg.comm_dtype, backend=backend
        )

    def overlap_comm(self, cfg: gpt.GPTConfig, param_shapes) -> dict | None:
        """The DDP bucket schedule's overlap declaration: every bucket's
        two-shot pair (its a2a AND its all-gather) must have independent
        compute scheduled around it — with B >= 2 buckets each exchange
        depends only on its own leaves' backward, so the rest of the
        sweep is free to hide the wire."""
        if cfg.grad_buckets < 2 or param_shapes is None:
            return None
        buckets = quant_comm.grad_bucket_plan(param_shapes, cfg.grad_buckets)
        if len(buckets) < 2:
            return None
        return {"all-to-all": len(buckets), "all-gather": len(buckets)}


class FSDP(Strategy):
    """Twin of the FSDP recipe (main-fsdp.py:60-69): ZeRO-3-style sharding of
    params, grads and optimizer state over the `data` axis, via GSPMD."""

    name = "fsdp"
    # param all-gather at use, grad reduce-scatter, small-tensor all-reduce
    comm_ops = ("all-gather", "reduce-scatter", "all-reduce")
    quantized_comm = True

    # Twin of size_based_auto_wrap_policy(min_num_params=100): tensors below
    # the threshold stay replicated (main-fsdp.py:62).
    def __init__(self, mesh: Mesh | None = None, min_shard_size: int = 100, cpu_offload: bool = False):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"data": -1})
        self.min_shard_size = min_shard_size
        self.cpu_offload = cpu_offload
        if cpu_offload:
            self.name = "fsdp-offload"

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        if (cfg.comm_dtype != "f32" or cfg.grad_buckets > 0) and cfg.num_experts > 0:
            raise ValueError(
                f"--comm_dtype {cfg.comm_dtype} / --grad_buckets "
                f"{cfg.grad_buckets} under FSDP requires a dense model: "
                f"the MoE aux-loss statistics are not psummed by the "
                f"hand-placed grad block — use ExpertParallel "
                f"(main-moe.py) for MoE comm"
            )

    def comm_ops_for(self, cfg: gpt.GPTConfig) -> tuple[str, ...]:
        if self._hand_placed(cfg):
            # grads-only first: the grad reduce-scatter becomes a packed
            # a2a; forward param gathers stay full-precision all-gathers
            return ("all-gather", "all-reduce", "all-to-all")
        return self.comm_ops

    def value_and_grad(self, params, cfg: gpt.GPTConfig, batch, targets, rng=None):
        """Default (f32, no buckets): GSPMD autodiff — per-tensor
        all-gather at use, grad reduce-scatter, all inserted by the
        partitioner. bf16/int8 (round 12) or --grad_buckets (round 18):
        the hand-placed shard_map block of `_quantized_fsdp_grads` —
        gather-at-use stays FULL precision, the grad reduce-scatter
        compresses (and/or buckets) through ops/quant_comm.py."""
        if not self._hand_placed(cfg):
            return super().value_and_grad(params, cfg, batch, targets, rng=rng)
        if cfg.num_experts > 0:
            raise ValueError(
                "--comm_dtype bf16/int8 / --grad_buckets under FSDP "
                "requires a dense model (see FSDP.validate_config)"
            )
        return _quantized_fsdp_grads(self, params, cfg, batch, targets, rng)

    def _sharded_indices(self, param_shapes) -> tuple[list, set]:
        """(flat leaves, indices of leaves the param_spec shards over
        `data`) — the subset the bucket plan partitions."""
        leaves = jax.tree_util.tree_leaves(param_shapes)
        sharded = {
            i for i, leaf in enumerate(leaves)
            if any(ax == "data" for ax in self.param_spec(leaf.shape))
        }
        return leaves, sharded

    def grad_comm(self, cfg: gpt.GPTConfig, param_shapes,
                  backend: str | None = None) -> dict | None:
        """Expected payload of the hand-placed FSDP grad wire. Serial:
        one packed reduce-scatter a2a per SHARDED leaf (replicated
        sub-threshold leaves psum in f32 and are not audited). Bucketed:
        one packed a2a per grad_bucket_plan bucket over the sharded
        subset, priced at the bucket dtype (f32 included). Either way the
        full-precision forward param all-gathers (one per sharded leaf,
        f32 result = the gathered tensor) ride alongside."""
        if not self._hand_placed(cfg):
            return None
        world = self.mesh.shape["data"]
        leaves, sharded = self._sharded_indices(param_shapes)
        gather = {
            "count": len(sharded),
            # f32 param gather, full tensor result
            "bytes": sum(_n_elems(leaves[i].shape) * 4 for i in sharded),
        }
        if cfg.grad_buckets > 0:
            buckets = quant_comm.grad_bucket_plan(
                param_shapes, cfg.grad_buckets, include=sharded
            )
            sizes = [
                sum(_n_elems(leaves[i].shape) for i in idxs)
                for idxs in buckets
            ]
            exp = quant_comm.expected_bucketed_reduce_scatter(
                sizes, world, cfg.comm_dtype, backend=backend
            )
            if not exp:
                return None
            return {"all-to-all": exp["all-to-all"], "all-gather": gather}
        a2a = {"count": 0, "bytes": 0}
        for i in sorted(sharded):
            exp = quant_comm.expected_reduce_scatter(
                _n_elems(leaves[i].shape), world, cfg.comm_dtype,
                backend=backend,
            )
            if exp:
                a2a["count"] += exp["all-to-all"]["count"]
                a2a["bytes"] += exp["all-to-all"]["bytes"]
        if not a2a["count"]:
            return None
        return {"all-to-all": a2a, "all-gather": gather}

    def overlap_comm(self, cfg: gpt.GPTConfig, param_shapes) -> dict | None:
        """The FSDP bucket schedule's overlap declaration: every bucket's
        backward reduce-scatter a2a must have independent compute around
        it. Forward param gathers are at-use by design (serial on the
        critical path) and are NOT declared."""
        if cfg.grad_buckets < 2 or param_shapes is None:
            return None
        _, sharded = self._sharded_indices(param_shapes)
        buckets = quant_comm.grad_bucket_plan(
            param_shapes, cfg.grad_buckets, include=sharded
        )
        if len(buckets) < 2:
            return None
        return {"all-to-all": len(buckets)}

    def param_spec(self, shape: tuple[int, ...]) -> P:
        axis_size = self.mesh.shape["data"]
        size = 1
        for d in shape:
            size *= d
        if size < self.min_shard_size:
            return P()
        # shard the largest dimension divisible by the axis size
        candidates = [(d, i) for i, d in enumerate(shape) if d % axis_size == 0]
        if not candidates:
            return P()
        _, dim = max(candidates)
        spec = [None] * len(shape)
        spec[dim] = "data"
        return P(*spec)

    def state_sharding(self, state_shapes):
        shardings = _sharding_tree(self.mesh, self.param_spec, state_shapes)
        if self.cpu_offload:
            # Twin of CPUOffload(offload_params=True) (main-fsdp.py:68):
            # sharded state lives in host memory; XLA streams it in on use.
            # Host memory spaces are a TPU feature; on other backends the
            # flag degrades to plain FSDP with a warning (the reference's
            # CPUOffload is likewise CUDA-only).
            if self._offload_supported():
                shardings = jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"), shardings
                )
            else:
                import warnings

                warnings.warn(
                    "--cpu_offload needs a TPU backend with host memory "
                    "spaces; running plain FSDP instead",
                    stacklevel=2,
                )
        return shardings

    def _offload_supported(self) -> bool:
        return jax.default_backend() in ("tpu", "axon")

    def to_compute(self, state):
        """Stream host-pinned state into device HBM at the top of the step
        (the XLA twin of FSDP's CPUOffload H2D param streaming,
        main-fsdp.py:68). The step's out_shardings put the updated state
        back in host memory."""
        if not (self.cpu_offload and self._offload_supported()):
            return state

        def put(leaf):
            sharding = NamedSharding(
                self.mesh, self.param_spec(leaf.shape), memory_kind="device"
            )
            return jax.device_put(leaf, sharding)

        return jax.tree.map(put, state)

    def batch_spec(self) -> P:
        return P("data")


class ContextParallel(Strategy):
    """Sequence/context parallelism via ring attention.

    The batch's *sequence* dimension shards over a `seq` mesh axis (optionally
    combined with a `data` axis for batch sharding). The whole forward runs
    inside shard_map: embeddings / norms / MLPs / head are token-local, and
    attention is the exact-causal ppermute ring of tpukit/ring_attention.py.
    Params are replicated; their gradient psum over the mesh falls out of the
    shard_map transpose. This axis has no reference counterpart — the
    cookbook caps sequence at 256 on one device (SURVEY §5) — and is the
    scale-out path for the long-context capability.
    """

    name = "cp"

    def __init__(
        self, mesh: Mesh | None = None, attention: str = "ring",
        host_permute: bool = False,
    ):
        """`attention` picks the sequence-parallel schedule:
        "ring" (default) — K/V ppermute hops, zigzag-balanced, works for
        any head count; "ulysses" — two all_to_alls re-partition to
        head-sharding and run full-sequence flash attention locally
        (needs heads % seq_shards == 0). See tpukit/ring_attention.py.

        `host_permute=True` declares that the CALLER applies the zigzag
        permutation host-side (via the fn `host_batch_fn` returns, as
        fit() does) and loss_fn must NOT re-permute in-jit — in-jit the
        same gather on the seq-sharded batch is a cross-shard reshard
        collective every step (ADVICE r4). With it set, every loss_fn
        call must receive host-permuted batches whenever zigzag is
        active."""
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"seq": -1})
        self.host_permute = host_permute
        if "seq" not in self.mesh.axis_names:
            raise ValueError("ContextParallel needs a 'seq' mesh axis")
        if attention not in ("ring", "ulysses"):
            raise ValueError(f"attention must be 'ring' or 'ulysses', got {attention!r}")
        self.attention = attention
        if attention == "ulysses":
            self.name = "cp-ulysses"
            # head re-partition round trips; grad psum over the mesh
            self.comm_ops = ("all-to-all", "all-reduce")
        else:
            # K/V ring hops; grad psum over the mesh
            self.comm_ops = ("collective-permute", "all-reduce")
        self.seq_size = self.mesh.shape["seq"]
        self.data_size = self.mesh.shape.get("data", 1)

    def batch_spec(self) -> P:
        data = "data" if "data" in self.mesh.axis_names else None
        return P(data, "seq")

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        self._validate_comm_dtype(cfg)
        if cfg.num_experts > 0:
            raise ValueError(
                "ContextParallel does not support MoE configs (the routed "
                "dispatch is token-global, the CP loss is seq-sharded) — "
                "use ExpertParallel (main-moe.py) for num_experts > 0"
            )
        # The model consumes sequence_length - 1 tokens after the LM shift
        # (prepare_batch, tpukit/batching.py).
        seq = cfg.max_position_embeddings - 1
        if seq % self.seq_size:
            raise ValueError(
                f"--sequence_length {cfg.max_position_embeddings}: the model "
                f"sequence {seq} must divide over {self.seq_size} sequence "
                f"shards; pick sequence_length = k*{self.seq_size} + 1"
            )
        if self.attention == "ulysses" and cfg.heads % self.seq_size:
            raise ValueError(
                f"ulysses attention re-partitions heads over the seq axis: "
                f"--heads {cfg.heads} must divide by {self.seq_size} "
                f"sequence shards (or use attention='ring')"
            )

    def _use_zigzag(self, seq_len: int) -> bool:
        """Zigzag layout (causal load balance — tpukit/ring_attention.py):
        permute the sequence so each shard holds one early + one late
        chunk; every per-token computation (embeddings, MLPs, CE sums) is
        permutation-invariant, so only the ring schedule needs to know.
        Falls back to the contiguous ring when 2*P doesn't divide S.
        The ulysses schedule keeps the contiguous layout (its local
        attention sees the full gathered sequence)."""
        return (
            self.attention == "ring"
            and seq_len % (2 * self.seq_size) == 0
            and self.seq_size > 1
        )

    def host_batch_fn(self, cfg: gpt.GPTConfig):
        """The zigzag permutation as a HOST-side numpy transform, applied
        before device placement (ADVICE r4: in-jit, the same gather on the
        globally seq-sharded batch makes GSPMD insert a cross-shard reshard
        of four token-sized arrays every train/eval step). Only returned
        when the strategy was constructed with `host_permute=True` — the
        explicit contract that loss_fn will receive pre-permuted batches."""
        seq_len = cfg.max_position_embeddings - 1  # model seq after the shift
        if not (self.host_permute and self._use_zigzag(seq_len)):
            return None
        from tpukit.ring_attention import zigzag_order

        order = zigzag_order(seq_len, self.seq_size)

        def permute(model_batch, targets):
            return (
                {key: val[:, order] for key, val in model_batch.items()},
                targets[:, order],
            )

        return permute

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        # `aux_out` matches the base signature so direct
        # `strategy.value_and_grad` calls on an MoE config reach the curated
        # error below instead of an opaque TypeError (ADVICE r5 #1);
        # validate_config raises the same message for the fit() entry point.
        if cfg.num_experts > 0:
            raise ValueError(
                "ContextParallel does not support MoE configs (the routed "
                "dispatch is token-global, the CP loss is seq-sharded) — "
                "use ExpertParallel (main-moe.py) for num_experts > 0"
            )
        seq_len = batch["input_ids"].shape[1]
        if seq_len % self.seq_size:
            raise ValueError(
                f"sequence length {seq_len} must divide over {self.seq_size} "
                f"sequence shards (pick a dividing --sequence_length)"
            )
        use_zigzag = self._use_zigzag(seq_len)
        if use_zigzag and not self.host_permute:
            from tpukit.ring_attention import zigzag_order

            order = zigzag_order(seq_len, self.seq_size)
            batch = {key: val[:, order] for key, val in batch.items()}
            targets = targets[:, order]
        local_cfg = cfg.replace(
            attention_impl="ring" if self.attention == "ring" else "ulysses",
            ring_axis="seq",
            ring_layout="zigzag" if use_zigzag else "contiguous",
        )
        batch_spec = self.batch_spec()
        axes = tuple(self.mesh.axis_names)

        from tpukit.compat import shard_map

        def local_loss(params, input_ids, position_ids, mask, tgts):
            if rng is None:
                local_rng = None
            else:
                # independent dropout mask per mesh position: fold the
                # shard's linearized mesh index into the step key
                lin = jnp.int32(0)
                for ax in axes:
                    lin = lin * self.mesh.shape[ax] + jax.lax.axis_index(ax)
                local_rng = jax.random.fold_in(rng, lin)
            x = gpt.apply_embeddings(params, local_cfg, input_ids, position_ids)
            x = gpt.apply_decoder_layers(
                params["layers"], local_cfg, x, mask,
                rng=local_rng, deterministic=local_rng is None,
            )
            if self.fused_head and not _fused_head_disabled():
                # Each shard's tokens through the fused head+CE kernel
                # (composes under shard_map Manual like the flash kernel):
                # no [B, S_local, V] logits tensor even per shard — CP is
                # the long-context strategy, where that buffer hurts most.
                from tpukit.ops.fused_head_ce import fused_head_ce
                from tpukit.ops.layers import layer_norm

                h = layer_norm(x, params["norm_out"]).astype(
                    local_cfg.compute_dtype
                )
                loss_sum, count, correct = fused_head_ce(
                    h.reshape(-1, h.shape[-1]),
                    params["lm_head"]["kernel"],
                    tgts.reshape(-1),
                    cfg.vocab_size,
                    with_accuracy=with_accuracy,
                )
            else:
                # custom-VJP sum: no f32 [B, S, V] tensor in either
                # direction (tpukit/ops/layers.py cross_entropy_sum)
                logits = gpt.apply_head(params, local_cfg, x)
                loss_sum, count = cross_entropy_sum(logits, tgts)
                if with_accuracy:
                    valid = tgts != -100
                    correct = jnp.sum(
                        jnp.where(valid, jnp.argmax(logits, axis=-1) == tgts, False)
                    ).astype(jnp.float32)
                else:
                    correct = jnp.float32(0)
            return (
                jax.lax.psum(loss_sum, axes),
                jax.lax.psum(count, axes),
                jax.lax.psum(correct, axes),
            )

        loss_sum, count, correct = shard_map(
            local_loss,
            mesh=self.mesh,
            in_specs=(P(), batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(params, batch["input_ids"], batch["position_ids"], batch["mask"], targets)

        denom = jnp.maximum(count, 1.0)
        return loss_sum / denom, correct / denom * 100.0


class TensorParallel(Strategy):
    """Megatron-style tensor parallelism, expressed purely as GSPMD shardings
    (SURVEY §2.4 lists TP as absent from the reference; on TPU it is a
    natural extension — no new code path, just different PartitionSpecs).

    Per-layer rule over a `model` mesh axis (optionally x `data` for batch
    sharding): q/k/v kernels and the ffn up-projection shard their *output*
    (head / hidden) dimension — column parallel; the attention out-projection
    and ffn down-projection shard their *input* dimension — row parallel, so
    XLA inserts exactly one all-reduce after attention and one after the MLP,
    the classic Megatron pattern. The lm_head shards its vocab dimension and
    the token embedding its vocab rows. Dimensions that do not divide the
    axis stay replicated. Optimizer state mirrors the parameter shardings.
    """

    name = "tp"
    fused_head = False  # the vocab-sharded head wants the GSPMD matmul path
    comm_ops = ("all-reduce",)  # post-attention + post-MLP Megatron pair

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"model": -1})
        if "model" not in self.mesh.axis_names:
            raise ValueError("TensorParallel needs a 'model' mesh axis")
        self.model_size = self.mesh.shape["model"]

    def batch_spec(self) -> P:
        return P("data") if "data" in self.mesh.axis_names else P()

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        # Same aux_out contract as the base class so MoE configs fail with
        # the curated error from any entry point (ADVICE r5 #1).
        if cfg.num_experts > 0:
            raise ValueError(
                "TensorParallel does not support MoE configs (the Megatron "
                "column/row rules assume dense FFN kernels) — use "
                "ExpertParallel (main-moe.py) for num_experts > 0"
            )
        # The fused qkv matmul would concatenate kernels along their sharded
        # (column) axis, forcing a weight re-layout every step — keep the
        # three Megatron column-parallel matmuls instead.
        return super().loss_fn(
            params, cfg.replace(fuse_qkv=False), batch, targets, with_accuracy, rng
        )

    def _spec_for(self, names: tuple[str, ...], shape: tuple[int, ...]) -> P:
        def shard(dim: int) -> P:
            if shape[dim] % self.model_size:
                return P()  # undividable -> replicate
            spec = [None] * len(shape)
            spec[dim] = "model"
            return P(*spec)

        path = "/".join(names)
        if "attn" in names and names[-1] == "kernel":
            if any(k in names for k in ("q", "k", "v")):
                return shard(len(shape) - 1)  # column parallel
            if "out" in names:
                return shard(len(shape) - 2)  # row parallel
        if "attn" in names and names[-1] == "bias" and any(
            k in names for k in ("q", "k", "v")
        ):
            return shard(len(shape) - 1)
        if "ffn" in names:
            if "up" in names:
                return shard(len(shape) - 1)  # column (kernel & bias)
            if "down" in names and names[-1] == "kernel":
                return shard(len(shape) - 2)  # row
        if "lm_head" in names and names[-1] == "kernel":
            return shard(len(shape) - 1)
        if "token" in names:
            return shard(0)  # vocab rows
        del path
        return P()

    def state_sharding(self, state_shapes):
        def spec(path, leaf):
            names = tuple(
                k.key for k in path if isinstance(k, jax.tree_util.DictKey)
            )
            return NamedSharding(self.mesh, self._spec_for(names, leaf.shape))

        return jax.tree_util.tree_map_with_path(spec, state_shapes)

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        self._validate_comm_dtype(cfg)
        if cfg.num_experts > 0:
            raise ValueError(
                "TensorParallel does not support MoE configs (the Megatron "
                "column/row rules assume dense FFN kernels) — use "
                "ExpertParallel (main-moe.py) for num_experts > 0"
            )


class ExpertParallel(Strategy):
    """Expert parallelism for MoE configs (beyond-reference: the cookbook
    has neither MoE nor EP — SURVEY §2.4 marks the row "not required").

    Layout on a `(data, expert)` mesh: batch rows shard over BOTH axes,
    each expert-bank leaf (`ffn/experts/*`, leading axes `[layers,
    num_experts, ...]`) shards its EXPERT axis over `expert`, and — round
    10 — the dense trunk (embeddings, attention, norms, router, lm_head)
    plus its Adam moments shards FSDP-style over the whole `(data,
    expert)` world (same `min_shard_size` threshold as the FSDP strategy;
    tensors below it stay replicated; dim choice and the once-per-step
    trunk gather in `to_compute` are documented at `_spec_for` /
    `to_compute` — routing is discrete, so the trunk forward must stay
    bit-exact). Round-5 EP replicated the whole trunk on every device,
    which made trunk memory — 3x trunk params with Adam — the EP scaling
    ceiling.

    The token exchange depends on `dispatch`:

      - "a2a" (default): ExpertParallel injects `moe_dispatch="a2a"` +
        this mesh into the config at loss time, and the MoE FFN runs the
        explicit shard_map dataflow of tpukit/ops/moe_dispatch.py — local
        rows pack into `[E, B_local, C, D]` capacity buffers and move
        through a hand-placed `lax.all_to_all` pair over `expert`, forward
        AND backward (the formulation is its own transpose). This is the
        token all_to_all GPU MoE frameworks hand-write with NCCL, actually
        placed by hand.

      - "pallas": the "a2a" exchange with the local expert FFN computed by
        the fused grouped-expert GEMM of tpukit/ops/moe_gemm.py instead of
        the batched capacity einsums — the collectives (and the byte
        audit) are byte-for-byte the a2a path's; only the on-device FFN
        spelling changes. Meshless callers of moe_dispatch="pallas" get
        the dropless sorted dataflow instead; under EP the exchange's
        static per-peer payloads make capacity buffers structural.

      - "xla": the round-5 behavior — global dispatch/combine einsums with
        partitioning left to GSPMD. The FORWARD partitions into
        all_to_all-shaped collectives, but the BACKWARD of the dispatch
        einsum (`jvp(bsec,bsd->ebcd)/transpose`) does not: the round-5
        multichip dryrun log (MULTICHIP_r05.json) is full of
        `[SPMD] Involuntary full rematerialization` warnings there — GSPMD
        resolves the `(data, expert)` resharding by REPLICATING the tensor
        and re-partitioning it, exactly the traffic EP exists to avoid.
        Kept as the comparison/fallback spelling; the a2a path's step is
        asserted warning-free and all_to_all-only in tests and the dryrun.

    Gradient flow falls out of the specs either way: expert grads reduce
    over `data`, trunk grads reduce-scatter over `data` (FSDP) and psum
    over `expert`. Optimizer state mirrors the parameter placement, so a
    device holds only its experts' and its trunk shard's Adam moments.
    """

    name = "ep"
    # the a2a/pallas dispatch payload quantizes (--comm_dtype int8: packed
    # block-scaled buffers through the same all_to_all schedule); trunk
    # FSDP comm stays full precision — dispatch payload first
    quantized_comm = True

    def __init__(
        self, mesh: Mesh | None = None, dispatch: str = "a2a",
        min_shard_size: int = 100,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"expert": -1})
        if "expert" not in self.mesh.axis_names:
            raise ValueError("ExpertParallel needs an 'expert' mesh axis")
        if dispatch not in ("xla", "a2a", "pallas"):
            raise ValueError(
                f"dispatch must be 'xla', 'a2a' or 'pallas', got {dispatch!r}"
            )
        self.dispatch = dispatch
        self.min_shard_size = min_shard_size
        self.expert_size = self.mesh.shape["expert"]
        self.data_size = self.mesh.shape.get("data", 1)
        # expected HLO collectives (obs/xla telemetry): token dispatch/
        # combine round trips when experts actually span devices; trunk
        # FSDP all-gather/reduce-scatter when the data axis is real; grad
        # psum for whatever stays replicated.
        ops = {"all-reduce"}
        if self.expert_size > 1:
            ops.add("all-to-all")
        if self.data_size * self.expert_size > 1:
            # trunk FSDP: gather at use, scatter the grads; GSPMD also
            # moves small trunk reshards with collective-permutes
            ops.update({"all-gather", "reduce-scatter", "collective-permute"})
        self.comm_ops = tuple(sorted(ops))

    def batch_spec(self) -> P:
        axes = tuple(a for a in ("data", "expert") if a in self.mesh.axis_names)
        return P(axes)

    @property
    def batch_divisor(self) -> int:
        return self.data_size * self.expert_size

    def validate_config(self, cfg: gpt.GPTConfig) -> None:
        if cfg.num_experts <= 0:
            raise ValueError(
                "ExpertParallel requires an MoE config: pass --num_experts N "
                "(N > 0); dense models belong on the other strategies"
            )
        if cfg.num_experts % self.expert_size:
            raise ValueError(
                f"--num_experts {cfg.num_experts} must divide over the "
                f"{self.expert_size}-way expert mesh axis"
            )
        if cfg.comm_dtype != "f32" and self.dispatch == "xla":
            raise ValueError(
                f"--comm_dtype {cfg.comm_dtype} under ExpertParallel needs "
                f"the hand-placed exchange: use --moe_dispatch a2a or "
                f"pallas (the xla dispatch leaves its collectives to GSPMD, "
                f"which cannot carry the packed int8 payload)"
            )
        if cfg.grad_buckets > 0 and self.dispatch == "xla":
            raise ValueError(
                f"--grad_buckets {cfg.grad_buckets} under ExpertParallel "
                f"needs the hand-placed exchange: use --moe_dispatch a2a "
                f"or pallas (the xla dispatch leaves its collectives to "
                f"GSPMD — there is no hand-placed schedule to declare "
                f"overlap for)"
            )

    def to_compute(self, tree):
        """Gather the sharded dense trunk ONCE at the top of each jitted
        step (GSPMD all-gather from the sharding constraint), leaving the
        expert bank and the whole optimizer state sharded.

        This is the deliberate EPxFSDP numerics choice: if trunk weights
        stay sharded through the forward, GSPMD computes their matmuls as
        partial sums + all-reduce, and those reduction-order ulps flip
        discrete top-k ROUTING decisions — a dense model absorbs ulps, a
        router amplifies them into different experts (measured: ~3.5e-3
        first-step loss drift on the parity fixture). Gathering up front
        makes the trunk forward the bit-exact DDP computation, so EP
        parity holds at the dense tolerance, while the at-rest state — the
        memory ceiling round 5 hit: params + BOTH Adam moments, 3x trunk
        bytes replicated on every device — shrinks by the mesh size. The
        moments never gather; only trunk params pay one transient
        replicated copy per step, the standard ZeRO-3 gather-at-use
        trade."""
        if self.data_size * self.expert_size <= 1:
            return tree
        repl = NamedSharding(self.mesh, P())
        is_state = hasattr(tree, "params")
        params = tree.params if is_state else tree

        def gather(path, leaf):
            names = tuple(
                k.key for k in path if isinstance(k, jax.tree_util.DictKey)
            )
            if "experts" in names:
                return leaf
            return jax.lax.with_sharding_constraint(leaf, repl)

        params = jax.tree_util.tree_map_with_path(gather, params)
        return tree.replace(params=params) if is_state else params

    def _dispatch_cfg(self, cfg: gpt.GPTConfig) -> gpt.GPTConfig:
        """Config the loss actually runs with: the a2a/pallas dispatch impl
        + this mesh injected for MoE configs. Loss-time only — checkpoints,
        decode and the plain model surface never carry a mesh in their
        config."""
        if cfg.num_experts <= 0 or self.dispatch == "xla":
            return cfg
        return cfg.replace(moe_dispatch=self.dispatch, moe_mesh=self.mesh)

    def loss_fn(
        self, params, cfg: gpt.GPTConfig, batch, targets,
        with_accuracy: bool = False, rng=None, aux_out: list | None = None,
    ):
        return super().loss_fn(
            params, self._dispatch_cfg(cfg), batch, targets,
            with_accuracy=with_accuracy, rng=rng, aux_out=aux_out,
        )

    def dispatch_comm(self, cfg: gpt.GPTConfig, global_batch: int,
                      seq: int, backend: str | None = None) -> dict | None:
        """Expected per-device all-to-all payload for one step of the a2a
        or pallas dispatch (tpukit/ops/moe_dispatch.expected_a2a — the
        pallas dispatch rides the identical exchange, so the same closed
        form audits both) — the audit number fit()'s xla record and
        bench.py's moe_ep_comm probe compare against the compiled HLO.
        None for the xla dispatch (GSPMD's choices are measured, not
        predicted) and for dense configs. `backend` makes the byte
        expectation dtype-aware (XLA:CPU upcasts bf16 payloads to f32 on
        the wire) so the audit is exact on every backend; None keeps the
        nominal accelerator sizes."""
        if self.dispatch == "xla" or cfg.num_experts <= 0:
            return None
        from tpukit.ops.moe_dispatch import expected_a2a

        return expected_a2a(
            cfg, self.data_size, self.expert_size, global_batch, seq,
            backend=backend,
        )

    def overlap_comm(self, cfg: gpt.GPTConfig, param_shapes) -> dict | None:
        """EP's grad wire is already bucket-granular: the a2a exchange is
        hand-placed PER LAYER (dispatch + combine, forward and backward —
        4L a2as per train step), so --grad_buckets under EP changes no
        dataflow; any value >= 1 DECLARES the overlap audit instead. The
        declaration covers the 2L backward hops: each backward a2a has
        the other layers' weight-grad accumulation independent of it (the
        dW branches neither feed nor consume another layer's exchange),
        which is the compute the scheduler hides the wire behind. The
        forward chain is honestly serial (layer i+1's tokens need layer
        i's combine) and is not declared."""
        if cfg.grad_buckets < 1 or self.expert_size <= 1:
            return None
        if cfg.num_experts <= 0 or self.dispatch == "xla":
            return None
        return {"all-to-all": 2 * cfg.num_layers}

    def _spec_for(self, names: tuple[str, ...], shape: tuple[int, ...]) -> P:
        if "experts" in names:
            # stacked layout [num_layers, num_experts, ...]: expert axis 1
            spec = [None] * len(shape)
            spec[1] = "expert"
            return P(*spec)
        # Dense trunk: FSDP-style over the WHOLE (data x expert) world, with
        # the FSDP strategy's min-size threshold (norms/biases stay
        # replicated). Two deliberate differences from the dense FSDP rule,
        # both learned the hard way on the parity fixture:
        #   - never shard a kernel's -2 dim: that is the forward CONTRACTION
        #     dim of every trunk matmul, and GSPMD computes a
        #     contraction-sharded matmul as partial sums + all-reduce whose
        #     reduction-order ulps flip discrete top-k ROUTING decisions (a
        #     dense model absorbs ulps; a router amplifies them into
        #     different experts);
        #   - embedding TABLES shard their row (vocab/position) dim — rows
        #     are gathered by id, never contracted, and a feature-sharded
        #     table makes the take() backward's scatter-add reshard through
        #     an extra GSPMD all-to-all that would pollute the hand-placed
        #     dispatch traffic the comm audit counts.
        # Sharding the full world (not just `data`) both maximizes the
        # memory win and avoids the partial-mesh `last_tile_dim_replicate`
        # shardings that the round-5 log showed GSPMD resharding by
        # involuntary full rematerialization.
        world = self.data_size * self.expert_size
        if world <= 1:
            return P()
        size = 1
        for d in shape:
            size *= d
        if size < self.min_shard_size:
            return P()
        axes = tuple(a for a in ("data", "expert") if a in self.mesh.axis_names)
        if "embeddings" in names:
            # rows or nothing: an undividable table (e.g. a position table
            # at a +1 sequence length) stays replicated rather than
            # feature-sharded — the feature-sharded fallback would buy a
            # few KB and cost a scatter-add all-to-all in the take()
            # backward, polluting the hand-placed dispatch audit
            dim = 0 if shape[0] % world == 0 else None
        else:
            candidates = [
                i for i, d in enumerate(shape)
                if d % world == 0
                and not (len(shape) >= 2 and i == len(shape) - 2)
            ]
            dim = candidates[-1] if candidates else None
        if dim is None:
            return P()
        spec = [None] * len(shape)
        spec[dim] = axes
        return P(*spec)

    def state_sharding(self, state_shapes):
        def spec(path, leaf):
            names = tuple(
                k.key for k in path if isinstance(k, jax.tree_util.DictKey)
            )
            return NamedSharding(self.mesh, self._spec_for(names, leaf.shape))

        return jax.tree_util.tree_map_with_path(spec, state_shapes)
