"""Parallelism strategies as mesh + sharding rules.

This is the TPU-native re-design of the reference's L1 layer (SURVEY §2.4):
where the reference wraps the model object (`DDP(model)` main-ddp.py:55,
`FSDP(model, ...)` main-fsdp.py:64-69, `Pipe(...)` main-pipe.py:79-83), here
a *strategy object* owns a `Mesh` and emits `NamedSharding`s for the train
state and the batch. `jax.jit` + GSPMD then inserts the collectives the
reference got from NCCL:

  - DataParallel: params/opt-state replicated, batch sharded on the `data`
    axis -> XLA emits a gradient all-reduce over ICI (the twin of DDP's
    bucketed NCCL all-reduce fired by autograd hooks, main-ddp.py:55,124).
  - FSDP: every tensor of params/grads/opt-state >= `min_shard_size` elements
    is sharded along its largest divisible axis -> XLA emits per-tensor
    all-gather (forward/backward) and reduce-scatter (grad) — the twin of
    FullyShardedDataParallel with `size_based_auto_wrap_policy(
    min_num_params=100)` (main-fsdp.py:60-69), where the wrap threshold
    becomes a shard-size threshold. `cpu_offload=True` pins the sharded
    params/opt-state to host memory (twin of `CPUOffload(offload_params=
    True)`, main-fsdp.py:68).
  - Pipeline strategies live in tpukit/pipeline.py (they need a schedule,
    not just shardings) and subclass `Strategy`.

Every strategy also carries the default loss computation; the pipeline
overrides it with the micro-batched schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpukit import mesh as mesh_lib
from tpukit.model import gpt
from tpukit.ops.layers import cross_entropy_loss, masked_accuracy


def _sharding_tree(mesh: Mesh, spec_fn, tree_shapes):
    """Map `spec_fn(shape) -> PartitionSpec` over a pytree of ShapeDtypeStructs
    (or arrays), returning NamedShardings."""
    return jax.tree.map(lambda leaf: NamedSharding(mesh, spec_fn(leaf.shape)), tree_shapes)


class Strategy:
    """Base: single-device (twin of main-single.py: plain `.to(device)`,
    main-single.py:21,33 — here, a trivial 1-device mesh)."""

    name = "single"

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh(None)

    # -- sharding rules ----------------------------------------------------

    def param_spec(self, shape: tuple[int, ...]) -> P:
        return P()

    def batch_spec(self) -> P:
        return P()

    def state_sharding(self, state_shapes):
        return _sharding_tree(self.mesh, self.param_spec, state_shapes)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def to_compute(self, state):
        """Hook run at the top of each jitted step: move offloaded state into
        device memory. Identity unless a strategy offloads (FSDP
        cpu_offload)."""
        return state

    def replicated(self):
        return NamedSharding(self.mesh, P())

    # -- loss --------------------------------------------------------------

    def loss_fn(self, params, cfg: gpt.GPTConfig, batch, targets, with_accuracy: bool = False):
        """Default forward + masked CE (+ masked accuracy for eval).

        Under a sharded batch this single jitted function IS the distributed
        step: the mean over the global batch is the twin of DDP's gradient
        all-reduce and of the explicit eval `dist.all_reduce(..., AVG)`
        (main-ddp.py:159-160) — GSPMD inserts the psum.
        """
        logits = gpt.forward(
            params, cfg, batch["input_ids"], batch["position_ids"], batch["mask"]
        )
        loss = cross_entropy_loss(logits, targets)
        accuracy = masked_accuracy(logits, targets) if with_accuracy else jnp.float32(0)
        return loss, accuracy

    def describe(self) -> str:
        return f"{self.name} over mesh {dict(zip(self.mesh.axis_names, self.mesh.devices.shape))}"


class SingleDevice(Strategy):
    name = "single"


class DataParallel(Strategy):
    """Twin of the DDP recipe's parallelism (main-ddp.py:55): batch sharded
    over `data`, params replicated. The gradient psum is emitted by XLA from
    the replicated-param + sharded-batch specs."""

    name = "ddp"

    def __init__(self, mesh: Mesh | None = None):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"data": -1})

    def batch_spec(self) -> P:
        return P("data")


class FSDP(Strategy):
    """Twin of the FSDP recipe (main-fsdp.py:60-69): ZeRO-3-style sharding of
    params, grads and optimizer state over the `data` axis, via GSPMD."""

    name = "fsdp"

    # Twin of size_based_auto_wrap_policy(min_num_params=100): tensors below
    # the threshold stay replicated (main-fsdp.py:62).
    def __init__(self, mesh: Mesh | None = None, min_shard_size: int = 100, cpu_offload: bool = False):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh({"data": -1})
        self.min_shard_size = min_shard_size
        self.cpu_offload = cpu_offload

    def param_spec(self, shape: tuple[int, ...]) -> P:
        axis_size = self.mesh.shape["data"]
        size = 1
        for d in shape:
            size *= d
        if size < self.min_shard_size:
            return P()
        # shard the largest dimension divisible by the axis size
        candidates = [(d, i) for i, d in enumerate(shape) if d % axis_size == 0]
        if not candidates:
            return P()
        _, dim = max(candidates)
        spec = [None] * len(shape)
        spec[dim] = "data"
        return P(*spec)

    def state_sharding(self, state_shapes):
        shardings = _sharding_tree(self.mesh, self.param_spec, state_shapes)
        if self.cpu_offload:
            # Twin of CPUOffload(offload_params=True) (main-fsdp.py:68):
            # sharded state lives in host memory; XLA streams it in on use.
            # Host memory spaces are a TPU feature; on other backends the
            # flag degrades to plain FSDP with a warning (the reference's
            # CPUOffload is likewise CUDA-only).
            if self._offload_supported():
                shardings = jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"), shardings
                )
            else:
                import warnings

                warnings.warn(
                    "--cpu_offload needs a TPU backend with host memory "
                    "spaces; running plain FSDP instead",
                    stacklevel=2,
                )
        return shardings

    def _offload_supported(self) -> bool:
        return jax.default_backend() in ("tpu", "axon")

    def to_compute(self, state):
        """Stream host-pinned state into device HBM at the top of the step
        (the XLA twin of FSDP's CPUOffload H2D param streaming,
        main-fsdp.py:68). The step's out_shardings put the updated state
        back in host memory."""
        if not (self.cpu_offload and self._offload_supported()):
            return state

        def put(leaf):
            sharding = NamedSharding(
                self.mesh, self.param_spec(leaf.shape), memory_kind="device"
            )
            return jax.device_put(leaf, sharding)

        return jax.tree.map(put, state)

    def batch_spec(self) -> P:
        return P("data")
