"""Static interleaved-1F1B tick tables (round 22, ROADMAP #5).

The explicit-vjp 1F1B machine (tpukit/pipeline.py Pipeline1F1B) runs a
fixed tick program: every tick, every device executes one forward unit
and one backward unit, with out-of-range work masked to zero. Its bubble
is therefore the masked-work fraction, (2S-2)/(M+2S-2) at S stages and M
micros — the win over GPipe is activation MEMORY (depth bounded by the
stage count), not bubble time.

Interleaved virtual stages (Megatron-LM's interleaved 1F1B; *Scaling
Deep Learning Training with MPMD Pipeline Parallelism*, PAPERS.md) split
each device's layer block into V non-contiguous chunks — device d owns
global chunks d, d+S, d+2S, ... — so one "hop" of the pipeline is 1/V of
the per-device work and the warm-up/cool-down shrinks toward
(S-1)/(M*V) of the useful work at equal M.

This module is the schedule AUTHORITY: a pure-Python greedy list
scheduler that emits the per-tick, per-device job tables the tick
machine unrolls, plus the idle-work accounting bench.py reports and
tools/report.py gates (`--min_bubble_gain`). Keeping it jax-free means
the CI lane's fast step and the bench bubble table run without devices,
and the machine, the bench and the comm plan all read ONE table — the
collective-permute count in the compiled HLO is exactly
`sum(t.ship_fwd) + sum(t.ship_bwd)` because the machine emits one
ppermute per shipping tick and nothing else.

Schedule model (matches the machine's execution cost, which is what the
bubble accounting must price):

- A tick has a forward PHASE and/or a backward PHASE, chosen statically.
  SPMD executes every phase on every device (work for devices without a
  job that tick is masked, but still computed) — so a tick costs
  `has_fwd * t_f + has_bwd * t_b` on EVERY device, and idle work is
  "phase executed, no job". Pure-F warm-up and pure-B cool-down ticks
  are how interleaving beats the flat machine, whose every tick pays
  both phases.
- fwd(g, m) on device g % S needs fwd(g-1, m) shipped: executable from
  tick f(g-1, m) + 1. Chunk 0 ingests embeddings at its own tick.
- bwd(G-1, m) is self-triggered: the head+CE vjp runs at fwd(G-1, m)'s
  tick on the last device, so the deepest chunk's backward is ready the
  SAME tick. bwd(g, m) for g < G-1 needs the cotangent shipped:
  executable from b(g+1, m) + 1.
- One fwd job and one bwd job per device per tick, at most.
- In-flight micro-chunks per device settle at ~(G + S - d) in steady
  state (the fill depth before the first backward retires) — Megatron's
  documented memory cost of interleaving. The generator reports the
  exact buffer depth per (device, chunk) in `depth`; a hard in-flight
  cap is available (`max_in_flight`) but defaults OFF, because capping
  below the fill depth stalls micro 0's wavefront — the very forwards
  the schedule needs to trigger the first backward.

BACKWARD_COST prices a backward chunk-step relative to a forward one for
the idle-WORK (not idle-tick) accounting: the backward phase replays the
chunk forward (remat) and then runs the transpose, ~2 forward
equivalents. The gate compares fractions of the same weighting, so the
1F1B baseline bubble (2S-2)/(M+2S-2) is weight-independent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

BACKWARD_COST = 2.0


@dataclass(frozen=True)
class Tick:
    """One row of the static tick program. Per-device entries are tuples
    indexed by device (stage) id; None = no job (masked execution)."""

    # (chunk_local, micro, slot) per device, or None
    fwd: tuple
    bwd: tuple
    # forward activation / backward cotangent arriving at the START of
    # this tick (shipped by the previous tick): (chunk_local, slot) per
    # device, or None
    recv_fwd: tuple
    recv_bwd: tuple
    # does this tick's end ship a forward / backward ring payload?
    ship_fwd: bool = False
    ship_bwd: bool = False
    # micro ingested by device 0 (chunk 0) this tick, -1 = none
    ingest: int = -1
    # micro whose head+CE runs on the last device this tick, -1 = none;
    # head_slot is that job's activation slot (static: the last device's
    # fwd slot this tick) — the head's cotangent stashes there
    head: int = -1
    head_slot: int = -1
    # micro whose embedding-transpose runs on device 0 this tick (its
    # chunk-0 backward), -1 = none
    emb: int = -1

    @property
    def has_fwd(self) -> bool:
        return any(j is not None for j in self.fwd)

    @property
    def has_bwd(self) -> bool:
        return any(j is not None for j in self.bwd)


@dataclass(frozen=True)
class InterleavedSchedule:
    num_stages: int
    virtual: int
    num_micro: int
    ticks: tuple  # tuple[Tick]
    depth: int  # activation-buffer slots per (device, chunk)
    stats: dict = field(default_factory=dict)


def flat_1f1b_bubble(num_stages: int, num_micro: int) -> float:
    """Idle-work fraction of the EXISTING flat 1F1B tick machine
    (pipeline.py's lax.scan over M + 2S - 2 ticks, both phases every
    tick): each device does M useful forward and M useful backward
    chunk-steps out of T executed each, independent of phase weights."""
    ticks = num_micro + 2 * num_stages - 2
    return 1.0 - num_micro / ticks


def _bubble_fraction(f_ticks: int, b_ticks: int, num_stages: int,
                     num_micro: int, virtual: int,
                     backward_cost: float = BACKWARD_COST) -> float:
    """Idle-work fraction of an interleaved program: per device, every
    forward-phase tick executes one chunk-forward (cost 1) and every
    backward-phase tick one chunk-backward (cost backward_cost); M*V of
    each are useful."""
    useful = num_micro * virtual * (1.0 + backward_cost)
    executed = f_ticks + backward_cost * b_ticks
    return 1.0 - useful / executed


def build_schedule(num_stages: int, virtual: int, num_micro: int,
                   include_backward: bool = True,
                   max_in_flight: int | None = None) -> InterleavedSchedule:
    """Greedy list scheduler for the interleaved-1F1B tick program.

    Priorities: backward jobs prefer the oldest micro, deepest chunk
    (the retire chain is the critical path); forward jobs prefer the
    DEEPEST ready chunk, oldest micro — which reproduces Megatron's
    grouped warm-up (chunk 0 micros 0..S-1, then chunk 1 micros 0..S-1,
    ...) and keeps micro 0's wavefront tight so the first backward fires
    at tick G-1. `include_backward=False` emits the forward-only program
    (the interleaved eval path). `max_in_flight` optionally caps forward-
    executed-but-not-retired chunk-steps per device (activation memory);
    None = uncapped (a cap below the fill depth stalls the wavefront
    that triggers the first backward and deadlocks the schedule).
    """
    S, V, M = num_stages, virtual, num_micro
    if S < 1 or V < 1 or M < 1:
        raise ValueError(f"need num_stages/virtual/num_micro >= 1, got "
                         f"{S}/{V}/{M}")
    G = S * V
    if max_in_flight is None:
        max_in_flight = G * M + 1  # uncapped
    f_tick: dict = {}  # (g, m) -> tick index
    b_tick: dict = {}
    # slot pools, per (device, chunk_local): slot ids alloc'd at the tick
    # the activation lands (arrival, or execution for ingest), freed the
    # tick after its backward consumes it
    free_slots: dict = {}
    next_slot: dict = {}
    slot_of: dict = {}  # (g, m) -> slot id

    def _alloc(d: int, c: int, g: int, m: int) -> int:
        pool = free_slots.setdefault((d, c), [])
        if pool:
            s = pool.pop()
        else:
            s = next_slot.get((d, c), 0)
            next_slot[(d, c)] = s + 1
        slot_of[(g, m)] = s
        return s

    total_jobs = G * M
    ticks: list = []
    in_flight = [0] * S  # fwd executed, bwd not yet, per device
    pending_recv_f: list = [None] * S  # stash targets for last ship_fwd
    pending_recv_b: list = [None] * S
    t = 0
    limit = 4 * (G + M) * (V + 2) + 64  # deadlock backstop
    while len(b_tick) < total_jobs if include_backward else len(f_tick) < total_jobs:
        if t > limit:
            raise RuntimeError(
                f"interleaved schedule failed to converge at S={S} V={V} "
                f"M={M} (scheduler bug)")
        recv_f = tuple(pending_recv_f)
        recv_b = tuple(pending_recv_b)
        pending_recv_f = [None] * S
        pending_recv_b = [None] * S

        # -- forward assignments -----------------------------------------
        fwd: list = [None] * S
        ingest = -1
        head = -1
        head_slot = -1
        for d in range(S):
            if include_backward and in_flight[d] >= max_in_flight:
                continue
            best = None
            for c in range(V - 1, -1, -1):  # deepest chunk first
                g = c * S + d
                for m in range(M):
                    if (g, m) in f_tick:
                        continue
                    if g > 0 and f_tick.get((g - 1, m), t + 1) + 1 > t:
                        continue
                    best = (c, g, m)
                    break  # oldest micro of this chunk
                if best is not None:
                    break
            if best is None:
                continue
            c, g, m = best
            f_tick[(g, m)] = t
            in_flight[d] += 1
            if g == 0:
                s = _alloc(d, c, g, m)  # ingest: stashed at execution
                ingest = m
            else:
                s = slot_of[(g, m)]  # alloc'd at arrival
            fwd[d] = (c, m, s)
            if g == G - 1:
                head = m
                head_slot = s
            if not include_backward:
                # forward-only (eval): the stash is dead once the chunk
                # forward consumed it — recycle immediately
                free_slots.setdefault((d, c), []).append(slot_of.pop((g, m)))
        ship_fwd = any(
            fwd[d] is not None and fwd[d][0] * S + d < G - 1 for d in range(S)
        )
        if ship_fwd:
            for d in range(S):
                if fwd[d] is None:
                    continue
                g = fwd[d][0] * S + d
                if g >= G - 1:
                    continue
                # consumer: chunk g+1 on device (d+1) % S — pre-alloc its
                # stash slot now; the payload lands at tick t+1
                nd, nc = (g + 1) % S, (g + 1) // S
                m = fwd[d][1]
                s = _alloc(nd, nc, g + 1, m)
                pending_recv_f[nd] = (nc, s)

        # -- backward assignments ----------------------------------------
        bwd: list = [None] * S
        emb = -1
        if include_backward:
            for d in range(S):
                best = None
                for m in range(M):  # oldest micro first
                    for c in range(V - 1, -1, -1):  # deepest chunk first
                        g = c * S + d
                        if (g, m) in b_tick or (g, m) not in f_tick:
                            continue
                        if g == G - 1:
                            if f_tick[(g, m)] > t:
                                continue
                        elif b_tick.get((g + 1, m), t + 1) + 1 > t:
                            continue
                        best = (c, g, m)
                        break
                    if best is not None:
                        break
                if best is None:
                    continue
                c, g, m = best
                b_tick[(g, m)] = t
                in_flight[d] -= 1
                s = slot_of.pop((g, m))
                free_slots.setdefault((d, c), []).append(s)
                bwd[d] = (c, m, s)
                if g == 0:
                    emb = m
            ship_bwd = any(
                bwd[d] is not None and bwd[d][0] * S + d > 0 for d in range(S)
            )
            if ship_bwd:
                for d in range(S):
                    if bwd[d] is None:
                        continue
                    g = bwd[d][0] * S + d
                    if g <= 0:
                        continue
                    # consumer: bwd(g-1, m) on device (d-1) % S; the
                    # cotangent shares the forward's activation slot
                    pd = (g - 1) % S
                    pending_recv_b[pd] = ((g - 1) // S, slot_of[(g - 1, bwd[d][1])])
        else:
            ship_bwd = False

        tick = Tick(fwd=tuple(fwd), bwd=tuple(bwd), recv_fwd=recv_f,
                    recv_bwd=recv_b, ship_fwd=ship_fwd, ship_bwd=ship_bwd,
                    ingest=ingest, head=head, head_slot=head_slot, emb=emb)
        if not (tick.has_fwd or tick.has_bwd):
            raise RuntimeError(
                f"interleaved schedule deadlocked at tick {t} "
                f"(S={S} V={V} M={M})")
        ticks.append(tick)
        t += 1

    depth = max(next_slot.values(), default=1)
    f_ticks = sum(1 for tk in ticks if tk.has_fwd)
    b_ticks = sum(1 for tk in ticks if tk.has_bwd)
    stats = {
        "ticks": len(ticks),
        "fwd_phase_ticks": f_ticks,
        "bwd_phase_ticks": b_ticks,
        "depth": depth,
        "ship_fwd_ticks": sum(1 for tk in ticks if tk.ship_fwd),
        "ship_bwd_ticks": sum(1 for tk in ticks if tk.ship_bwd),
        "bubble_frac": (
            _bubble_fraction(f_ticks, b_ticks, S, M, V)
            if include_backward else float("nan")
        ),
        "flat_1f1b_bubble_frac": flat_1f1b_bubble(S, M),
    }
    return InterleavedSchedule(
        num_stages=S, virtual=V, num_micro=M, ticks=tuple(ticks),
        depth=depth, stats=stats,
    )


@lru_cache(maxsize=64)
def cached_schedule(num_stages: int, virtual: int, num_micro: int,
                    include_backward: bool = True) -> InterleavedSchedule:
    """The machine traces one program per (S, V, M, phase) — cache the
    table so retracing (jit cache misses, eval + train in one run) does
    not regenerate it."""
    return build_schedule(num_stages, virtual, num_micro,
                          include_backward=include_backward)


def bubble_table(num_stages: int, virtuals=(1, 2, 4), micros=(4, 8, 16)):
    """The measured bubble-fraction table the bench record carries:
    one row per (V, M). V=1 rows price the EXISTING flat machine
    (pipeline.py's scan — that is what `--virtual_stages 1` runs);
    V > 1 rows come from the generated tick tables."""
    rows = []
    for m in micros:
        for v in virtuals:
            if v == 1:
                rows.append({
                    "virtual_stages": 1, "micro": m,
                    "ticks": m + 2 * num_stages - 2,
                    "bubble_frac": round(flat_1f1b_bubble(num_stages, m), 4),
                })
            else:
                sched = build_schedule(num_stages, v, m)
                rows.append({
                    "virtual_stages": v, "micro": m,
                    "ticks": sched.stats["ticks"],
                    "fwd_phase_ticks": sched.stats["fwd_phase_ticks"],
                    "bwd_phase_ticks": sched.stats["bwd_phase_ticks"],
                    "depth": sched.depth,
                    "bubble_frac": round(sched.stats["bubble_frac"], 4),
                })
    return rows
