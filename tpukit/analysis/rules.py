"""hlolint rule engine — named anti-pattern rules over the HLO IR.

Every rule here is a regression this repo actually hit, promoted from a
bespoke assertion scattered across the tree into one named, reusable
check (the catalog below cites the original incident). `lint_module`
runs them all over a parsed module (+ the compile's captured stderr and
the caller's declared CommPlan) and returns findings; `assert_clean`
turns error findings into the RAISE discipline the dryrun and CI lanes
enforce.

Rule catalog (and where each one came from):

  comm-plan          The declared CommPlan (grad_comm / dispatch_comm /
                     decode_step_comm unified, analysis/plan.py) diffed
                     against the module's collectives — the round-10
                     "hand-scheduling means predicting" discipline, one
                     spelling instead of four comparison loops.
  involuntary-remat  `[SPMD] Involuntary full rematerialization` in the
                     captured compiler stderr: GSPMD replicated a tensor
                     it could not reshard (the round-5 EP einsum dispatch,
                     MULTICHIP_r05). Zero is the bar for any hand-placed
                     schedule.
  s32-index-plumbing Integer-dtype collectives serving scatter/gather
                     index exchange — GSPMD partitioning a batched
                     scatter emits s32 collective-permute/all-gather
                     plumbing (the round-14 decode buf scatter, rewritten
                     as a one-hot select). s8/u8 payloads are quantized
                     data, never indices, and small integer psums
                     (token counts) sit under the byte threshold. Error
                     on hand-scheduled programs (a CommPlan is declared),
                     warn on GSPMD-placed worlds, where small id gathers
                     for row-sharded tables are the partitioner's
                     legitimate cost (the f32 FSDP embedding `_take`).
  wire-upcast        A collective element dtype wider than the declared
                     wire dtype (--comm_dtype / the plan's per-op wire
                     entry) — the round-12 finding that XLA:CPU's float
                     normalization moves bf16 payloads at f32, now a
                     named rule instead of a renderer soft-excuse. int8
                     payloads are upcast-immune: any widening there is a
                     hard error on every backend.
  donation-dropped   Donated arguments missing from the executable's
                     input_output_alias table — silent 2x HBM, and the
                     round-14 jaxlib class where executables DESERIALIZED
                     from the persistent compile cache mis-alias donated
                     buffers (serve/decode.py strips donation for exactly
                     that reason).
  overlap            Two halves (ROADMAP #5, promoted round 18). The
                     reporting half: for each async `-start`/`-done`
                     pair, does compute actually sit between them
                     (severity "info", unchanged since round 16). The
                     GATE half: when the CommPlan DECLARES an overlap
                     schedule (plan.overlap = {op: K}, set by
                     --grad_buckets worlds via Strategy.overlap_comm),
                     at least K collectives of each declared kind must
                     each have >= OVERLAP_MIN_CONCURRENT compute
                     instructions INDEPENDENT of them in the dataflow
                     (HloModule.concurrent_compute — neither ancestor
                     nor descendant, so a scheduler may run them between
                     the wire's start and done on any backend, async
                     pairs or not). Shortfall is an error: a world that
                     claims bucketed overlap must show the structure.
                     On declared worlds an async pair of a declared op
                     with NOTHING between start and done also errors —
                     but only when its own cone shows overlap was
                     AVAILABLE (the async form was bought and wasted);
                     a dataflow-serial pair of the same op kind (EP's
                     forward dispatch hops) stays info.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpukit.analysis import hlo_ir
from tpukit.analysis.hlo_ir import HloModule, collective_summary
from tpukit.analysis.plan import CommPlan

# The GSPMD partitioner's last-resort warning (spmd_partitioner.cc): it
# could not move a tensor between two shardings efficiently, so it
# REPLICATES the full tensor and re-partitions — for MoE dispatch that is
# exactly the all-device traffic expert parallelism exists to avoid.
INVOLUNTARY_REMAT = "Involuntary full rematerialization"

# Integer collective payloads smaller than this are scalar bookkeeping
# (token counts, loop carries), not index plumbing.
S32_PLUMBING_MIN_BYTES = 256

# A declared-overlap collective counts as overlappable when at least this
# many compute instructions are independent of it (concurrent_compute).
# Post-fusion a "compute instruction" is typically a whole fused kernel.
# Calibrated on the audited worlds: a SERIAL schedule's one flattened
# payload shows 7-9 independent fusions (the rng/token-count/loss-scalar
# residue — roughly constant across model shapes), while the smallest
# genuine bucket/backward wire measured 41+ and GROWS with the model
# (every other bucket's backward is independent of it). 16 sits between
# with margin both ways; the gate's job is to catch serial schedules
# claiming overlap, not to grade schedulers.
OVERLAP_MIN_CONCURRENT = 16

SEVERITIES = ("error", "warn", "info")


@dataclass
class Finding:
    """One rule verdict. `severity` "error" findings fail `assert_clean`
    (the dryrun/CI RAISE discipline); "warn" renders loudly but passes;
    "info" is reporting (the overlap audit today)."""

    rule: str
    severity: str
    message: str
    computation: str | None = None
    instruction: str | None = None
    data: dict = field(default_factory=dict)

    def to_record(self, **common) -> dict:
        """JSONL row (kind="hlolint", DESIGN.md §6)."""
        rec = {"kind": "hlolint", "rule": self.rule,
               "severity": self.severity, "message": self.message}
        if self.computation:
            rec["computation"] = self.computation
        if self.instruction:
            rec["instruction"] = self.instruction
        if self.data:
            rec["data"] = self.data
        rec.update(common)
        return rec


def count_involuntary_remat(text: str) -> int:
    """Number of `[SPMD] Involuntary full rematerialization` warnings in a
    compiler log / captured stderr — each one is a tensor GSPMD gave up on
    and resolved by replicate-then-repartition. Zero is the bar for any
    step whose collectives are hand-placed."""
    return text.count(INVOLUNTARY_REMAT)


# -- individual rules -------------------------------------------------------


def _rule_comm_plan(module: HloModule, ctx: dict) -> list[Finding]:
    plan: CommPlan | None = ctx.get("plan")
    if plan is None:
        return []
    measured = collective_summary(module)
    out = []
    for op, exp in sorted(plan.ops.items()):
        got = measured.get(op, {"count": 0, "bytes": 0})
        if got["count"] != exp["count"] or got["bytes"] != exp["bytes"]:
            out.append(Finding(
                rule="comm-plan", severity="error",
                message=(
                    f"{plan.label}: {op} measured x{got['count']} "
                    f"{got['bytes']}B vs declared x{exp['count']} "
                    f"{exp['bytes']}B"
                ),
                data={"op": op, "measured": got, "expected": dict(exp)},
            ))
    if plan.exhaustive:
        for op, got in sorted(measured.items()):
            if op not in plan.ops:
                out.append(Finding(
                    rule="comm-plan", severity="error",
                    message=(
                        f"{plan.label}: unplanned {op} x{got['count']} "
                        f"{got['bytes']}B (plan is exhaustive — every "
                        f"collective must be declared)"
                    ),
                    data={"op": op, "measured": got},
                ))
    return out


def _rule_involuntary_remat(module: HloModule, ctx: dict) -> list[Finding]:
    n = count_involuntary_remat(ctx.get("compiler_stderr") or "")
    if not n:
        return []
    return [Finding(
        rule="involuntary-remat", severity="error",
        message=(
            f"compile emitted {n} '[SPMD] {INVOLUNTARY_REMAT}' warning(s) "
            f"— GSPMD fell back to replicate-then-repartition (the round-5 "
            f"EP dispatch regression); hand-placed collectives must make "
            f"this zero"
        ),
        data={"count": n},
    )]


def _rule_s32_index_plumbing(module: HloModule, ctx: dict) -> list[Finding]:
    # The zero bar applies to HAND-SCHEDULED programs (a CommPlan was
    # declared): there, integer collectives mean GSPMD partitioned a
    # scatter/gather through index exchange behind the schedule's back.
    # GSPMD-placed worlds (no plan) legitimately carry small id gathers —
    # e.g. the f32 FSDP world all-gathers the batch-sharded token ids so
    # every shard of the row-sharded embedding table can run its local
    # `_take` gather and scatter-add — so those report as "warn": visible
    # in the renderer, not a CI failure.
    severity = "error" if ctx.get("plan") is not None else "warn"
    out = []
    for instr in module.collectives():
        int_bytes = sum(
            b for dt, b in _payload_dtypes(instr)
            if dt in hlo_ir.INDEX_DTYPES
        )
        if int_bytes <= S32_PLUMBING_MIN_BYTES:
            continue
        out.append(Finding(
            rule="s32-index-plumbing", severity=severity,
            message=(
                f"{instr.opcode} %{instr.name} moves {int_bytes}B of "
                f"integer payload — GSPMD index plumbing for a partitioned "
                f"scatter/gather (the round-14 decode buf scatter class; "
                f"rewrite the scatter as a one-hot select or reshard the "
                f"indices)"
            ),
            computation=instr.computation, instruction=instr.name,
            data={"op": instr.base_op, "int_bytes": int_bytes,
                  "dtypes": sorted(instr.result_dtypes())},
        ))
    return out


def _payload_dtypes(instr) -> list[tuple[str, int]]:
    """(dtype, bytes) of the real payload arrays — async ctx scalars
    excluded AND the operand-alias half of async `-start` tuples dropped
    (hlo_ir.payload_shapes), so wire-upcast and s32-plumbing never price
    an aliased operand as payload."""
    return hlo_ir.payload_shapes(
        instr.raw_shape, instr.base_op, instr.is_start
    )


def _rule_wire_upcast(module: HloModule, ctx: dict) -> list[Finding]:
    plan: CommPlan | None = ctx.get("plan")
    if plan is None or not plan.wire:
        return []
    backend = ctx.get("backend")
    out = []
    for instr in module.collectives():
        expected = plan.wire.get(instr.base_op)
        if expected is None:
            continue
        exp_size = hlo_ir.itemsize(expected) or 4
        for dt, b in _payload_dtypes(instr):
            size = hlo_ir.itemsize(dt)
            if size is None or size <= exp_size:
                continue
            cpu_bf16 = (expected == "bf16" and dt == "f32"
                        and backend == "cpu")
            out.append(Finding(
                rule="wire-upcast",
                # the known XLA:CPU float normalization is named, not
                # silent — but it is the backend's doing, not a schedule
                # regression, so it warns instead of failing CI
                severity="warn" if cpu_bf16 else "error",
                message=(
                    f"{instr.opcode} %{instr.name} moves {dt} payload, "
                    f"declared wire dtype is {expected}"
                    + (" (XLA:CPU float normalization upcasts bf16 "
                       "payloads to f32 on the wire — the round-12 "
                       "finding)" if cpu_bf16 else
                       " — the payload travels wider than the config "
                       "promised")
                ),
                computation=instr.computation, instruction=instr.name,
                data={"op": instr.base_op, "declared": expected,
                      "actual": dt, "bytes": b},
            ))
            break  # one finding per instruction
    return out


def _rule_donation_dropped(module: HloModule, ctx: dict) -> list[Finding]:
    expect = ctx.get("expect_donated")
    if not expect:
        return []
    aliased = module.aliased_params()
    missing = sorted(set(range(int(expect))) - aliased)
    if not missing:
        return []
    shown = ", ".join(str(p) for p in missing[:8])
    more = f" (+{len(missing) - 8} more)" if len(missing) > 8 else ""
    return [Finding(
        rule="donation-dropped", severity="error",
        message=(
            f"{len(missing)} of {expect} donated parameters missing from "
            f"the input_output_alias table (params {shown}{more}) — "
            f"donated state that does not alias is a silent 2x HBM "
            f"footprint"
            + ("; an EMPTY table on a donated jit is also the round-14 "
               "deserialized-executable mis-alias class"
               if not aliased else "")
        ),
        data={"expected": int(expect), "aliased": len(aliased),
              "missing": missing[:32]},
    )]


def _rule_overlap(module: HloModule, ctx: dict) -> list[Finding]:
    plan: CommPlan | None = ctx.get("plan")
    declared = getattr(plan, "overlap", None) if plan is not None else None
    out = []
    for pair in module.async_pairs():
        # On an overlap-declared world, an empty declared-op pair is a
        # regression ONLY when the pair provably COULD have overlapped:
        # its independent-compute cone clears the bar yet the schedule
        # placed nothing between start and done (the async form was
        # bought for exactly this wire and wasted). A pair whose cone is
        # empty-ish stays info — EP's forward dispatch hops are honestly
        # serial by dataflow and share the declared op KIND with the
        # backward hops the declaration actually covers; erroring on
        # them would fail worlds for a schedule they never promised.
        gate = bool(declared) and pair.start.base_op in (declared or {})
        could_overlap = (
            gate and not pair.overlapped
            and module.concurrent_compute(pair.start)
            >= OVERLAP_MIN_CONCURRENT
        )
        severity = "error" if could_overlap else "info"
        out.append(Finding(
            rule="overlap", severity=severity,
            message=(
                f"{pair.start.opcode} %{pair.start.name}: "
                f"{pair.compute_between} compute op(s) between start and "
                f"done — "
                + ("overlapped" if pair.overlapped
                   else "NO overlap (the pair completes back-to-back; "
                        "the async form bought nothing"
                        + (", with independent compute AVAILABLE on a "
                           "world that DECLARED bucketed overlap)"
                           if could_overlap else ")"))
            ),
            computation=pair.start.computation,
            instruction=pair.start.name,
            data={"op": pair.start.base_op,
                  "compute_between": pair.compute_between,
                  "between": len(pair.between),
                  "overlapped": pair.overlapped},
        ))
    if not declared:
        return out
    # The gate half: the declared bucket wires must be independently
    # schedulable. Measured in the dataflow (concurrent_compute), so the
    # verdict is identical whether the backend prints async pairs (TPU)
    # or sync collectives (XLA:CPU) — a serial one-payload-after-backward
    # schedule fails it on both.
    for op, need in sorted(declared.items()):
        colls = [i for i in module.collectives() if i.base_op == op]
        conc = {i.name: module.concurrent_compute(i) for i in colls}
        hidden = [n for n, c in conc.items() if c >= OVERLAP_MIN_CONCURRENT]
        occupancy = sorted(conc.values())
        data = {
            "op": op, "declared": int(need), "measured": len(colls),
            "overlappable": len(hidden),
            "min_concurrent": occupancy[0] if occupancy else 0,
            "max_concurrent": occupancy[-1] if occupancy else 0,
            "threshold": OVERLAP_MIN_CONCURRENT,
        }
        if len(hidden) < int(need):
            out.append(Finding(
                rule="overlap", severity="error",
                message=(
                    f"{plan.label}: declared {need} overlap-scheduled {op} "
                    f"bucket wire(s), only {len(hidden)} of {len(colls)} "
                    f"have >= {OVERLAP_MIN_CONCURRENT} independent compute "
                    f"op(s) to hide behind (per-op concurrency "
                    f"{occupancy}) — the schedule is serial where it "
                    f"claims to overlap"
                ),
                data=data,
            ))
        else:
            out.append(Finding(
                rule="overlap", severity="info",
                message=(
                    f"{plan.label}: overlap gate ok — {len(hidden)}/"
                    f"{len(colls)} {op} wire(s) independently schedulable "
                    f"(declared {need}, min concurrent compute "
                    f"{data['min_concurrent']})"
                ),
                data=data,
            ))
    return out


RULES = {
    "comm-plan": _rule_comm_plan,
    "involuntary-remat": _rule_involuntary_remat,
    "s32-index-plumbing": _rule_s32_index_plumbing,
    "wire-upcast": _rule_wire_upcast,
    "donation-dropped": _rule_donation_dropped,
    "overlap": _rule_overlap,
}


def lint_module(
    module: HloModule,
    *,
    plan: CommPlan | None = None,
    compiler_stderr: str = "",
    backend: str | None = None,
    expect_donated: int | None = None,
    waive: tuple[str, ...] = (),
) -> list[Finding]:
    """Run every rule over a parsed module. `waive` names rules to skip
    (a lint must be silenceable per call site, loudly — the dryrun prints
    what it waived). Findings come back error-first."""
    unknown = set(waive) - set(RULES)
    if unknown:
        raise ValueError(
            f"unknown hlolint rule(s) in waiver: {sorted(unknown)} — "
            f"known: {sorted(RULES)}"
        )
    ctx = {
        "plan": plan,
        "compiler_stderr": compiler_stderr,
        "backend": backend,
        "expect_donated": expect_donated,
    }
    findings: list[Finding] = []
    for name, rule in RULES.items():
        if name in waive:
            continue
        findings.extend(rule(module, ctx))
    order = {s: i for i, s in enumerate(SEVERITIES)}
    findings.sort(key=lambda f: (order.get(f.severity, 99), f.rule))
    return findings


def lint_text(text: str, **kwargs) -> list[Finding]:
    """Parse + lint in one call (the CLI / fixture path)."""
    return lint_module(hlo_ir.parse_hlo(text), **kwargs)


def summarize(findings: list[Finding]) -> dict:
    """Compact verdict for a JSONL record (fit()'s kind="xla" row):
    error/warn counts, the violated rule names, and the overlap tally."""
    errors = [f for f in findings if f.severity == "error"]
    warns = [f for f in findings if f.severity == "warn"]
    # async-pair reports carry compute_between; the round-18 gate verdicts
    # carry a declared count instead — summarized separately so a record
    # reader can tell "pairs seen" from "gate measured"
    pairs = [f for f in findings
             if f.rule == "overlap" and "compute_between" in f.data]
    gates = [f for f in findings
             if f.rule == "overlap" and "declared" in f.data]
    out = {
        "clean": not errors,
        "errors": len(errors),
        "warnings": len(warns),
        "violations": sorted({f.rule for f in errors}),
    }
    if warns:
        out["warned"] = sorted({f.rule for f in warns})
    if pairs:
        out["overlap"] = {
            "pairs": len(pairs),
            "overlapped": sum(
                1 for f in pairs if f.data.get("overlapped")
            ),
        }
    if gates:
        out["overlap_gate"] = {
            "declared": sum(f.data["declared"] for f in gates),
            "overlappable": sum(f.data["overlappable"] for f in gates),
            "ok": all(f.severity != "error" for f in gates),
        }
    return out


def assert_clean(findings: list[Finding], label: str = "") -> None:
    """RAISE on any error finding — the dryrun/CI discipline. The message
    carries every error so a red MULTICHIP record names the regression."""
    errors = [f for f in findings if f.severity == "error"]
    if not errors:
        return
    lines = "\n".join(f"  [{f.rule}] {f.message}" for f in errors)
    raise AssertionError(
        f"hlolint: {len(errors)} violation(s)"
        + (f" in {label}" if label else "") + f":\n{lines}"
    )
