"""tpukit.analysis — structured static analysis of compiled programs.

Three layers (docs/DESIGN.md §15):

  - `hlo_ir`: parse optimized HLO text into computations → instructions
    with shapes/dtypes, while-body membership, async start/done pairing
    and the executable's input–output alias table. jax-free.
  - `plan`: CommPlan — the declared collective schedule (grad_comm /
    dispatch_comm / decode_step_comm unified behind one interface).
  - `rules`: the named anti-pattern rules + lint driver; every rule is a
    regression this repo hit, with the incident cited in its docstring.

`tools/hlolint.py` is the CLI; `__graft_entry__.dryrun_multichip` and
fit()'s kind="xla" record invoke the same engine.
"""

from tpukit.analysis.hlo_ir import (  # noqa: F401
    COLLECTIVE_OPS,
    Alias,
    AsyncPair,
    Computation,
    HloModule,
    Instruction,
    collective_summary,
    parse_hlo,
)
from tpukit.analysis.plan import (  # noqa: F401
    CommPlan,
    decode_comm_plan,
    fleet_decode_comm_plan,
    ring_wire_bytes,
    train_comm_plan,
)
from tpukit.analysis.rules import (  # noqa: F401
    INVOLUNTARY_REMAT,
    RULES,
    Finding,
    assert_clean,
    count_involuntary_remat,
    lint_module,
    lint_text,
    summarize,
)
