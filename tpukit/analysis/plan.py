"""CommPlan — the declared collective schedule of one compiled program.

Rounds 10–15 accumulated four closed-form comm predictions, each with its
own shape and its own comparison loop: `Strategy.grad_comm` (quantized
DDP/FSDP grad wire), `ExpertParallel.dispatch_comm` (the MoE a2a
exchange), `serve.decode.decode_step_comm` (the TP decode step) and
`moe_dispatch.expected_a2a` under them. The dryrun, fit()'s xla record,
bench probes and four test files each re-spelled "fetch the expectation,
index the measured dict, compare count and bytes". A CommPlan is that
expectation normalized once: {op: {count, bytes}} plus, where the
formula knows it, the wire element dtype each op's payload must travel
at — so the rule engine (analysis/rules.py) diffs EVERY audited program
the same way and `wire-upcast` has a declared dtype to check against.

`exhaustive=True` means the plan IS the program's whole collective set
(the decode audit: measured == expected, nothing else tolerated);
False means the plan covers only the hand-placed ops and GSPMD's own
scalar psums etc. ride alongside unchecked.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class CommPlan:
    """Declared per-step collective expectation for one compiled program."""

    label: str
    # op kind -> {"count": int, "bytes": int} (result-payload convention,
    # the numbers obs.xla.collective_bytes reports)
    ops: dict[str, dict] = field(default_factory=dict)
    # op kind -> HLO element type its payload must travel at ("s8", "f32",
    # "bf16"); only ops whose formula fixes the dtype appear here.
    # SCOPE: a wire entry asserts that EVERY collective of that op kind in
    # the program travels at (or under) the declared dtype — declare a
    # kind here only when the plan owns all of its instances (true for
    # the quantized DDP/FSDP/EP programs today: comm-plan's exact count
    # check would flag a surplus same-kind collective anyway, and the
    # wire rule then names the dtype drift rather than leaving it inside
    # an opaque byte mismatch).
    wire: dict[str, str] = field(default_factory=dict)
    # True: measured collectives must equal `ops` exactly, surplus kinds
    # are violations (the decode audit). False: only the kinds in `ops`
    # are checked (train worlds, where GSPMD's loss/count psums coexist).
    exhaustive: bool = False
    # nominal comm dtype the run declared (--comm_dtype), for reporting
    comm_dtype: str = "f32"
    # Overlap declaration (round 18, --grad_buckets): {op: K} — at least
    # K collectives of that kind must each have independent compute the
    # scheduler can hide them behind (Strategy.overlap_comm). None = the
    # serial schedule; the hlolint `overlap` rule stays reporting-only.
    # With a declaration the rule GATES (severity error on shortfall) —
    # a world that claims bucketed overlap must show the structure.
    overlap: dict | None = None

    def expected(self, op: str) -> dict:
        return self.ops.get(op, {"count": 0, "bytes": 0})


def _wire_dtype_of(comm_dtype: str) -> str:
    return {"int8": "s8", "bf16": "bf16"}.get(comm_dtype, "f32")


# expected_a2a's wire marker speaks numpy dtype names for compute dtypes
# ("float32") and its own tag for packed payloads; HLO speaks "f32"/"s8".
_WIRE_TO_HLO = {
    "s8-packed": "s8", "int8": "s8",
    "float32": "f32", "f32": "f32",
    "bfloat16": "bf16", "bf16": "bf16",
    "float16": "f16", "f16": "f16",
    "float64": "f64", "f64": "f64",
}


def train_comm_plan(strategy, cfg, *, param_shapes=None, global_batch=None,
                    seq=None, backend=None, phase="train") -> CommPlan | None:
    """The unified train-step plan for a strategy+config: grad_comm
    (quantized DDP/FSDP) and dispatch_comm (EP a2a/pallas) folded into one
    CommPlan, or None when the strategy hand-places nothing (plain GSPMD
    worlds are measured, not predicted).

    `param_shapes` feeds grad_comm; `global_batch`/`seq` feed
    dispatch_comm — pass what the strategy needs, the other pair may stay
    None. `phase="eval"` builds the forward-only plan (no grad wire, the
    dispatch's eval entry). Byte expectations are backend-aware exactly as
    the underlying formulas are (XLA:CPU's bf16->f32 wire upcast is priced
    in, int8 is upcast-immune)."""
    comm = getattr(cfg, "comm_dtype", "f32")
    ops: dict[str, dict] = {}
    wire: dict[str, str] = {}

    grad_fn = getattr(strategy, "grad_comm", None) if phase == "train" else None
    if grad_fn is not None and param_shapes is not None:
        gexp = grad_fn(cfg, param_shapes, backend=backend)
        if gexp:
            for op, rec in gexp.items():
                ops[op] = {"count": rec["count"], "bytes": rec["bytes"]}
            wdt = _wire_dtype_of(comm)
            if "all-to-all" in gexp:
                wire["all-to-all"] = wdt
            if "all-gather" in gexp:
                # DDP's two-shot gathers the PACKED payload; FSDP's forward
                # param gathers stay full precision by design
                wire["all-gather"] = (
                    wdt if strategy.name == "ddp" else "f32"
                )

    disp_fn = getattr(strategy, "dispatch_comm", None)
    if disp_fn is not None and global_batch is not None and seq is not None:
        dexp = disp_fn(cfg, global_batch=global_batch, seq=seq,
                       backend=backend)
        if dexp:
            train = dexp.get(phase, {"count": 0, "bytes": 0})
            if train.get("count"):
                rec = ops.setdefault("all-to-all", {"count": 0, "bytes": 0})
                rec["count"] += train["count"]
                rec["bytes"] += train["bytes"]
                wname = train.get("wire")
                if wname:
                    # expected_a2a's wire marker names the dtype the payload
                    # actually travels at on this backend
                    wire["all-to-all"] = _WIRE_TO_HLO.get(wname, wname)
                elif comm != "f32":
                    wire["all-to-all"] = _wire_dtype_of(comm)

    pipe_fn = getattr(strategy, "pipe_comm", None)
    if pipe_fn is not None and global_batch is not None and seq is not None:
        # Interleaved pipeline schedules (round 22): the unrolled tick
        # machine's shipping ticks are static, so the strategy states the
        # exact collective-permute count/bytes of the compiled step; MoE
        # worlds also pin all-to-all to ZERO (the pallas dispatch is
        # collective-free — a surplus a2a means the buffer dataflow leaked
        # in). None for the flat V=1 scan, whose hops live inside one scan
        # body instruction.
        pexp = pipe_fn(cfg, global_batch=global_batch, seq=seq, phase=phase)
        if pexp:
            for op, rec in pexp.items():
                dst = ops.setdefault(op, {"count": 0, "bytes": 0})
                dst["count"] += rec["count"]
                dst["bytes"] += rec["bytes"]

    if not ops:
        return None
    # --grad_buckets overlap declaration (train phase only — eval has no
    # backward, hence no grad wire to overlap): the strategy names how
    # many of each op kind must be independently schedulable; the rule
    # engine's `overlap` gate measures the compiled module against it.
    overlap = None
    overlap_fn = getattr(strategy, "overlap_comm", None)
    if phase == "train" and overlap_fn is not None:
        overlap = overlap_fn(cfg, param_shapes)
    return CommPlan(
        label=f"{strategy.name} {phase} step",
        ops=ops, wire=wire, exhaustive=False, comm_dtype=comm,
        overlap=overlap,
    )


def decode_comm_plan(cfg, mesh, slots: int, top_k: int = 0,
                     paged: bool = False, verify_tokens: int = 1) -> CommPlan:
    """The serving decode-step plan: `decode_step_comm`'s closed form as
    an EXHAUSTIVE CommPlan — the compiled step must move these collectives
    and nothing else (the round-14/15 audit bar, unchanged).
    `verify_tokens = spec_k + 1` prices the SPECULATIVE verify step
    instead (round 17, `serve/spec.verify_step`): same collective counts,
    every byte term widened by the verify window — the hlolint
    `spec_verify` world audits it."""
    from tpukit.serve.decode import decode_step_comm

    expected = decode_step_comm(cfg, mesh, slots, top_k=top_k, paged=paged,
                                verify_tokens=verify_tokens)
    label = ("spec verify step" if verify_tokens > 1
             else f"decode step [{'paged' if paged else 'ring'}]")
    return CommPlan(
        label=label,
        ops={op: dict(rec) for op, rec in expected.items()},
        wire={},
        exhaustive=True,
        comm_dtype=getattr(cfg, "comm_dtype", "f32"),
    )


def fleet_decode_comm_plan(cfg, mesh, slots: int, top_k: int = 0,
                           paged: bool = False) -> CommPlan:
    """Per-replica decode plan for the fleet router (round 19,
    tpukit/serve/fleet.py): the router is pure host-side scheduling over
    DISJOINT device subsets — it adds ZERO collectives — so each
    replica's decode program must audit against exactly the standalone
    engine's closed form (`decode_comm_plan`), merely compiled on a
    subset mesh. A fleet whose per-replica HLO drifts from this plan has
    leaked router state into the compiled program (e.g. a cross-replica
    sharding constraint), which is precisely what the hlolint
    `fleet_decode` world exists to catch: it compiles `decode_step` on a
    NON-LEADING device subset of the 8-virtual-device mesh and requires
    plan-exact collectives with 0 involuntary-remat warnings."""
    p = decode_comm_plan(cfg, mesh, slots, top_k=top_k, paged=paged)
    p.label = f"fleet replica {p.label}"
    return p


def ring_wire_bytes(collectives: dict[str, dict], world: int) -> int:
    """Estimated bytes each device actually moves over the interconnect
    for the parsed collectives, from their RESULT payloads (what
    `collective_summary` reports) via the standard ring-algorithm cost
    model. Needed because result bytes are not comparable ACROSS op kinds:
    a reduce-scatter's result is 1/world of the data it moved, an
    all-reduce moves ~2x its result (reduce-scatter + all-gather phases).
    Per-device wire cost for result payload R on a `world`-way ring:

      all-reduce         2 * R * (world-1)/world   (RS + AG phases)
      all-gather             R * (world-1)/world
      all-to-all             R * (world-1)/world
      reduce-scatter         R * (world-1)          (result is 1/world)
      collective-permute     R                      (one hop)

    This is the denominator-normalizer for the quantized-collective
    headline (bench.py's quant_comm record, tests): "int8 moves <= 30% of
    the f32 wire bytes" compares ring-model wire, not raw result sizes."""
    if world <= 1:
        return 0
    frac = (world - 1) / world
    mult = {
        "all-reduce": 2.0 * frac,
        "all-gather": frac,
        "all-to-all": frac,
        "reduce-scatter": float(world - 1),
        "collective-permute": 1.0,
    }
    total = 0.0
    for op, rec in collectives.items():
        total += rec.get("bytes", 0) * mult.get(op, 1.0)
    return int(total)
