"""Structured IR over optimized HLO module text.

Every hard perf/correctness win since round 10 was caught or proven by an
HLO audit — the involuntary-remat detection, the exact closed-form byte
asserts, the s32 scatter-plumbing rewrite, the wire-dtype upcast, the
jaxlib donation mis-alias — but each check read the module as FLAT TEXT
(one regex over `compiled.as_text()`). Flat text cannot scope an op to its
computation (a collective inside the decode-quantum `while` body is the
body's, once — not a line at a text offset), cannot pair an async
`-start` with its `-done` to ask what runs between them, and never sees
the executable's input–output alias table at all. This module parses the
text once into computations → instructions and keeps those relationships,
so the rule engine (analysis/rules.py) asks structural questions instead
of re-deriving them per check.

The parser is deliberately jax-free: it consumes the printed text of an
optimized module (what `compiled.as_text()` returns, or a saved fixture)
and nothing else, so `tools/hlolint.py` can lint a captured `.hlo.txt`
without a backend and the golden-fixture tests stay import-light.

Grammar actually relied on (XLA's HloPrinter, stable across the versions
this repo has seen):

  HloModule <name>, key={...}, input_output_alias={ {0}: (0, {}, may-alias) }, ...
  %comp.1 (arg: (s32[], f32[8,8])) -> f32[8,8] { ... }
  ENTRY %main.25 (Arg_0.1: f32[8,8]) -> f32[8,8] { ... }
  [ROOT ]%name = SHAPE opcode(operands), attr=..., metadata={...}

Anything that does not match the instruction grammar is kept as an opaque
line rather than raising: lint must degrade to "less information", never
take down the audit that invoked it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# HLO collective ops worth metering, normalized (async "-start" variants
# fold into the base name; "-done" carries no payload and is skipped).
# One spelling, shared with obs.xla (which re-exports it).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# Integer element types wide enough to be GSPMD index plumbing (s8/u8 are
# quantized payloads, never indices; pred is a mask).
INDEX_DTYPES = ("s32", "u32", "s64", "u64")

# `f32[8,256]{1,0}` or scalar `f32[]` — group 1 dtype, group 2 dims.
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def itemsize(dtype: str) -> int | None:
    """Bytes per element for an HLO primitive type name, None for
    token/opaque types that carry no payload."""
    return _ITEMSIZE.get(dtype)


def _shape_list(shape_str: str) -> list[tuple[str, int]]:
    """[(dtype, bytes)] for every array shape in a shape/tuple string."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        size = _ITEMSIZE.get(dtype)
        if size is None:
            continue  # token/opaque types carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dtype, n * size))
    return out


@dataclass
class Instruction:
    """One HLO instruction, as printed."""

    name: str                       # without the leading %
    opcode: str                     # as printed, e.g. "all-gather-start"
    raw_shape: str                  # result shape text, tuples included
    operands: tuple[str, ...]       # operand instruction names, without %
    attrs: str                      # raw text after the operand list
    computation: str = ""           # owning computation name
    index: int = 0                  # position within the computation
    is_root: bool = False

    @property
    def base_op(self) -> str:
        """Opcode with any async -start/-done suffix stripped."""
        for suffix in ("-start", "-done"):
            if self.opcode.endswith(suffix):
                return self.opcode[: -len(suffix)]
        return self.opcode

    @property
    def is_start(self) -> bool:
        return self.opcode.endswith("-start")

    @property
    def is_done(self) -> bool:
        return self.opcode.endswith("-done")

    def result_shapes(self) -> list[tuple[str, int]]:
        """[(dtype, bytes)] for every array in the result shape."""
        return _shape_list(self.raw_shape)

    def result_dtypes(self) -> set[str]:
        return {dt for dt, _ in self.result_shapes()}

    def attr(self, key: str) -> str | None:
        """Value of a `key=%name` / `key=value` attribute, or None."""
        m = re.search(rf"\b{re.escape(key)}=%?([\w.\-]+)", self.attrs)
        return m.group(1) if m else None


@dataclass
class Computation:
    """A named computation block: ENTRY, a while body/cond, a fusion, a
    reduction — whatever the printer emitted."""

    name: str
    instructions: list[Instruction] = field(default_factory=list)
    is_entry: bool = False
    # role, derived from the instruction that references this computation:
    # "entry" | "while_body" | "while_cond" | "fusion" | "reduction" |
    # "call" | "other"
    role: str = "other"
    # name of the referencing instruction's computation, e.g. the entry
    # computation for a top-level while body
    parent: str | None = None

    def find(self, opcode: str) -> list[Instruction]:
        return [i for i in self.instructions if i.opcode == opcode]


@dataclass
class Alias:
    """One input_output_alias table entry: output {output_index} aliases
    parameter `param_number` at {param_index}."""

    output_index: tuple[int, ...]
    param_number: int
    param_index: tuple[int, ...]
    kind: str  # "may-alias" | "must-alias"


@dataclass
class AsyncPair:
    """A matched `-start`/`-done` pair inside one computation, with the
    instructions scheduled between them. `compute_between` counts the
    non-trivial ones — the overlap the async form exists to buy."""

    start: Instruction
    done: Instruction
    between: list[Instruction]
    compute_between: int

    @property
    def overlapped(self) -> bool:
        return self.compute_between > 0


# Opcodes that shuffle or annotate values without doing work worth hiding
# a collective behind; everything else between a start/done pair counts as
# overlap compute.
_NONCOMPUTE_OPS = frozenset({
    "get-tuple-element", "tuple", "parameter", "constant", "bitcast",
    "copy", "after-all", "opt-barrier", "partition-id", "replica-id",
    "broadcast", "reshape", "transpose",
})


@dataclass
class HloModule:
    """Parsed module: computations by name, entry name, alias table."""

    name: str
    computations: dict[str, Computation]
    entry: str | None
    aliases: list[Alias]
    header: str = ""

    # -- navigation --------------------------------------------------------

    def instructions(self):
        """Every instruction in every computation, in printed order —
        exactly once each, because the printer emits each computation
        once no matter how many call sites it has."""
        for comp in self.computations.values():
            yield from comp.instructions

    def computation_of(self, instr: Instruction) -> Computation | None:
        return self.computations.get(instr.computation)

    def in_loop_body(self, instr: Instruction) -> bool:
        """True when the instruction's computation is (transitively) a
        while-loop body — a scan/decode-quantum op executed per iteration,
        printed once."""
        comp = self.computations.get(instr.computation)
        seen = set()
        while comp is not None and comp.name not in seen:
            seen.add(comp.name)
            if comp.role == "while_body":
                return True
            comp = self.computations.get(comp.parent) if comp.parent else None
        return False

    def collectives(self) -> list[Instruction]:
        """Every payload-carrying collective instance: the sync form and
        the async `-start` (the `-done` is the same transfer completing)."""
        out = []
        for instr in self.instructions():
            if instr.base_op in COLLECTIVE_OPS and not instr.is_done:
                out.append(instr)
        return out

    def async_pairs(self) -> list[AsyncPair]:
        """Matched `-start`/`-done` pairs, each with the instructions the
        schedule placed between them. A done whose start lives in another
        computation (never printed by XLA today) is skipped rather than
        mispaired."""
        pairs = []
        for comp in self.computations.values():
            starts = {
                i.name: i for i in comp.instructions if i.is_start
            }
            for done in comp.instructions:
                if not done.is_done:
                    continue
                start = next(
                    (starts[op] for op in done.operands if op in starts), None
                )
                if start is None:
                    continue
                between = comp.instructions[start.index + 1: done.index]
                compute = sum(
                    1 for i in between if i.opcode not in _NONCOMPUTE_OPS
                )
                pairs.append(AsyncPair(start, done, list(between), compute))
        return pairs

    def aliased_params(self) -> set[int]:
        """Parameter numbers covered by at least one alias entry."""
        return {a.param_number for a in self.aliases}

    def concurrent_compute(self, instr: Instruction) -> int:
        """How many compute instructions in `instr`'s computation are
        INDEPENDENT of it — neither in its operand (ancestor) cone nor in
        its result (descendant) cone. This is the dataflow form of the
        overlap question: independent work is exactly what a scheduler
        (XLA's latency-hiding scheduler on TPU, the thunk executor's
        concurrency on CPU) may place between a collective's start and
        done. An async `-start`/`-done` pair's compute_between is a
        schedule SAMPLE of this set; the cone measure is the
        backend-independent upper structure — a collective with an empty
        independent set can never overlap anything, whatever the
        scheduler does. Non-compute shuffles (_NONCOMPUTE_OPS), other
        collectives and `-done` halves don't count: hiding a wire behind
        another wire is not overlap."""
        comp = self.computations.get(instr.computation)
        if comp is None:
            return 0
        by_name, users = self._adjacency(comp)

        def cone(start: str, edges) -> set[str]:
            seen, todo = set(), [start]
            while todo:
                name = todo.pop()
                if name in seen:
                    continue
                seen.add(name)
                todo.extend(edges(name))
            return seen

        ancestors = cone(
            instr.name,
            lambda n: (op for op in by_name[n].operands if op in by_name),
        )
        descendants = cone(instr.name, lambda n: users.get(n, ()))
        dependent = ancestors | descendants
        count = 0
        for i in comp.instructions:
            if i.name in dependent:
                continue
            if i.opcode in _NONCOMPUTE_OPS or i.is_done:
                continue
            if i.base_op in COLLECTIVE_OPS:
                continue
            count += 1
        return count

    def _adjacency(self, comp: Computation):
        """(by_name, users) maps for one computation, memoized — the
        overlap gate walks one cone pair per declared collective, and
        rebuilding the maps per walk is O(collectives x instructions)
        for nothing."""
        cache = getattr(self, "_adjacency_cache", None)
        if cache is None:
            cache = self._adjacency_cache = {}
        hit = cache.get(comp.name)
        if hit is not None:
            return hit
        by_name = {i.name: i for i in comp.instructions}
        users: dict[str, list[str]] = {}
        for i in comp.instructions:
            for op in i.operands:
                if op in by_name:
                    users.setdefault(op, []).append(i.name)
        cache[comp.name] = (by_name, users)
        return cache[comp.name]


# -- parsing ----------------------------------------------------------------

# `%region_0.5 (arg: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {` /
# `ENTRY %main.25 (Arg_0.1: f32[8,8]) -> f32[8,8] {`
_COMP_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\)\s*->\s*.*\{\s*$")

# `[ROOT ]%name = SHAPE opcode(` — SHAPE is one shape or a (tuple); the
# tuple never nests for real result shapes, and XLA's printer interleaves
# /*index=N*/ comments which the permissive [^)]* absorbs.
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"((?:\([^)]*\))|(?:[^\s(]+))\s+"
    r"([a-z][\w\-]*)\("
)

_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")

_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\(\s*(\d+)\s*,\s*\{([\d,\s]*)\}\s*(?:,\s*([\w-]+))?\)"
)


def _index_tuple(text: str) -> tuple[int, ...]:
    return tuple(int(t) for t in text.replace(" ", "").split(",") if t)


def _parse_header(line: str) -> tuple[str, list[Alias]]:
    """Module name + alias table from the `HloModule ...` header line."""
    m = re.match(r"HloModule\s+([^\s,]+)", line)
    name = m.group(1) if m else ""
    aliases: list[Alias] = []
    key = "input_output_alias={"
    at = line.find(key)
    if at >= 0:
        # balanced-brace scan: the table nests {output_index} entries
        depth, start = 1, at + len(key)
        end = start
        while end < len(line) and depth:
            if line[end] == "{":
                depth += 1
            elif line[end] == "}":
                depth -= 1
            end += 1
        body = line[start: end - 1]
        for om, pn, pi, kind in _ALIAS_ENTRY_RE.findall(body):
            aliases.append(
                Alias(
                    output_index=_index_tuple(om),
                    param_number=int(pn),
                    param_index=_index_tuple(pi),
                    kind=kind or "may-alias",
                )
            )
    return name, aliases


def _split_operand_list(line: str, open_at: int) -> tuple[str, str]:
    """(operand text, attr tail) given the index of the opening paren —
    scans to the balanced close so nested tuple-shape parens inside the
    operand list don't truncate it."""
    depth, i = 0, open_at
    while i < len(line):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[open_at + 1: i], line[i + 1:]
        i += 1
    return line[open_at + 1:], ""


def parse_hlo(text: str) -> HloModule:
    """Parse printed (optimized) HLO module text. Tolerant by design:
    unrecognized lines are skipped, a truncated module still yields the
    computations that did print."""
    module_name = ""
    aliases: list[Alias] = []
    header = ""
    computations: dict[str, Computation] = {}
    entry: str | None = None
    current: Computation | None = None

    for raw in text.splitlines():
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("HloModule"):
            header = line
            module_name, aliases = _parse_header(line)
            continue
        if current is None:
            cm = _COMP_RE.match(line)
            if cm:
                comp = Computation(name=cm.group(2), is_entry=bool(cm.group(1)))
                computations[comp.name] = comp
                if comp.is_entry:
                    comp.role = "entry"
                    entry = comp.name
                current = comp
                continue
        elif line.startswith("}"):
            current = None
            continue
        im = _INSTR_RE.match(line)
        if not im:
            continue  # comments/continuations: opaque, never fatal
        if current is None:
            # instruction with no enclosing computation: a snippet or a
            # truncated dump. The flat regex this parser replaced accepted
            # those, so they land in an implicit "<toplevel>" computation
            # (per line — a later real computation header still opens its
            # own block) rather than vanishing.
            target = computations.setdefault(
                "<toplevel>", Computation(name="<toplevel>")
            )
        else:
            target = current
        root, name, shape, opcode = im.groups()
        open_at = im.end() - 1
        operand_text, attrs = _split_operand_list(line, open_at)
        instr = Instruction(
            name=name,
            opcode=opcode,
            raw_shape=shape,
            operands=tuple(_OPERAND_NAME_RE.findall(operand_text)),
            attrs=attrs,
            computation=target.name,
            index=len(target.instructions),
            is_root=bool(root),
        )
        target.instructions.append(instr)

    module = HloModule(
        name=module_name,
        computations=computations,
        entry=entry,
        aliases=aliases,
        header=header,
    )
    _link_roles(module)
    return module


def _link_roles(module: HloModule) -> None:
    """Derive each computation's role + parent from the instructions that
    reference it (`body=`/`condition=`/`calls=`/`to_apply=`)."""
    for instr in module.instructions():
        for key, role in (
            ("body", "while_body"),
            ("condition", "while_cond"),
            ("calls", "fusion" if instr.opcode == "fusion" else "call"),
            ("to_apply", "reduction"),
        ):
            target = instr.attr(key)
            if target is None:
                continue
            comp = module.computations.get(target)
            if comp is not None and not comp.is_entry:
                comp.role = role
                comp.parent = instr.computation


# -- collective summary (the obs.xla.collective_bytes contract) -------------

# Async `-start` ops whose result tuple ALIASES the operands alongside the
# results: `(operands..., results..., ctx scalars...)`. all-reduce-start's
# tuple (when present) holds only the reduced results — XLA's combiner
# fuses grad buffers into one variadic all-reduce — so halving it would
# drop real payload.
_START_WITH_OPERAND_ALIASES = ("all-gather", "collective-permute")


def payload_shapes(shape_str: str, op: str, is_start: bool) -> list[tuple[str, int]]:
    """(dtype, bytes) of the real payload arrays of one collective — the
    RULES' view of an instruction: async ctx scalars (small u32/s32
    appendages) dropped for every form, and the operand-alias half of
    `-start` tuples dropped, so a rule never prices the same buffer twice
    on the backends (TPU) that emit async pairs. `result_payload_bytes`
    below keeps the historical sync-op contract (full result tuple, ctx
    scalars only dropped on async starts) — that is the byte accounting
    the regex-equality fixtures pin; rules want the true payload."""
    shapes = [
        (dt, b) for dt, b in _shape_list(shape_str)
        if not (b <= 8 and dt in ("u32", "s32", "u64", "s64"))
    ]
    if is_start and op in _START_WITH_OPERAND_ALIASES:
        if len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
    return shapes


def result_payload_bytes(shape_str: str, op: str, is_start: bool) -> int:
    """Result payload of one collective instance. Sync ops: the full result
    shape (a tuple IS the result for multi-operand all-reduce). For async
    `-start` forms of the operand-aliasing ops above, count only the
    results half, else the aliases double the reported volume on exactly
    the backends (TPU) that emit async pairs."""
    shapes = _shape_list(shape_str)
    if is_start and op in _START_WITH_OPERAND_ALIASES:
        # drop the u32/s32 context scalars these async ops append
        shapes = [
            (dt, b) for dt, b in shapes
            if not (b <= 8 and dt in ("u32", "s32", "u64", "s64"))
        ]
        if len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
    return sum(b for _, b in shapes)


def collective_summary(module: HloModule) -> dict[str, dict[str, int]]:
    """{op: {count, bytes}} over every payload-carrying collective in the
    module — the contract `obs.xla.collective_bytes` has always reported,
    now computed from the IR (each op attributed to its computation once,
    not rediscovered by text position). Byte-for-byte equal to the
    original flat-regex parse on the golden fixtures
    (tests/test_analysis.py proves it)."""
    out: dict[str, dict[str, int]] = {}
    for instr in module.collectives():
        rec = out.setdefault(instr.base_op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += result_payload_bytes(
            instr.raw_shape, instr.base_op, instr.is_start
        )
    return out
