"""Batch preparation: raw tokenized batch -> model inputs + LM targets.

Twin of `prepare_batch` (reference utils.py:5-39). Semantics twinned exactly:

  - targets are input_ids shifted by one: inputs `[:, :-1]`, targets `[:, 1:]`
    (utils.py:22);
  - target positions equal to the pad id become -100, the cross-entropy
    ignore index (utils.py:25);
  - position_ids are `arange(S-1)` broadcast over the batch (utils.py:28-30);
  - the attention mask is **inverted** (`~mask`) to the "True = masked"
    convention and its last column is dropped (utils.py:17,36).

Works on host numpy; device placement happens at the jit boundary with the
strategy's batch sharding (the TPU-native replacement for the reference's
`.to(device, non_blocking=True)` copies, utils.py:34-38).
"""

from __future__ import annotations

import numpy as np

IGNORE_INDEX = -100


def prepare_batch(batch: dict, pad_id: int) -> tuple[dict, np.ndarray]:
    """Args: `batch` with `input_ids` and `attention_mask`, both `[B, S]`
    integer arrays (numpy or anything `np.asarray` accepts).

    Returns `(model_batch, targets)` where `model_batch` has keys matching the
    model's keyword surface (`input_ids`, `position_ids`, `mask`) — the same
    contract as reference utils.py:32-37 — and `targets` is `[B, S-1]` int32
    with pad positions set to -100.
    """
    input_ids = np.asarray(batch["input_ids"])
    attention_mask = np.asarray(batch["attention_mask"])[:, :-1]

    inputs = input_ids[:, :-1].astype(np.int32)
    targets = input_ids[:, 1:].astype(np.int32).copy()
    targets[targets == pad_id] = IGNORE_INDEX

    seq_len = inputs.shape[1]
    position_ids = np.broadcast_to(np.arange(seq_len, dtype=np.int32), inputs.shape)

    model_batch = dict(
        input_ids=inputs,
        position_ids=np.ascontiguousarray(position_ids),
        mask=~attention_mask.astype(bool),
    )
    return model_batch, targets
