"""Shared trainer: train state, jitted steps, and the epoch loop.

The reference duplicates its whole train/eval/generate/checkpoint loop in
every recipe (SURVEY §2.7; e.g. main-single.py:80-151 vs main-ddp.py:102-185
are near-identical). Here the loop lives once and the *strategy* is the only
thing a recipe supplies — the same pedagogical diff the cookbook wanted,
without the duplication.

Loop surface twins the reference exactly:
  - running train loss printed through tqdm every PRINT_FREQ=8 steps
    (main-single.py:19,104-108), process-0-gated in distributed recipes
    (tqdm(..., disable=rank != 0), main-ddp.py:106,137);
  - per-epoch validation loss + masked accuracy in the bar
    (main-single.py:110-138);
  - three fixed greedy generations per epoch: "The big brown cat ",
    "One day, ", "She said " (main-single.py:140-144), process-0 only;
  - end-of-training checkpoint (main-single.py:146-151).

TPU-native differences (deliberate, documented):
  - One jitted `train_step` holds forward+loss+backward+AdamW update; the
    state is donated, so parameters update in place in HBM.
  - The running-loss accumulator stays on device; the host syncs once per
    PRINT_FREQ window instead of the reference's per-step `loss.item()`
    (main-single.py:103, a D2H sync every step).
  - bf16 is the compute dtype (no GradScaler twin: bf16 needs no loss
    scaling; the reference's scaler is inert for bf16 anyway,
    main-single.py:78). `--disable_amp` flips compute to fp32. Eval runs
    in bf16 *unconditionally*, twinning the reference quirk of an
    always-enabled eval autocast (main-single.py:119).
  - `--disable_compile` maps to `jax.disable_jit()` (debug mode), the
    analogue of skipping torch.compile (main-single.py:38-39).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from tqdm import tqdm

from tpukit import checkpoint as ckpt_lib
from tpukit.batching import IGNORE_INDEX, prepare_batch
from tpukit.cache import enable_compilation_cache
from tpukit.data import get_dataset, get_tokenizer, transform_dataset
from tpukit.flags import TrainFlags
from tpukit.loader import DataLoader
from tpukit.prefetch import HostPrefetcher
from tpukit.mesh import initialize_runtime, is_process_zero
from tpukit.model import gpt
from tpukit.obs import (
    AnomalyTracer,
    FlightRecorder,
    HangWatchdog,
    Heartbeat,
    MFUMeter,
    SpanTimeline,
    SpikeSentinel,
    StepLogger,
    compiled_stats,
    format_breakdown,
    format_checksum,
    global_norms,
    live_memory_stats,
    make_state_checksum,
    trace,
)
from tpukit.sampling import generate_batch
from tpukit.shardings import Strategy

PRINT_FREQ = 8  # twin of main-single.py:19
GENERATION_PROMPTS = ["The big brown cat ", "One day, ", "She said "]  # main-single.py:142-144


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jax.Array


def create_train_state(rng, cfg: gpt.GPTConfig, optimizer, strategy=None) -> TrainState:
    params = gpt.init_params(rng, cfg)
    if strategy is not None:
        # layout hook (e.g. Pipeline pads stacked layers to a stage multiple
        # with identity layers when num_layers doesn't divide the stages)
        params = strategy.prepare_params(params, cfg)
    return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.int32(0))


def make_optimizer(learning_rate: float) -> optax.GradientTransformation:
    """Twin of `torch.optim.AdamW(params, lr=...)` (main-single.py:42): torch
    AdamW defaults are betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2."""
    return optax.adamw(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=1e-2)


def make_step_fns(
    cfg: gpt.GPTConfig, optimizer, strategy: Strategy, state_shapes,
    seed: int = 0, log_grad_norms: bool = False,
):
    """Build jitted train/eval steps with the strategy's shardings applied.

    GSPMD reads the in/out shardings and inserts the collectives: grad psum
    for DP, per-tensor all-gather/reduce-scatter for FSDP, nothing for
    single-device. The pipeline strategy's schedule is inside its loss_fn.

    Dropout (VERDICT r2 #6): when cfg.dropout > 0 the train step folds the
    training step counter into a seed-derived key and threads it to the
    strategy's loss — active in training, never in eval (the reference's
    train()/eval() mode split, models/gpt.py:31,65). With dropout off no rng
    is traced at all, so the compiled step is unchanged.

    `log_grad_norms` (round-6 telemetry, --log_grad_norms): the train step
    ADDITIONALLY returns `{grad,update,param}_norm` f32 scalars, computed
    inside the same jitted program (the grads/updates are already live — no
    second compilation, no extra pass). Off (default): the traced graph is
    exactly the flag-free one, so the compiled HLO is byte-identical.
    """
    eval_cfg = cfg.replace(compute_dtype=jnp.bfloat16)  # eval autocast always on
    dropout_key = jax.random.PRNGKey(seed ^ 0x5EED) if cfg.dropout > 0 else None

    def train_step(state: TrainState, batch, targets):
        state = strategy.to_compute(state)
        rng = (
            jax.random.fold_in(dropout_key, state.step)
            if dropout_key is not None
            else None
        )

        # autodiff over loss_fn by default; Pipeline1F1B overrides with its
        # explicit per-stage-vjp schedule (see Strategy.value_and_grad)
        loss, grads = strategy.value_and_grad(
            state.params, cfg, batch, targets, rng=rng
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        if log_grad_norms:
            return new_state, loss, global_norms(grads, updates, params)
        return new_state, loss

    def eval_step(state: TrainState, batch, targets):
        state = strategy.to_compute(state)
        loss, accuracy = strategy.loss_fn(
            state.params, eval_cfg, batch, targets, with_accuracy=True
        )
        return loss, accuracy

    state_sh = strategy.state_sharding(state_shapes)
    state_sharding = TrainState(
        params=state_sh.params, opt_state=state_sh.opt_state, step=strategy.replicated()
    )
    batch_sh = strategy.batch_sharding()
    repl = strategy.replicated()

    train_out_sh = (state_sharding, repl)
    if log_grad_norms:
        norm_sh = {k: repl for k in ("grad_norm", "update_norm", "param_norm")}
        train_out_sh = (state_sharding, repl, norm_sh)
    train_step = jax.jit(
        train_step,
        in_shardings=(state_sharding, batch_sh, batch_sh),
        out_shardings=train_out_sh,
        donate_argnums=(0,),
    )
    eval_step = jax.jit(
        eval_step,
        in_shardings=(state_sharding, batch_sh, batch_sh),
        out_shardings=(repl, repl),
    )
    return train_step, eval_step, state_sharding


def make_global_batch(batch_sharding, model_batch, targets, place: bool = False):
    """Assemble per-process host arrays into global device arrays.

    Single-process: identity (jit places numpy at the sharding). Multi-host
    (the v4-32 ladder configs: one process per host, SURVEY §2.5): each
    process holds only its DistributedSampler shard of the batch —
    `jax.make_array_from_process_local_data` builds the global sharded
    array a cross-host jit can consume. This replaces the reference's
    per-rank DataLoader+DistributedSampler feeding (main-ddp.py:83-100);
    feeding the full global batch from every process would be rejected by
    a jit whose shardings span non-addressable devices.

    `place=True` (the prefetch path) makes the single-process case an
    explicit `jax.device_put` at the batch sharding instead of leaving the
    H2D copy to the jit boundary — so the transfer itself happens on the
    prefetch thread, ahead of the step that consumes it. Values are
    bit-identical either way (the batch is integer/bool data placed at the
    same sharding the jit would have used).
    """
    if jax.process_count() == 1:
        if not place:
            return model_batch, targets

        def conv(x):
            return jax.device_put(x, batch_sharding)

        return jax.tree.map(conv, model_batch), conv(targets)

    spec = batch_sharding.spec
    if len(spec) > 0 and spec[0] is not None:
        # batch rows are sharded across processes: each process supplied
        # only its DistributedSampler shard
        def conv(x):
            return jax.make_array_from_process_local_data(batch_sharding, x)
    else:
        # rows are process-replicated (pure pipeline / CP seq sharding):
        # every process loaded the identical full global batch; carve each
        # host's addressable shards out of it
        def conv(x):
            return jax.make_array_from_callback(
                x.shape, batch_sharding, lambda idx, x=x: x[idx]
            )

    return jax.tree.map(conv, model_batch), conv(targets)


@jax.jit
def _valid_count(targets):
    """Global valid-token count of a (possibly cross-host sharded) targets
    array. jit makes the sum a collective under GSPMD, so every process sees
    the same number — a host-side count would only cover the local shard."""
    return jnp.sum(targets != IGNORE_INDEX)


@functools.lru_cache(maxsize=None)
def _replicator(mesh):
    """One jitted all-gather-to-replicated program per mesh — rebuilding the
    lambda per call would retrace (and recompile) every epoch."""
    from jax.sharding import NamedSharding

    repl = NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.jit(lambda p: p, out_shardings=repl)


def replicated_params(strategy: Strategy, state: TrainState):
    """Parameters addressable on every host for the decode loop — running it
    on process 0 with params still sharded across hosts is the reference's
    latent multi-host hang (rank-0-only FSDP generate, main-ddp.py:170-174,
    SURVEY §3.5). This is a collective — EVERY process must call it.

    Small models get a fully-replicated copy (one compiled all-gather, then
    the 20-step decode runs gather-free). Past TPUKIT_REPLICATE_PARAMS_MB
    (default 1 GiB — ADVICE r3: FSDP configs that shard out of memory
    necessity would OOM on a transient full copy) the params keep their
    sharded layout — routed through `strategy.to_compute` so offloaded
    (pinned_host) state still moves into device memory — and the decode jit
    lets GSPMD gather per-op: one layer's parameters live at a time instead
    of all of them.
    """
    limit = int(os.environ.get("TPUKIT_REPLICATE_PARAMS_MB", "1024")) * 2**20
    total = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(state.params)
    )
    if total > limit:
        # Move ONLY the params subtree into device memory: to_compute maps
        # leaf-wise, and running it on the whole TrainState would transiently
        # pull both Adam moments (~3x params) into HBM for a decode that
        # never reads them (ADVICE r4).
        return strategy.to_compute(state.params)
    return _replicator(strategy.mesh)(state.params)


def generate_samples(
    strategy: Strategy,
    state: TrainState,
    cfg: gpt.GPTConfig,
    tokenizer,
    prompts=GENERATION_PROMPTS,
    max_new_tokens: int = 20,
) -> list[str]:
    """SPMD-safe qualitative eval: replicate params, then greedy-decode each
    prompt. Every process must call this (the replication is collective);
    each returns the same texts, and the caller prints on process 0 only —
    the reference's rank-0 gating (main-ddp.py:170-174) moved from "only
    rank 0 computes" (a deadlock for sharded state) to "all compute, rank 0
    prints"."""
    params = replicated_params(strategy, state)
    # ONE batched jitted call (VERDICT r4 #7): one compile and one decode
    # per epoch instead of a serial compile+decode per prompt — `generate`
    # stays as the single-prompt API.
    return generate_batch(
        params, cfg, list(prompts), tokenizer, max_new_tokens=max_new_tokens
    )


def _place_like(host_tree, sharding_tree):
    """Place a host-array pytree at the given shardings (multi-host safe —
    see mesh.place_host_array)."""
    from tpukit.mesh import place_host_array

    return jax.tree.map(place_host_array, host_tree, sharding_tree)


@contextlib.contextmanager
def _debug_nans_scope():
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@dataclasses.dataclass
class FitResult:
    state: TrainState
    tokenizer: Any
    config: gpt.GPTConfig
    checkpoint_path: Any
    metrics: dict


def fit(
    flags: TrainFlags,
    strategy: Strategy,
    num_epochs: int | None = None,
    make_loaders: Callable | None = None,
) -> FitResult:
    """The shared training entry point every recipe calls."""
    initialize_runtime()
    p0 = is_process_zero()
    if flags.prefetch < 0:
        raise ValueError(f"--prefetch must be >= 0, got {flags.prefetch}")
    if flags.hang_timeout < 0:
        raise ValueError(f"--hang_timeout must be >= 0, got {flags.hang_timeout}")
    if flags.divergence_check_freq < 0:
        raise ValueError(
            f"--divergence_check_freq must be >= 0, got "
            f"{flags.divergence_check_freq}"
        )
    # Persistent XLA compilation cache (round 7): repeat runs of the same
    # program skip recompiles; hits/misses are logged at the end of the run.
    cache_stats = (
        enable_compilation_cache(flags.compilation_cache_dir)
        if flags.compilation_cache_dir
        else None
    )

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2  # every recipe pins pad to 2 (main-single.py:23)

    compute_dtype = jnp.float32 if flags.disable_amp else jnp.bfloat16
    cfg = gpt.GPTConfig(
        dim=flags.dim,
        head_dim=flags.head_dim,
        heads=flags.heads,
        num_layers=flags.num_layers,
        vocab_size=tokenizer.vocab_size,
        max_position_embeddings=flags.sequence_length,
        dropout=flags.dropout,
        compute_dtype=compute_dtype,
        remat_layers=flags.remat,
        scan_layers=flags.scan_layers,
        num_experts=flags.num_experts,
        router_top_k=flags.moe_top_k,
    )
    optimizer = make_optimizer(flags.learning_rate)
    strategy.validate_config(cfg)  # fail fast with a clear shape/mesh error

    # ---- data -----------------------------------------------------------
    if make_loaders is not None:
        train_loader, validation_loader = make_loaders(flags, tokenizer, strategy)
        # meter math: a rank-sharded custom loader reports per-host rows
        loader_procs = getattr(train_loader, "num_replicas", 1)
    else:
        train_ds, validation_ds = get_dataset(slice_size=flags.dataset_slice)
        train_ds = transform_dataset(
            train_ds, tokenizer, max_length=flags.sequence_length, num_proc=flags.num_workers
        )
        validation_ds = transform_dataset(
            validation_ds, tokenizer, max_length=flags.sequence_length, num_proc=flags.num_workers
        )
        # Global batch = per-replica batch x data-parallel degree, the twin
        # of "per-rank DataLoader(batch_size)" under torchrun (main-ddp.py:
        # 83-100). Wrap-padding keeps every step full-shape — the twin of
        # DistributedSampler's pad-by-wrapping, applied unconditionally so
        # the jitted step compiles exactly once (a ragged final batch would
        # recompile and, under Pipeline, violate the micro-batch divisor).
        replicas = strategy.mesh.shape.get("data", 1)
        global_batch = flags.batch_size * replicas
        if global_batch % strategy.batch_divisor:
            raise ValueError(
                f"global batch {global_batch} (batch_size {flags.batch_size} x "
                f"{replicas} data shards) must be a multiple of "
                f"{strategy.batch_divisor} for the {strategy.name} strategy"
            )
        # Multi-host: when the strategy shards batch rows, each process
        # loads only its DistributedSampler shard of every global batch
        # (twin of per-rank DataLoader under torchrun, main-ddp.py:83-100);
        # make_global_batch assembles the global array. Strategies that
        # replicate rows across processes (pure pipeline / CP) need the
        # identical full batch on every host instead.
        spec = strategy.batch_spec()
        rows_sharded = len(spec) > 0 and spec[0] is not None
        procs = jax.process_count() if rows_sharded else 1
        rank = jax.process_index() if rows_sharded else 0
        if global_batch % procs:
            raise ValueError(
                f"global batch {global_batch} must divide across {procs} hosts"
            )
        per_host = global_batch // procs
        loader_procs = procs
        train_loader = DataLoader(
            train_ds, per_host, shuffle=True, seed=flags.seed, drop_last=False,
            pad_to_batch=True, num_replicas=procs, rank=rank,
        )
        # Validation pads with all-ignore rows (not wrap-duplicates), so the
        # final batch's metrics equal the exact partial-batch metrics the
        # reference's single-device eval computes (main-single.py:110-138).
        validation_loader = DataLoader(
            validation_ds, per_host, shuffle=False, pad_to_batch=True,
            pad_mode="empty", pad_fill=tokenizer.pad_token_id,
            num_replicas=procs, rank=rank,
        )

    # ---- state ----------------------------------------------------------
    init_fn = partial(create_train_state, cfg=cfg, optimizer=optimizer, strategy=strategy)
    state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(flags.seed))
    train_step, eval_step, state_sharding = make_step_fns(
        cfg, optimizer, strategy, state_shapes, seed=flags.seed,
        log_grad_norms=flags.log_grad_norms,
    )

    # Initialize directly into the sharded layout (no host-side giant pytree).
    state = jax.jit(init_fn, out_shardings=state_sharding)(jax.random.PRNGKey(flags.seed))

    if flags.resume:
        from pathlib import Path

        resume_path = (
            ckpt_lib.latest_any() if flags.resume == "latest" else Path(flags.resume)
        )
        if resume_path is None or not resume_path.exists():
            raise FileNotFoundError(
                f"--resume {flags.resume}: no checkpoint found"
            )
        # Both formats restore against the abstract state_shapes (never a
        # device_get of the live state — that is exactly the gather that
        # fails for cross-host-sharded state). Sharded checkpoints place
        # their shards straight into the strategy's shardings; consolidated
        # ones come back as host arrays and are placed below.
        restored, was_sharded = ckpt_lib.restore_any(
            resume_path, state_shapes, state_sharding
        )
        state = restored if was_sharded else _place_like(restored, state_sharding)
        if p0:
            print(
                f"resumed from {resume_path} at step {int(jax.device_get(state.step))}"
            )

    batch_sh = strategy.batch_sharding()
    # Host-side batch transform (ContextParallel's zigzag permute — ADVICE
    # r4: in-jit it is a per-step cross-shard reshard collective).
    host_batch = strategy.host_batch_fn(cfg)

    def host_pipeline(raw):
        """The whole host side of one training batch — prepare, strategy
        transform, global-array assembly WITH explicit device placement.
        This is what the prefetch thread runs `--prefetch` batches ahead;
        it is the same work the synchronous path's data+h2d spans time."""
        b, t = prepare_batch(raw, tokenizer.pad_token_id)
        if host_batch is not None:
            b, t = host_batch(b, t)
        b, t = make_global_batch(batch_sh, b, t, place=True)
        return raw, b, t

    # Checkpoint writer: the async writer snapshots on this thread and
    # publishes from a background one (join barrier at the next save), so
    # periodic saves stop stalling the step loop on encode+disk I/O.
    async_saver = ckpt_lib.AsyncCheckpointer() if flags.async_checkpoint else None

    def save_checkpoint(st):
        if async_saver is not None:
            return async_saver.save_auto(st, format=flags.checkpoint_format)
        return ckpt_lib.save_auto(st, format=flags.checkpoint_format)

    seq = flags.sequence_length - 1  # model sees S-1 after the shift
    meter = MFUMeter(cfg, seq)
    logger = StepLogger(flags.metrics_log if p0 else "")
    # ---- telemetry (tpukit/obs, round 6) --------------------------------
    spans = SpanTimeline()
    # Flight recorder (round 8): always on — a bounded ring of recent
    # step/window/sentinel records, read only when a diagnostics bundle is
    # dumped. The cost is one dict + deque append per step (<1% of any
    # real step; bench.py's obs_overhead record audits it).
    recorder = FlightRecorder()
    # Sentinel runs on EVERY process with identical inputs (the window loss
    # is a replicated global mean), so an "abort" decision is collective-
    # consistent — each process checkpoints and raises in lockstep instead
    # of process 0 abandoning a collective the others are blocked in.
    sentinel = (
        SpikeSentinel(flags.spike_threshold)
        if flags.spike_threshold > 0
        else None
    )
    heart = (
        Heartbeat(flags.heartbeat_dir, timeout_s=flags.heartbeat_timeout)
        if flags.heartbeat_dir
        else None
    )
    spike_events = 0
    # XLA static analysis (cost/memory/comm bytes) is captured once per
    # compiled step function, lazily at its first batch (real avals in
    # hand), and only when a metrics log is requested — with telemetry off
    # nothing here touches the step functions.
    xla_pending = {"train_step": train_step, "eval_step": eval_step}

    def capture_xla(fn_name, *call_args):
        jitted = xla_pending.pop(fn_name, None)
        # p0-gated like the logger that consumes it: the analysis
        # (as_text + HLO parse) is pure host work other processes would
        # only discard. The AOT lower/compile it triggers is process-local,
        # so skipping it off-p0 cannot desynchronize a multi-host run.
        if jitted is None or not flags.metrics_log or not p0:
            return
        with spans.span("telemetry"):
            structs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), call_args
            )
            stats = compiled_stats(jitted, *structs)
        if stats:
            expected = getattr(strategy, "comm_ops", ())
            logger.log(
                kind="xla", fn=fn_name, strategy=strategy.name,
                expected_comm_ops=list(expected), **stats,
            )

    epochs = num_epochs if num_epochs is not None else flags.epochs
    checkpoint_path = None

    # The step counter is tracked on host (one D2H sync here, after a
    # possible resume, then pure host arithmetic) so periodic checkpointing
    # never forces a per-step `int(state.step)` sync inside the hot loop.
    host_step = int(state.step)

    # ---- failure observability (round 8): watchdog + bundles + trace-on-
    # anomaly + divergence checksums (docs/DESIGN.md "failure
    # observability"). The watchdog exists whenever bundles can be asked
    # for (--hang_timeout and/or --debug_dir); its monitor thread only
    # runs with a positive timeout.
    debug_dir = flags.debug_dir or (
        "debug"
        if flags.hang_timeout > 0
        or flags.trace_on_anomaly > 0
        or flags.divergence_check_freq > 0
        else ""
    )
    # in-flight async state the bundle snapshots; the prefetcher slot is
    # re-pointed each epoch
    pf_live: dict[str, Any] = {"pf": None}

    def _prefetch_probe():
        pf = pf_live["pf"]
        if pf is None:
            return None
        return {"depth": pf.depth, "buffered": pf.buffered}

    watchdog = (
        HangWatchdog(
            debug_dir,
            timeout_s=flags.hang_timeout,
            recorder=recorder,
            heartbeat=heart,
            probes={
                "host_step": lambda: host_step,
                "async_checkpoint_in_flight": (
                    lambda: async_saver.in_flight if async_saver else False
                ),
                "prefetcher": _prefetch_probe,
            },
            config=flags,
        )
        if debug_dir
        else None
    )
    # Trace-on-anomaly: the FIRST anomaly arms a jax.profiler capture of
    # the next K steps. Mutually exclusive with a whole-run --profile_dir
    # trace (jax supports one active capture).
    tracer = (
        AnomalyTracer(
            os.path.join(debug_dir, "anomaly_trace"), flags.trace_on_anomaly
        )
        if flags.trace_on_anomaly > 0 and debug_dir and not flags.profile_dir
        else None
    )
    # Divergence checksums ride a SEPARATE jitted program so the train
    # step's HLO is byte-identical with the flag off (tests assert it).
    checksum_fn = (
        make_state_checksum() if flags.divergence_check_freq > 0 else None
    )
    if checksum_fn is not None:
        if jax.process_count() > 1 and heart is None:
            # cross-replica comparison rides the heartbeat files; without
            # them a multi-host run would pay for checksums that nothing
            # ever compares — the exact silent failure this flag exists
            # to catch. Fail loudly instead.
            raise ValueError(
                "--divergence_check_freq needs --heartbeat_dir on multi-"
                "process runs: checksums are compared across processes "
                "through the shared heartbeat files"
            )
        # Compile the checksum program NOW, before the watchdog ever arms:
        # its one-off trace+compile at the first check step would otherwise
        # run inside an armed iteration and a long compile could dump a
        # spurious "hang" bundle (and burn the once-per-run anomaly trace).
        jax.block_until_ready(checksum_fn(state)["params"])
    last_checksum: tuple[int, str] | None = None  # (step, hex)
    # (process, checksum_step, checksum) triples already reported: beats
    # republish the same mismatch every window until the next check step,
    # and one divergence must not spam the JSONL or drain the bundle budget
    reported_divergence: set = set()
    # Check-step dispatches are ASYNC (two u32 scalars in flight, the
    # producing state released at dispatch); the D2H read happens at the
    # window boundary, which syncs anyway — so a check step costs one
    # extra jitted pass, never a mid-window pipeline stall.
    pending_checks: list[tuple[int, dict]] = []
    hangs_logged = 0

    def flush_checks() -> None:
        nonlocal last_checksum
        for st, ck in pending_checks:
            cs = format_checksum(ck)  # the deferred D2H read
            last_checksum = (st, cs)
            recorder.record("divergence_check", step=st, checksum=cs)
            logger.log(kind="divergence_check", step=st, checksum=cs)
        pending_checks.clear()

    def note_anomaly(reason: str, step: int) -> None:
        """First anomaly arms the trace; every anomaly lands in the ring."""
        recorder.record("anomaly", reason=reason, step=step)
        if tracer is not None and tracer.trigger(reason):
            logger.log(
                kind="anomaly_trace", event="armed", reason=reason, step=step
            )

    def dump_bundle(reason: str, step: int, **ctx):
        if watchdog is None:
            return None
        path = watchdog.trigger(reason, step=step, **ctx)
        if path is not None:
            logger.log(
                kind="watchdog", event="bundle", reason=reason, step=step,
                bundle=str(path),
            )
        return path

    if heart is not None:
        heart.beat(host_step)  # liveness file exists before the first compile

    maybe_nojit = jax.disable_jit() if flags.disable_compile else contextlib.nullcontext()
    # Debug toolchain (SURVEY §5): abort with a traceback at the first
    # NaN/Inf inside any jitted computation. Scoped to this fit() so debug
    # mode does not leak into later runs in the same process.
    maybe_nans = (
        _debug_nans_scope() if flags.debug_nans else contextlib.nullcontext()
    )
    # First call of each compiled step function pays the jit compile —
    # minutes at pod scale — so the watchdog only arms once the function
    # is warm: --hang_timeout bounds the steady-state step, not the
    # compile.
    warm = {"train": False, "eval": False}

    def _close_obs():
        # runs on ANY exit of the training block (normal, spike abort,
        # debug_nans, KeyboardInterrupt): flush a partial anomaly trace
        # and stop the monitor thread before the final checkpoint I/O
        if tracer is not None and tracer.stop():
            logger.log(kind="anomaly_trace", event="stopped", step=host_step)
        if watchdog is not None:
            watchdog.close()

    # _cleanup: any exception unwinding the loop (debug_nans aborts, device
    # OOM, KeyboardInterrupt) must release the epoch's prefetch worker —
    # close() is idempotent, so registering each epoch's prefetcher is safe.
    with contextlib.ExitStack() as _obs_guard, maybe_nojit, maybe_nans, \
            trace(flags.profile_dir), contextlib.ExitStack() as _cleanup:
        _obs_guard.callback(_close_obs)
        for epoch in range(epochs):
            # ---- train ---------------------------------------------------
            train_loader.set_epoch(epoch)
            # Exact global real-row schedule (VERDICT r4 #6): pure host math
            # (wrap-pad positions don't depend on the shuffle), so the meter
            # is exact on ragged final batches without a per-step cross-host
            # reduction that would re-serialize the async dispatch pipeline.
            # Custom loaders without the method fall back to the
            # per-shard x num_replicas approximation.
            global_rows = (
                train_loader.global_real_row_counts()
                if hasattr(train_loader, "global_real_row_counts")
                else None
            )
            # total=None for reduced-interface custom loaders (make_loaders
            # contract: iterable + set_epoch; __len__ optional)
            bar = tqdm(
                total=len(train_loader) if hasattr(train_loader, "__len__") else None,
                disable=not p0,
            )
            bar.set_description(f"[training] Epoch {epoch+1}/{epochs} | loss: ?????")
            running = None
            norms = None  # on-device window norms when --log_grad_norms
            # Input source (round 7): with --prefetch N (default 2) a
            # background thread runs the whole host pipeline N batches
            # ahead, so loader wait + prepare + H2D assembly overlap the
            # in-flight compiled step; the measured wait is the residual
            # `prefetch_stall` span. --prefetch 0 is the synchronous
            # reference path, bit-identical losses (tests/test_prefetch.py).
            # One prefetcher per epoch: set_epoch has already run, and the
            # epoch boundary flushes instead of buffering across epochs.
            pf = (
                HostPrefetcher(train_loader, host_pipeline, depth=flags.prefetch)
                if flags.prefetch > 0
                else None
            )
            pf_live["pf"] = pf  # bundle probe sees this epoch's prefetcher
            if pf is not None:
                _cleanup.callback(pf.close)
            _cleanup.callback(bar.close)
            it = iter(train_loader) if pf is None else None
            i = -1
            while True:
                # The watchdog deadline covers the WHOLE iteration — input
                # wait, dispatch, window sync, periodic checkpoint — so a
                # hang in any of them trips it; re-arming each iteration
                # resets the clock.
                if watchdog is not None and warm["train"]:
                    watchdog.arm(host_step + 1)
                if tracer is not None and tracer.maybe_start():
                    logger.log(
                        kind="anomaly_trace", event="started",
                        step=host_step + 1, reason=tracer.reason,
                        dir=tracer.trace_dir,
                    )
                if pf is not None:
                    with spans.span("prefetch_stall"):
                        try:
                            raw, batch, targets = next(pf)
                        except StopIteration:
                            break
                    i += 1
                else:
                    # Explicit iterator so the loader wait is a measured
                    # span — a data-bound run shows up as a "data" slice of
                    # the window instead of silently deflating tokens/sec.
                    with spans.span("data"):
                        try:
                            raw = next(it)
                        except StopIteration:
                            break
                        i += 1
                        batch, targets = prepare_batch(raw, tokenizer.pad_token_id)
                        if host_batch is not None:
                            batch, targets = host_batch(batch, targets)
                    with spans.span("h2d"):
                        batch, targets = make_global_batch(batch_sh, batch, targets)
                bar.update(1)
                capture_xla("train_step", state_shapes, batch, targets)
                with spans.span("step"):
                    if flags.log_grad_norms:
                        state, loss, norms = train_step(state, batch, targets)
                    else:
                        state, loss = train_step(state, batch, targets)
                warm["train"] = True
                host_step += 1
                recorder.record("step", step=host_step, epoch=epoch)
                if tracer is not None and tracer.tracing and tracer.step():
                    logger.log(
                        kind="anomaly_trace", event="stopped", step=host_step
                    )
                if (
                    checksum_fn is not None
                    and host_step % flags.divergence_check_freq == 0
                ):
                    with spans.span("telemetry"):
                        pending_checks.append((host_step, checksum_fn(state)))
                running = loss if running is None else running + loss
                # Honest throughput (VERDICT r2 #8): count only original
                # dataset rows — wrap-padding duplicates train but are not
                # new tokens; the precomputed global schedule makes the
                # count exact on ragged multi-host batches (VERDICT r4 #6).
                real_rows = raw.get("real_rows") if isinstance(raw, dict) else None
                if global_rows is not None:
                    meter.update(int(global_rows[i]) * targets.shape[1])
                elif real_rows is None:
                    meter.update(targets.size)  # custom loader: no row info
                else:
                    meter.update(real_rows * loader_procs * targets.shape[1])
                if i > 0 and not i % PRINT_FREQ:
                    with spans.span("sync"):
                        avg = float(running) / PRINT_FREQ  # one D2H sync per window
                        norm_vals = (
                            {k: float(v) for k, v in norms.items()}
                            if norms is not None
                            else {}
                        )
                    win = spans.window()
                    bar.set_description(
                        f"[training] Epoch {epoch+1}/{epochs} | loss: {avg:.3f}"
                    )
                    record = dict(
                        kind="train", epoch=epoch, step=host_step, loss=avg,
                        tokens_per_sec=meter.tokens_per_sec, mfu=meter.mfu,
                        goodput=win["goodput"], spans=win["fractions"],
                        window_s=win["total_s"], **norm_vals,
                    )
                    hbm = live_memory_stats()
                    if hbm:
                        record["hbm"] = hbm
                    if pf is not None:
                        # buffer gauges: how long this thread actually
                        # blocked on input (the honest residual of the old
                        # data+h2d cost after overlap) and how full the
                        # prefetch buffer ran (0 = starved, depth = ahead)
                        pstats = pf.window_stats()
                        record["prefetch_stall_s"] = round(
                            win["seconds"].get("prefetch_stall", 0.0), 6
                        )
                        record["prefetch_occupancy"] = round(
                            pstats["occupancy"], 3
                        )
                    logger.log(**record)
                    recorder.record(
                        "window", step=host_step, epoch=epoch, loss=avg,
                        goodput=win["goodput"],
                        window_s=round(win["total_s"], 6),
                    )
                    if (
                        watchdog is not None
                        and len(watchdog.hang_events) > hangs_logged
                    ):
                        # the monitor thread already dumped the bundle(s);
                        # surface the event in the JSONL from this thread
                        # and trace the recovery steps. hang_events pairs
                        # each overrun with ITS bundle (None if the dump
                        # budget was spent), so the record never points at
                        # an unrelated sentinel bundle.
                        new_events = watchdog.hang_events[hangs_logged:]
                        hangs_logged = len(watchdog.hang_events)
                        logger.log(
                            kind="watchdog", event="hang", step=host_step,
                            hangs=len(watchdog.hang_events),
                            bundles=[
                                e["bundle"] for e in new_events if e["bundle"]
                            ],
                        )
                        note_anomaly("hang", host_step)
                    running = None
                    if pending_checks:
                        with spans.span("telemetry"):
                            flush_checks()
                    if heart is not None:
                        heart.beat(
                            host_step,
                            checksum=last_checksum[1] if last_checksum else None,
                            checksum_step=(
                                last_checksum[0] if last_checksum else None
                            ),
                        )
                        if p0:
                            # step_lag = one window: SPMD lockstep keeps
                            # healthy processes equal, so a process a full
                            # window behind (e.g. restarted onto an old
                            # checkpoint) is worth naming
                            stragglers = heart.check(step_lag=PRINT_FREQ)
                            if stragglers:
                                logger.log(
                                    kind="straggler", step=host_step,
                                    stragglers=stragglers,
                                )
                                recorder.record(
                                    "straggler", step=host_step,
                                    stragglers=stragglers,
                                )
                                print(f"heartbeat: straggling processes {stragglers}")
                                note_anomaly("straggler", host_step)
                                dump_bundle(
                                    "straggler", host_step,
                                    stragglers=stragglers,
                                )
                            if checksum_fn is not None:
                                # beats republish their latest checksum
                                # every window; report each mismatching
                                # (process, step, checksum) ONCE
                                diverged = [
                                    m for m in heart.check_divergence()
                                    if (
                                        m["process"], m["checksum_step"],
                                        m["checksum"],
                                    ) not in reported_divergence
                                ]
                                if diverged:
                                    reported_divergence.update(
                                        (
                                            m["process"], m["checksum_step"],
                                            m["checksum"],
                                        )
                                        for m in diverged
                                    )
                                    logger.log(
                                        kind="divergence", step=host_step,
                                        mismatches=diverged,
                                    )
                                    recorder.record(
                                        "divergence", step=host_step,
                                        mismatches=diverged,
                                    )
                                    print(
                                        "divergence: replica checksum "
                                        f"mismatch {diverged}"
                                    )
                                    note_anomaly("divergence", host_step)
                                    dump_bundle(
                                        "divergence", host_step,
                                        mismatches=diverged,
                                    )
                    if sentinel is not None:
                        event = sentinel.observe(avg, host_step)
                        if event is not None:
                            spike_events += 1
                            logger.log(
                                kind="spike", action=flags.spike_action,
                                **event.record(),
                            )
                            recorder.record(
                                "spike", step=event.step, event=event.kind,
                                action=flags.spike_action,
                            )
                            note_anomaly(event.kind, host_step)
                            dump_bundle(event.kind, host_step)
                            if p0:
                                print(
                                    f"loss sentinel: {event.kind} at step "
                                    f"{event.step} (loss {event.loss:.4g})"
                                )
                            if flags.spike_action == "abort":
                                # Preserve the blown-up state for autopsy,
                                # then fail loudly. Collective-consistent:
                                # every process observed the same replicated
                                # loss and takes this branch together.
                                with spans.span("checkpoint"):
                                    checkpoint_path = (
                                        save_checkpoint(state)
                                        or checkpoint_path
                                    )
                                    if async_saver is not None:
                                        # abort must leave a DURABLE autopsy
                                        async_saver.wait()
                                # (the raise unwinds through _cleanup, which
                                # closes this epoch's prefetcher and bar)
                                logger.close()
                                raise RuntimeError(
                                    f"loss sentinel aborted training: "
                                    f"{event.kind} at step {event.step} "
                                    f"(loss {event.loss:.6g}); state "
                                    f"checkpointed at {checkpoint_path}"
                                )
                if flags.checkpoint_every and host_step % flags.checkpoint_every == 0:
                    if watchdog is not None:
                        # checkpoint I/O (sync writer: encode + disk) may
                        # legitimately exceed the step deadline; the next
                        # iteration re-arms
                        watchdog.disarm()
                    # Async: only the snapshot is charged here; the encode +
                    # disk write overlaps the following steps.
                    with spans.span("checkpoint"):
                        checkpoint_path = (
                            save_checkpoint(state) or checkpoint_path
                        )
                    recorder.record("checkpoint", step=host_step)
            # Close THIS epoch's prefetcher + bar now (pop_all keeps the
            # fit-lifetime stack from accumulating dead objects across
            # epochs; the stack still covers exceptional unwinds above).
            _cleanup.pop_all().close()
            pf_live["pf"] = None
            if watchdog is not None:
                watchdog.disarm()

            # ---- validation ---------------------------------------------
            bar = tqdm(validation_loader, disable=not p0)
            bar.set_description(
                f"[validation] Epoch {epoch+1}/{epochs} | loss: ?????, accuracy: ?????"
            )
            total_loss, total_acc, total_weight = 0.0, 0.0, 0.0
            eval_metrics = {"loss": float("nan"), "accuracy": float("nan")}
            for i, raw in enumerate(bar):
                # eval steps hang in the same collectives train steps do;
                # same deadline, same first-call compile exemption
                if watchdog is not None and warm["eval"]:
                    watchdog.arm(host_step)
                with spans.span("eval"):
                    batch, targets = prepare_batch(raw, tokenizer.pad_token_id)
                    if host_batch is not None:
                        batch, targets = host_batch(batch, targets)
                    batch, targets = make_global_batch(batch_sh, batch, targets)
                    capture_xla("eval_step", state_shapes, batch, targets)
                    # Token-weighted epoch aggregate (VERDICT r3 #9): each
                    # batch's mean loss/accuracy weighs by its valid-token
                    # count, so a padded final batch no longer weighs like a
                    # full one (the reference's mean-of-batch-means,
                    # main-single.py:128-137, is exact only when batches
                    # divide evenly). Counted on the GLOBAL targets (a jitted
                    # reduction over the sharded array), so every process
                    # aggregates with the same weights — a host-local count
                    # would make ranks disagree about the epoch metric
                    # (caught by tests/test_multiprocess.py).
                    weight = float(_valid_count(targets))
                    loss, acc = eval_step(state, batch, targets)
                    warm["eval"] = True
                    if weight > 0.0:
                        total_loss += float(loss) * weight
                        total_acc += float(acc) * weight
                        total_weight += weight
                    if total_weight > 0.0:
                        eval_metrics = {
                            "loss": total_loss / total_weight,
                            "accuracy": total_acc / total_weight,
                        }
                bar.set_description(
                    f"[validation] Epoch {epoch+1}/{epochs} | "
                    f"loss: {eval_metrics['loss']:.3f}, accuracy: {eval_metrics['accuracy']:.2f}"
                )
            logger.log(kind="validation", epoch=epoch, **eval_metrics)
            recorder.record("validation", epoch=epoch, **eval_metrics)
            if watchdog is not None:
                # generation + epoch-end checkpointing have their own (much
                # longer) natural durations; the next epoch's loop re-arms
                watchdog.disarm()

            # ---- qualitative eval (all processes compute — the replication
            # inside generate_samples is collective — process 0 prints) ----
            # clamp the decode budget so tiny --sequence_length debug
            # runs still fit a prompt in the position table
            gen_tokens = min(20, cfg.max_position_embeddings - 2)
            with spans.span("generate"):
                texts = generate_samples(
                    strategy, state, cfg, tokenizer, max_new_tokens=gen_tokens
                )
            if p0:
                print("Argmax sampling from model")
                for text in texts:
                    print(text)

            # ---- epoch wall-clock summary (span timeline): where the
            # epoch's host time went, and the goodput fraction (share spent
            # inside/waiting on the compiled steps) ------------------------
            ep = spans.epoch()
            logger.log(
                kind="epoch", epoch=epoch, goodput=ep["goodput"],
                total_s=ep["total_s"], seconds=ep["seconds"],
                fractions=ep["fractions"],
            )
            recorder.record(
                "epoch", epoch=epoch, goodput=ep["goodput"],
                total_s=round(ep["total_s"], 6),
            )
            if pending_checks:
                flush_checks()  # checks taken since the last window
            if heart is not None:
                heart.beat(
                    host_step,
                    checksum=last_checksum[1] if last_checksum else None,
                    checksum_step=last_checksum[0] if last_checksum else None,
                )
            if p0:
                print(f"epoch {epoch+1} wallclock: {format_breakdown(ep)}")

    # ---- final checkpoint (twin of main-single.py:146-151; format routed
    # by save_auto so sharded multi-host state never hits the consolidated
    # gather, VERDICT r2 #1) ----------------------------------------------
    checkpoint_path = save_checkpoint(state) or checkpoint_path
    if async_saver is not None:
        # exit barrier: fit() must not return before the last write is
        # durable (the caller may read or delete the checkpoint next)
        async_saver.wait()
    if cache_stats is not None and p0:
        cs = cache_stats.stats()
        logger.log(kind="compile_cache", **cs)
        print(
            f"compile cache {cs['dir']}: "
            f"{cs.get('hits', 0)} hits, "
            f"{cs.get('misses', cs['new_entries'])} misses, "
            f"{cs['entries']} entries (+{cs['new_entries']})"
        )
    logger.close()

    metrics = {
        "eval": eval_metrics if epochs else {},
        "tokens_per_sec": meter.tokens_per_sec,
        "tokens_per_sec_per_chip": meter.tokens_per_sec_per_chip,
        "mfu": meter.mfu,
        # exact global count (VERDICT r4 #6) — multi-process tests assert
        # ranks agree and match the dataset's real row total
        "train_tokens": meter.total_tokens,
        "spike_events": spike_events,
    }
    if p0 and meter.tokens_per_sec:
        print(
            f"throughput: {meter.tokens_per_sec:,.0f} tok/s "
            f"({meter.tokens_per_sec_per_chip:,.0f} tok/s/chip)"
            + (f", MFU {meter.mfu*100:.1f}%" if meter.mfu else "")
        )
    return FitResult(
        state=state, tokenizer=tokenizer, config=cfg,
        checkpoint_path=checkpoint_path, metrics=metrics,
    )
