"""Shared trainer: train state, jitted steps, and the epoch loop.

The reference duplicates its whole train/eval/generate/checkpoint loop in
every recipe (SURVEY §2.7; e.g. main-single.py:80-151 vs main-ddp.py:102-185
are near-identical). Here the loop lives once and the *strategy* is the only
thing a recipe supplies — the same pedagogical diff the cookbook wanted,
without the duplication.

Loop surface twins the reference exactly:
  - running train loss printed through tqdm every PRINT_FREQ=8 steps
    (main-single.py:19,104-108), process-0-gated in distributed recipes
    (tqdm(..., disable=rank != 0), main-ddp.py:106,137);
  - per-epoch validation loss + masked accuracy in the bar
    (main-single.py:110-138);
  - three fixed greedy generations per epoch: "The big brown cat ",
    "One day, ", "She said " (main-single.py:140-144), process-0 only;
  - end-of-training checkpoint (main-single.py:146-151).

TPU-native differences (deliberate, documented):
  - One jitted `train_step` holds forward+loss+backward+AdamW update; the
    state is donated, so parameters update in place in HBM.
  - The running-loss accumulator stays on device; the host syncs once per
    PRINT_FREQ window instead of the reference's per-step `loss.item()`
    (main-single.py:103, a D2H sync every step).
  - bf16 is the compute dtype (no GradScaler twin: bf16 needs no loss
    scaling; the reference's scaler is inert for bf16 anyway,
    main-single.py:78). `--disable_amp` flips compute to fp32. Eval runs
    in bf16 *unconditionally*, twinning the reference quirk of an
    always-enabled eval autocast (main-single.py:119).
  - `--disable_compile` maps to `jax.disable_jit()` (debug mode), the
    analogue of skipping torch.compile (main-single.py:38-39).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import time
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from tqdm import tqdm

from tpukit import chaos as chaos_lib
from tpukit import checkpoint as ckpt_lib
from tpukit import reshard as reshard_lib
from tpukit import retry as retry_lib
from tpukit.batching import IGNORE_INDEX, prepare_batch
from tpukit.cache import enable_compilation_cache
from tpukit.data import get_dataset, get_tokenizer, transform_dataset
from tpukit.flags import TrainFlags
from tpukit.loader import DataLoader
from tpukit.prefetch import HostPrefetcher
from tpukit.mesh import initialize_runtime, is_process_zero
from tpukit.recovery import (
    AnomalyAbort,
    Preempted,
    PreemptCoordinator,
    PreemptionGuard,
    RecoveryEngine,
    RollbackBudgetExhausted,
    RollbackCoordinator,
)
from tpukit.model import gpt
from tpukit.obs import (
    AnomalyTracer,
    FlightRecorder,
    HangWatchdog,
    Heartbeat,
    MetricRegistry,
    MFUMeter,
    SpanTimeline,
    SpikeSentinel,
    StepLogger,
    capture_compiler_stderr,
    compiled_stats,
    format_breakdown,
    format_checksum,
    global_norms,
    live_memory_stats,
    make_state_checksum,
    merge_snapshot_dir,
    profiler_trace,
    publish_snapshot,
    write_merged,
)
from tpukit.sampling import generate_batch
from tpukit.shardings import Strategy

PRINT_FREQ = 8  # twin of main-single.py:19
GENERATION_PROMPTS = ["The big brown cat ", "One day, ", "She said "]  # main-single.py:142-144


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jax.Array


def create_train_state(rng, cfg: gpt.GPTConfig, optimizer, strategy=None) -> TrainState:
    params = gpt.init_params(rng, cfg)
    if strategy is not None:
        # layout hook (e.g. Pipeline pads stacked layers to a stage multiple
        # with identity layers when num_layers doesn't divide the stages)
        params = strategy.prepare_params(params, cfg)
    return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.int32(0))


def make_optimizer(learning_rate: float) -> optax.GradientTransformation:
    """Twin of `torch.optim.AdamW(params, lr=...)` (main-single.py:42): torch
    AdamW defaults are betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2."""
    return optax.adamw(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=1e-2)


def make_step_fns(
    cfg: gpt.GPTConfig, optimizer, strategy: Strategy, state_shapes,
    seed: int = 0, log_grad_norms: bool = False,
):
    """Build jitted train/eval steps with the strategy's shardings applied.

    GSPMD reads the in/out shardings and inserts the collectives: grad psum
    for DP, per-tensor all-gather/reduce-scatter for FSDP, nothing for
    single-device. The pipeline strategy's schedule is inside its loss_fn.

    Dropout (VERDICT r2 #6): when cfg.dropout > 0 the train step folds the
    training step counter into a seed-derived key and threads it to the
    strategy's loss — active in training, never in eval (the reference's
    train()/eval() mode split, models/gpt.py:31,65). With dropout off no rng
    is traced at all, so the compiled step is unchanged.

    `log_grad_norms` (round-6 telemetry, --log_grad_norms): the train step
    ADDITIONALLY returns `{grad,update,param}_norm` f32 scalars, computed
    inside the same jitted program (the grads/updates are already live — no
    second compilation, no extra pass). Off (default): the traced graph is
    exactly the flag-free one, so the compiled HLO is byte-identical.
    """
    eval_cfg = cfg.replace(compute_dtype=jnp.bfloat16)  # eval autocast always on
    # The per-step key feeds dropout AND (round 12) the stochastic-rounding
    # noise of the DataParallel quantized grad psum — the one SR site a key
    # can be threaded into (FSDP's and EP's SR noise lives inside custom-vjp
    # backwards, which derive step-varying keys from the cotangent data
    # instead: quant_comm._fallback_key). With dropout 0 the rate-0 dropout
    # is an identity, so the SR-only case changes nothing but the rounding.
    needs_rng = cfg.dropout > 0 or (
        cfg.quant_stochastic and cfg.comm_dtype == "int8"
    )
    dropout_key = jax.random.PRNGKey(seed ^ 0x5EED) if needs_rng else None

    def train_step(state: TrainState, batch, targets):
        state = strategy.to_compute(state)
        rng = (
            jax.random.fold_in(dropout_key, state.step)
            if dropout_key is not None
            else None
        )

        # autodiff over loss_fn by default; Pipeline1F1B overrides with its
        # explicit per-stage-vjp schedule (see Strategy.value_and_grad)
        loss, grads = strategy.value_and_grad(
            state.params, cfg, batch, targets, rng=rng
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        if log_grad_norms:
            return new_state, loss, global_norms(grads, updates, params)
        return new_state, loss

    def eval_step(state: TrainState, batch, targets):
        state = strategy.to_compute(state)
        loss, accuracy = strategy.loss_fn(
            state.params, eval_cfg, batch, targets, with_accuracy=True
        )
        return loss, accuracy

    state_sh = strategy.state_sharding(state_shapes)
    state_sharding = TrainState(
        params=state_sh.params, opt_state=state_sh.opt_state, step=strategy.replicated()
    )
    batch_sh = strategy.batch_sharding()
    repl = strategy.replicated()

    train_out_sh = (state_sharding, repl)
    if log_grad_norms:
        norm_sh = {k: repl for k in ("grad_norm", "update_norm", "param_norm")}
        train_out_sh = (state_sharding, repl, norm_sh)
    train_step = jax.jit(
        train_step,
        in_shardings=(state_sharding, batch_sh, batch_sh),
        out_shardings=train_out_sh,
        donate_argnums=(0,),
    )
    eval_step = jax.jit(
        eval_step,
        in_shardings=(state_sharding, batch_sh, batch_sh),
        out_shardings=(repl, repl),
    )
    return train_step, eval_step, state_sharding


def make_global_batch(batch_sharding, model_batch, targets, place: bool = False):
    """Assemble per-process host arrays into global device arrays.

    Single-process: identity (jit places numpy at the sharding). Multi-host
    (the v4-32 ladder configs: one process per host, SURVEY §2.5): each
    process holds only its DistributedSampler shard of the batch —
    `jax.make_array_from_process_local_data` builds the global sharded
    array a cross-host jit can consume. This replaces the reference's
    per-rank DataLoader+DistributedSampler feeding (main-ddp.py:83-100);
    feeding the full global batch from every process would be rejected by
    a jit whose shardings span non-addressable devices.

    `place=True` (the prefetch path) makes the single-process case an
    explicit `jax.device_put` at the batch sharding instead of leaving the
    H2D copy to the jit boundary — so the transfer itself happens on the
    prefetch thread, ahead of the step that consumes it. Values are
    bit-identical either way (the batch is integer/bool data placed at the
    same sharding the jit would have used).
    """
    if jax.process_count() == 1:
        if not place:
            return model_batch, targets

        def conv(x):
            return jax.device_put(x, batch_sharding)

        return jax.tree.map(conv, model_batch), conv(targets)

    spec = batch_sharding.spec
    if len(spec) > 0 and spec[0] is not None:
        # batch rows are sharded across processes: each process supplied
        # only its DistributedSampler shard
        def conv(x):
            return jax.make_array_from_process_local_data(batch_sharding, x)
    else:
        # rows are process-replicated (pure pipeline / CP seq sharding):
        # every process loaded the identical full global batch; carve each
        # host's addressable shards out of it
        def conv(x):
            return jax.make_array_from_callback(
                x.shape, batch_sharding, lambda idx, x=x: x[idx]
            )

    return jax.tree.map(conv, model_batch), conv(targets)


@jax.jit
def _valid_count(targets):
    """Global valid-token count of a (possibly cross-host sharded) targets
    array. jit makes the sum a collective under GSPMD, so every process sees
    the same number — a host-side count would only cover the local shard."""
    return jnp.sum(targets != IGNORE_INDEX)


@functools.lru_cache(maxsize=None)
def _replicator(mesh):
    """One jitted all-gather-to-replicated program per mesh — rebuilding the
    lambda per call would retrace (and recompile) every epoch."""
    from jax.sharding import NamedSharding

    repl = NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.jit(lambda p: p, out_shardings=repl)


def replicated_params(strategy: Strategy, state: TrainState):
    """Parameters addressable on every host for the decode loop — running it
    on process 0 with params still sharded across hosts is the reference's
    latent multi-host hang (rank-0-only FSDP generate, main-ddp.py:170-174,
    SURVEY §3.5). This is a collective — EVERY process must call it.

    Small models get a fully-replicated copy (one compiled all-gather, then
    the 20-step decode runs gather-free). Past TPUKIT_REPLICATE_PARAMS_MB
    (default 1 GiB — ADVICE r3: FSDP configs that shard out of memory
    necessity would OOM on a transient full copy) the params keep their
    sharded layout — routed through `strategy.to_compute` so offloaded
    (pinned_host) state still moves into device memory — and the decode jit
    lets GSPMD gather per-op: one layer's parameters live at a time instead
    of all of them.
    """
    limit = int(os.environ.get("TPUKIT_REPLICATE_PARAMS_MB", "1024")) * 2**20
    total = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(state.params)
    )
    if total > limit:
        # Move ONLY the params subtree into device memory: to_compute maps
        # leaf-wise, and running it on the whole TrainState would transiently
        # pull both Adam moments (~3x params) into HBM for a decode that
        # never reads them (ADVICE r4).
        return strategy.to_compute(state.params)
    return _replicator(strategy.mesh)(state.params)


def generate_samples(
    strategy: Strategy,
    state: TrainState,
    cfg: gpt.GPTConfig,
    tokenizer,
    prompts=GENERATION_PROMPTS,
    max_new_tokens: int = 20,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
) -> list[str]:
    """SPMD-safe qualitative eval: replicate params, then decode each
    prompt (greedy by default; `temperature`/`top_k`/`seed` sample — round
    14, through the serving engine's batched KV-cached decode). Every
    process must call this (the replication is collective); each returns
    the same texts, and the caller prints on process 0 only — the
    reference's rank-0 gating (main-ddp.py:170-174) moved from "only
    rank 0 computes" (a deadlock for sharded state) to "all compute, rank 0
    prints"."""
    params = replicated_params(strategy, state)
    # Strategies that train on a re-laid-out param tree (the interleaved
    # pipeline stores the layer stack chunk-permuted) restore the natural
    # layer order for the plain sequential decode; identity for the rest.
    params = strategy.inference_params(params, cfg)
    # ONE batched jitted call (VERDICT r4 #7): one compile and one decode
    # per epoch instead of a serial compile+decode per prompt — `generate`
    # stays as the single-prompt API.
    return generate_batch(
        params, cfg, list(prompts), tokenizer, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, seed=seed,
    )


def _place_like(host_tree, sharding_tree):
    """Place a host-array pytree at the given shardings (multi-host safe —
    see mesh.place_host_array)."""
    from tpukit.mesh import place_host_array

    return jax.tree.map(place_host_array, host_tree, sharding_tree)


@contextlib.contextmanager
def _debug_nans_scope():
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@dataclasses.dataclass
class FitResult:
    state: TrainState
    tokenizer: Any
    config: gpt.GPTConfig
    checkpoint_path: Any
    metrics: dict


def fit(
    flags: TrainFlags,
    strategy: Strategy,
    num_epochs: int | None = None,
    make_loaders: Callable | None = None,
) -> FitResult:
    """The shared training entry point every recipe calls.

    Round 9: `fit` validates the recovery flags, installs the run-scoped
    environment — SIGTERM/SIGINT preemption handlers, the chaos
    fault-injection engine (`--chaos_spec`), the transient-I/O retry
    policy + observer (`--io_retries`) — and guarantees their teardown on
    EVERY exit path (clean, abort, preemption, crash), so none of it
    leaks across fits in one process. The training loop itself lives in
    `_fit_body`.
    """
    initialize_runtime()
    if flags.prefetch < 0:
        raise ValueError(f"--prefetch must be >= 0, got {flags.prefetch}")
    if flags.hang_timeout < 0:
        raise ValueError(f"--hang_timeout must be >= 0, got {flags.hang_timeout}")
    if flags.divergence_check_freq < 0:
        raise ValueError(
            f"--divergence_check_freq must be >= 0, got "
            f"{flags.divergence_check_freq}"
        )
    if flags.on_anomaly not in ("none", "rollback"):
        raise ValueError(
            f"--on_anomaly must be none|rollback, got {flags.on_anomaly!r}"
        )
    if flags.max_rollbacks < 0:
        raise ValueError(f"--max_rollbacks must be >= 0, got {flags.max_rollbacks}")
    if flags.io_retries < 0:
        raise ValueError(f"--io_retries must be >= 0, got {flags.io_retries}")
    if flags.keep_checkpoints < 0:
        raise ValueError(
            f"--keep_checkpoints must be >= 0 (0 keeps everything), got "
            f"{flags.keep_checkpoints}"
        )
    if flags.on_anomaly == "rollback" and jax.process_count() > 1 and not flags.heartbeat_dir:
        # the rollback decision is made collective through the heartbeat
        # directory; without it a multi-process world could roll back to
        # two different steps and deadlock in mismatched collectives
        raise ValueError(
            "--on_anomaly rollback needs --heartbeat_dir on multi-process "
            "runs: the rollback decision is published through the shared "
            "heartbeat directory"
        )
    # Chaos harness (round 9): parse NOW so a typo'd fault plan fails at
    # startup, not silently never fires. Installed module-wide for the
    # run's duration (checkpoint/loader I/O sites reach it through
    # tpukit.chaos.maybe_io_fault); uninstalled on any exit.
    chaos_engine = (
        chaos_lib.ChaosEngine(
            flags.chaos_spec, seed=flags.seed,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
        if flags.chaos_spec
        else None
    )
    # Transient host-I/O retry policy + observer: every retried attempt
    # lands in the JSONL (kind="retry") and the flight-recorder ring.
    retry_log = retry_lib.RetryLog()
    prev_policy = retry_lib.set_default_policy(
        retry_lib.RetryPolicy(retries=flags.io_retries)
    )
    retry_lib.set_observer(retry_log)
    prev_chaos = chaos_lib.install(chaos_engine)
    guard = PreemptionGuard()
    try:
        with guard:
            return _fit_body(
                flags, strategy, num_epochs, make_loaders,
                chaos_engine, retry_log, guard,
            )
    finally:
        chaos_lib.install(prev_chaos)
        retry_lib.set_observer(None)
        retry_lib.set_default_policy(prev_policy)


def _fit_body(
    flags: TrainFlags,
    strategy: Strategy,
    num_epochs: int | None,
    make_loaders: Callable | None,
    chaos_engine,
    retry_log,
    preempt_guard: PreemptionGuard,
) -> FitResult:
    p0 = is_process_zero()
    # Persistent XLA compilation cache (round 7): repeat runs of the same
    # program skip recompiles; hits/misses are logged at the end of the run.
    cache_stats = (
        enable_compilation_cache(flags.compilation_cache_dir)
        if flags.compilation_cache_dir
        else None
    )

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2  # every recipe pins pad to 2 (main-single.py:23)

    compute_dtype = jnp.float32 if flags.disable_amp else jnp.bfloat16
    cfg = gpt.GPTConfig(
        dim=flags.dim,
        head_dim=flags.head_dim,
        heads=flags.heads,
        num_layers=flags.num_layers,
        vocab_size=tokenizer.vocab_size,
        max_position_embeddings=flags.sequence_length,
        dropout=flags.dropout,
        compute_dtype=compute_dtype,
        remat_layers=flags.remat,
        scan_layers=flags.scan_layers,
        num_experts=flags.num_experts,
        router_top_k=flags.moe_top_k,
        virtual_stages=flags.virtual_stages,
        comm_dtype=flags.comm_dtype,
        quant_stochastic=flags.quant_stochastic,
        grad_buckets=flags.grad_buckets,
    )
    optimizer = make_optimizer(flags.learning_rate)
    strategy.validate_config(cfg)  # fail fast with a clear shape/mesh error

    # ---- data -----------------------------------------------------------
    if make_loaders is not None:
        train_loader, validation_loader = make_loaders(flags, tokenizer, strategy)
        # meter math: a rank-sharded custom loader reports per-host rows
        loader_procs = getattr(train_loader, "num_replicas", 1)
        global_batch = None  # a custom loader owns its batch geometry
    else:
        train_ds, validation_ds = get_dataset(slice_size=flags.dataset_slice)
        train_ds = transform_dataset(
            train_ds, tokenizer, max_length=flags.sequence_length, num_proc=flags.num_workers
        )
        validation_ds = transform_dataset(
            validation_ds, tokenizer, max_length=flags.sequence_length, num_proc=flags.num_workers
        )
        # Global batch = per-replica batch x data-parallel degree, the twin
        # of "per-rank DataLoader(batch_size)" under torchrun (main-ddp.py:
        # 83-100). Wrap-padding keeps every step full-shape — the twin of
        # DistributedSampler's pad-by-wrapping, applied unconditionally so
        # the jitted step compiles exactly once (a ragged final batch would
        # recompile and, under Pipeline, violate the micro-batch divisor).
        replicas = strategy.mesh.shape.get("data", 1)
        global_batch = flags.batch_size * replicas
        if global_batch % strategy.batch_divisor:
            raise ValueError(
                f"global batch {global_batch} (batch_size {flags.batch_size} x "
                f"{replicas} data shards) must be a multiple of "
                f"{strategy.batch_divisor} for the {strategy.name} strategy"
            )
        # Multi-host: when the strategy shards batch rows, each process
        # loads only its DistributedSampler shard of every global batch
        # (twin of per-rank DataLoader under torchrun, main-ddp.py:83-100);
        # make_global_batch assembles the global array. Strategies that
        # replicate rows across processes (pure pipeline / CP) need the
        # identical full batch on every host instead.
        spec = strategy.batch_spec()
        rows_sharded = len(spec) > 0 and spec[0] is not None
        procs = jax.process_count() if rows_sharded else 1
        rank = jax.process_index() if rows_sharded else 0
        if global_batch % procs:
            raise ValueError(
                f"global batch {global_batch} must divide across {procs} hosts"
            )
        per_host = global_batch // procs
        loader_procs = procs
        train_loader = DataLoader(
            train_ds, per_host, shuffle=True, seed=flags.seed, drop_last=False,
            pad_to_batch=True, num_replicas=procs, rank=rank,
        )
        # Validation pads with all-ignore rows (not wrap-duplicates), so the
        # final batch's metrics equal the exact partial-batch metrics the
        # reference's single-device eval computes (main-single.py:110-138).
        validation_loader = DataLoader(
            validation_ds, per_host, shuffle=False, pad_to_batch=True,
            pad_mode="empty", pad_fill=tokenizer.pad_token_id,
            num_replicas=procs, rank=rank,
        )

    # ---- state ----------------------------------------------------------
    init_fn = partial(create_train_state, cfg=cfg, optimizer=optimizer, strategy=strategy)
    state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(flags.seed))
    train_step, eval_step, state_sharding = make_step_fns(
        cfg, optimizer, strategy, state_shapes, seed=flags.seed,
        log_grad_norms=flags.log_grad_norms,
    )

    # Initialize directly into the sharded layout (no host-side giant pytree).
    state = jax.jit(init_fn, out_shardings=state_sharding)(jax.random.PRNGKey(flags.seed))

    # The world THIS run saves from / resumes into (round 13): every save's
    # meta sidecar records it, and `--resume` compares it against the
    # checkpoint's to decide plain-restore vs reshard.
    run_world = reshard_lib.current_world(strategy, global_batch=global_batch)

    # Mid-epoch continuation (round 9): a PREEMPTION save carries resume
    # metadata (epoch + batches consumed); resuming from one continues the
    # interrupted epoch at the exact batch it stopped at — the uninterrupted
    # run's state, bit-exact. Other checkpoints (periodic/final) keep the
    # established semantics: train `--epochs` more epochs from batch 0.
    # Round 13 makes the restore ELASTIC: a checkpoint whose recorded world
    # differs from this run's is resharded onto the current state_sharding
    # specs (tpukit/reshard.py) instead of failing or silently misloading.
    start_epoch, start_skip = 0, 0
    resize_event = None
    if flags.resume:
        from pathlib import Path

        resume_path = (
            ckpt_lib.latest_any() if flags.resume == "latest" else Path(flags.resume)
        )
        if resume_path is None or not resume_path.exists():
            raise FileNotFoundError(
                f"--resume {flags.resume}: no checkpoint found"
            )
        if flags.resume != "latest":
            # `latest_any` already verified its pick (hashing the whole
            # blob / every shard); only an explicit path needs the check.
            ok, detail = ckpt_lib.verify_checkpoint(resume_path)
            if not ok:
                raise ValueError(
                    f"--resume {flags.resume}: checkpoint {resume_path} "
                    f"failed integrity verification ({detail})"
                )
        meta = ckpt_lib.read_meta(resume_path)
        saved_w = reshard_lib.saved_world(resume_path)
        mismatch = reshard_lib.describe_mismatch(saved_w, run_world)
        if mismatch and meta and meta.get("resize_to") is not None:
            # resize@N:M chaos contract: the preempt-save named the world
            # it expects to come back at — a relaunch at a DIFFERENT world
            # that is not M means the resize path under test was not
            # exercised; fail loud instead of quietly passing another
            # scenario. A same-world resume (mismatch is None) stays
            # legal: that is how a control run reproduces the trajectory.
            want = int(meta["resize_to"])
            if want != run_world["device_count"]:
                raise RuntimeError(
                    f"--resume {flags.resume}: checkpoint {resume_path} was "
                    f"preempt-saved by a resize@N:{want} chaos fault "
                    f"expecting relaunch at {want} devices, but this world "
                    f"has {run_world['device_count']}"
                )
        if mismatch:
            # Stale-incarnation sweep BEFORE any new-world reader exists:
            # beat files, rollback decisions and preempt requests from the
            # old world carry step numbers, checksums and process indices
            # the resized world must never compare against (a vanished
            # rank's beat file is never overwritten — without the sweep it
            # poisons the straggler/divergence checks forever).
            swept = (
                reshard_lib.sweep_stale_world(flags.heartbeat_dir)
                if flags.heartbeat_dir and p0
                else []
            )
            state, rs_info = reshard_lib.reshard_restore(
                resume_path, state_shapes, state_sharding
            )
            resize_event = dict(
                kind="resize",
                step=int(jax.device_get(state.step)),
                checkpoint=str(resume_path),
                mismatch=mismatch,
                saved_world=saved_w,
                world=run_world,
                swept=swept,
                **rs_info,
            )
        else:
            # Both formats restore against the abstract state_shapes (never
            # a device_get of the live state — that is exactly the gather
            # that fails for cross-host-sharded state). Sharded checkpoints
            # place their shards straight into the strategy's shardings;
            # consolidated ones come back as host arrays and are placed
            # below.
            restored, was_sharded = ckpt_lib.restore_any(
                resume_path, state_shapes, state_sharding
            )
            state = (
                restored if was_sharded else _place_like(restored, state_sharding)
            )
        if meta and meta.get("preempted"):
            start_epoch = int(meta.get("epoch", 0))
            start_skip = int(meta.get("batch_in_epoch", 0))
            saved_gb = (saved_w or {}).get("global_batch")
            if start_skip and saved_gb and global_batch and saved_gb != global_batch:
                import warnings

                warnings.warn(
                    f"mid-epoch resume across a global-batch change "
                    f"({saved_gb} -> {global_batch} rows): batch_in_epoch "
                    f"counts the OLD world's batches, so the stream position "
                    f"is approximate — hold batch_size x data-shards "
                    f"constant across a resize for exact continuation",
                    stacklevel=2,
                )
        if p0:
            print(
                f"resumed from {resume_path} at step {int(jax.device_get(state.step))}"
                + (
                    f" (resharded: {mismatch})"
                    if resize_event is not None
                    else ""
                )
                + (
                    f" (preempted mid-epoch: continuing epoch {start_epoch} "
                    f"at batch {start_skip})"
                    if start_skip or meta and meta.get("preempted")
                    else ""
                )
            )
    if chaos_engine is not None and chaos_engine.skip_batches:
        # chaos `skip@N`: fast-forward the first trained epoch's stream by
        # N batches WITHOUT moving the step counter — exactly the stream
        # position a post-rollback run sits at, which is what lets a
        # control run reproduce a recovered run's trajectory bit-exactly.
        start_skip += chaos_engine.skip_batches

    batch_sh = strategy.batch_sharding()
    # Host-side batch transform (ContextParallel's zigzag permute — ADVICE
    # r4: in-jit it is a per-step cross-shard reshard collective).
    host_batch = strategy.host_batch_fn(cfg)

    def host_pipeline(raw):
        """The whole host side of one training batch — prepare, strategy
        transform, global-array assembly WITH explicit device placement.
        This is what the prefetch thread runs `--prefetch` batches ahead;
        it is the same work the synchronous path's data+h2d spans time."""
        b, t = prepare_batch(raw, tokenizer.pad_token_id)
        if host_batch is not None:
            b, t = host_batch(b, t)
        b, t = make_global_batch(batch_sh, b, t, place=True)
        return raw, b, t

    # Checkpoint writer: the async writer snapshots on this thread and
    # publishes from a background one (join barrier at the next save), so
    # periodic saves stop stalling the step loop on encode+disk I/O.
    async_saver = ckpt_lib.AsyncCheckpointer() if flags.async_checkpoint else None

    def save_checkpoint(st, meta=None):
        # Every save records the SAVING world (round 13): the meta sidecar's
        # `world` entry is what lets a relaunch detect a topology change and
        # reshard instead of failing — periodic and final saves carry it
        # too, not just preemption saves, because any checkpoint can be the
        # one an elastic relaunch resumes from.
        meta = {**(meta or {}), "world": run_world}
        if async_saver is not None:
            return async_saver.save_auto(
                st, format=flags.checkpoint_format, meta=meta
            )
        return ckpt_lib.save_auto(st, format=flags.checkpoint_format, meta=meta)

    def prune_checkpoints() -> None:
        """Retention (--keep_checkpoints K, round 13): after a successful
        publish, drop published checkpoints older than the newest K.
        Quarantined timelines and the newest integrity-verified
        (`latest_good`) candidate are never pruned (checkpoint.py). An
        in-flight async save is invisible to the scan until its atomic
        publish — the next prune catches up."""
        if flags.keep_checkpoints <= 0 or not p0:
            return
        # assume_newest_verified: this call always follows OUR OWN publish,
        # whose writer just computed the checksums — re-hashing it here
        # every save interval would double per-save disk I/O.
        removed = ckpt_lib.prune_checkpoints(
            "checkpoints", keep=flags.keep_checkpoints,
            assume_newest_verified=True,
        )
        if removed:
            logger.log(
                kind="ckpt_prune", step=host_step,
                keep=flags.keep_checkpoints, pruned=removed,
            )
            recorder.record("ckpt_prune", step=host_step, pruned=len(removed))

    seq = flags.sequence_length - 1  # model sees S-1 after the shift
    meter = MFUMeter(cfg, seq)
    logger = StepLogger(flags.metrics_log if p0 else "")
    # ---- telemetry (tpukit/obs, round 6) --------------------------------
    spans = SpanTimeline()
    # Flight recorder (round 8): always on — a bounded ring of recent
    # step/window/sentinel records, read only when a diagnostics bundle is
    # dumped. The cost is one dict + deque append per step (<1% of any
    # real step; bench.py's obs_overhead record audits it).
    recorder = FlightRecorder()
    # Metrics plane (round 22): mergeable counters/gauges/log-bucket
    # histograms derived from telemetry the loop ALREADY computes (the
    # window spans, the MFU meter, the recovery observers) — never a new
    # sync or wall read on the hot path. Pure observer: --no_metrics must
    # not change a single token (bench.py's metrics_overhead record
    # asserts bit-identity and <1% throughput cost).
    metric_reg = None if flags.no_metrics else MetricRegistry()

    def publish_metrics(final: bool = False) -> None:
        """Atomic per-process snapshot into --metrics_dir (heartbeat-file
        discipline: every process writes its own file, process 0 merges).
        Window cadence, so tools/top.py can tail a live run."""
        if metric_reg is None or not flags.metrics_dir:
            return
        nproc = jax.process_count()
        publish_snapshot(
            flags.metrics_dir, jax.process_index(), metric_reg,
            process_count=nproc, time_s=time.time(),
        )
        if p0:
            merged, meta = merge_snapshot_dir(flags.metrics_dir, nproc)
            write_merged(flags.metrics_dir, merged, meta=meta)

    if resize_event is not None:
        # the elastic restore happened before the logger existed; surface
        # it now so the JSONL (and tools/report.py) names the topology
        # change, the reshard cost, and the stale files swept
        logger.log(**resize_event)
        recorder.record(
            "resize", step=resize_event["step"],
            mismatch=resize_event["mismatch"],
        )
        if p0:
            print(
                f"elastic resize: {resize_event['mismatch']} "
                f"({resize_event['format']} reshard, "
                f"{resize_event['bytes_read']} bytes read in "
                f"{resize_event['wall_s']:.3f}s)"
            )
    # Sentinel runs on EVERY process with identical inputs (the window loss
    # is a replicated global mean), so an "abort" decision is collective-
    # consistent — each process checkpoints and raises in lockstep instead
    # of process 0 abandoning a collective the others are blocked in.
    sentinel = (
        SpikeSentinel(flags.spike_threshold)
        if flags.spike_threshold > 0
        else None
    )
    heart = (
        Heartbeat(flags.heartbeat_dir, timeout_s=flags.heartbeat_timeout)
        if flags.heartbeat_dir
        else None
    )
    spike_events = 0
    # XLA static analysis (cost/memory/comm bytes) is captured once per
    # compiled step function, lazily at its first batch (real avals in
    # hand), and only when a metrics log is requested — with telemetry off
    # nothing here touches the step functions.
    xla_pending = {"train_step": train_step, "eval_step": eval_step}

    def capture_xla(fn_name, *call_args):
        jitted = xla_pending.pop(fn_name, None)
        # p0-gated like the logger that consumes it: the analysis
        # (as_text + HLO parse) is pure host work other processes would
        # only discard. The AOT lower/compile it triggers is process-local,
        # so skipping it off-p0 cannot desynchronize a multi-host run.
        if jitted is None or not flags.metrics_log or not p0:
            return
        with spans.span("telemetry"):
            structs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), call_args
            )
            hlo = {}
            # the AOT compile below is what emits GSPMD's involuntary-
            # remat warnings — captured here so the lint's remat rule
            # audits the production compile, not an empty string (a
            # cache-served compile stays silent; the CI lane runs cold
            # for exactly that reason)
            with capture_compiler_stderr() as cap:
                stats = compiled_stats(jitted, *structs, hlo_out=hlo)
        if stats:
            ops_for = getattr(strategy, "comm_ops_for", None)
            expected = (
                ops_for(cfg) if ops_for is not None
                else getattr(strategy, "comm_ops", ())
            )
            extra = {}
            # Hand-scheduled dispatch audit (round 10): strategies that
            # place their own collectives (ExpertParallel's a2a MoE
            # dispatch) predict the per-device all-to-all payload in
            # closed form; the record carries it next to the measured HLO
            # bytes so tools/report.py can flag a dispatch regression.
            audit_fn = getattr(strategy, "dispatch_comm", None)
            if audit_fn is not None:
                ids = call_args[1]["input_ids"]
                # backend-aware expectation (round 12): the formula prices
                # in XLA:CPU's bf16->f32 wire upcast, so the renderer can
                # compare bytes EXACTLY on every backend instead of
                # soft-excusing CPU eval windows
                audit = audit_fn(
                    cfg, global_batch=ids.shape[0], seq=ids.shape[1],
                    backend=jax.default_backend(),
                )
                if audit:
                    key = "train" if fn_name == "train_step" else "eval"
                    extra["a2a_expected"] = audit[key]
            # quantized grad-collective audit (round 12): DDP/FSDP predict
            # their compressed grad payload in closed form; the record
            # carries it next to the measured HLO bytes
            grad_fn = getattr(strategy, "grad_comm", None)
            if grad_fn is not None and fn_name == "train_step":
                gaudit = grad_fn(
                    cfg, state_shapes.params, backend=jax.default_backend()
                )
                if gaudit:
                    extra["quant_grad_expected"] = gaudit
                    extra["comm_dtype"] = cfg.comm_dtype
            # hlolint rule verdicts (round 16, tpukit/analysis): the same
            # engine the dryrun and tools/hlolint.py run — CommPlan diff,
            # remat/wire/donation/index-plumbing rules, overlap tally —
            # summarized onto the record so a report can flag a schedule
            # regression without recompiling anything. Best-effort like
            # the rest of telemetry: a lint crash must never take down
            # the run.
            if hlo.get("text"):
                try:
                    from tpukit.analysis import (
                        lint_module, parse_hlo,
                        summarize as lint_summarize, train_comm_plan,
                    )

                    ids = call_args[1]["input_ids"]
                    lint_plan = train_comm_plan(
                        strategy, cfg, param_shapes=state_shapes.params,
                        global_batch=ids.shape[0], seq=ids.shape[1],
                        backend=jax.default_backend(),
                        phase="train" if fn_name == "train_step" else "eval",
                    )
                    findings = lint_module(
                        parse_hlo(hlo["text"]), plan=lint_plan,
                        compiler_stderr=cap["text"],
                        backend=jax.default_backend(),
                        # train_step donates the state (donate_argnums);
                        # eval_step does not
                        expect_donated=(
                            len(jax.tree_util.tree_leaves(state_shapes))
                            if fn_name == "train_step" else None
                        ),
                    )
                    extra["hlolint"] = lint_summarize(findings)
                except Exception:
                    pass
            logger.log(
                kind="xla", fn=fn_name, strategy=strategy.name,
                backend=jax.default_backend(),
                expected_comm_ops=list(expected), **extra, **stats,
            )

    epochs = num_epochs if num_epochs is not None else flags.epochs
    checkpoint_path = None

    # ---- recovery engine (round 9, docs/DESIGN.md "recovery") -----------
    # --on_anomaly rollback: a sentinel/divergence firing restores the
    # last integrity-verified checkpoint older than the detection window,
    # in process, and training continues with the input stream still
    # moving FORWARD (the offending batch window is never replayed).
    recovery = (
        RecoveryEngine(
            "checkpoints",
            max_rollbacks=flags.max_rollbacks,
            coordinator=RollbackCoordinator(
                flags.heartbeat_dir or None,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
                timeout_s=flags.heartbeat_timeout,
            ),
        )
        if flags.on_anomaly == "rollback"
        else None
    )
    timeline = 0  # collective rollbacks executed (tags heartbeat checksums)
    skip_save_step = -1  # suppress the periodic re-save right after a restore
    # Multi-process preemption is collectivized the same way (see
    # recovery.PreemptCoordinator): the graceful checkpoint is a
    # step-keyed collective write, so every rank must save at the same
    # step even though their host loops observe the signal at different
    # wall-clocks. Without a shared heartbeat directory we fall back to
    # the uncoordinated poll and say so once.
    preempt_coord = None
    if jax.process_count() > 1:
        if flags.heartbeat_dir:
            preempt_coord = PreemptCoordinator(
                flags.heartbeat_dir,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        elif jax.process_index() == 0:
            import warnings

            warnings.warn(
                "multi-process run without --heartbeat_dir: a SIGTERM/"
                "SIGINT preemption checkpoint cannot be coordinated across "
                "processes and may deadlock the step-keyed collective save "
                "if ranks observe the signal at different steps"
            )

    # The step counter is tracked on host (one D2H sync here, after a
    # possible resume, then pure host arithmetic) so periodic checkpointing
    # never forces a per-step `int(state.step)` sync inside the hot loop.
    host_step = int(state.step)
    if preempt_coord is not None:
        # Tag this incarnation's coordination records with its starting
        # step: every rank restores the same checkpoint, so the tag is
        # collective for free, and a stale decision/request that survives
        # the init cleanup (relaunch race — a fast rank can poll before a
        # slow p0's sweep) can never match a resumed run, whose start step
        # sits exactly ON the stale decision's execute_after boundary.
        preempt_coord.run_start = host_step

    # ---- failure observability (round 8): watchdog + bundles + trace-on-
    # anomaly + divergence checksums (docs/DESIGN.md "failure
    # observability"). The watchdog exists whenever bundles can be asked
    # for (--hang_timeout and/or --debug_dir); its monitor thread only
    # runs with a positive timeout.
    debug_dir = flags.debug_dir or (
        "debug"
        if flags.hang_timeout > 0
        or flags.trace_on_anomaly > 0
        or flags.divergence_check_freq > 0
        else ""
    )
    # in-flight async state the bundle snapshots; the prefetcher slot is
    # re-pointed each epoch
    pf_live: dict[str, Any] = {"pf": None}

    def _prefetch_probe():
        pf = pf_live["pf"]
        if pf is None:
            return None
        return {"depth": pf.depth, "buffered": pf.buffered}

    watchdog = (
        HangWatchdog(
            debug_dir,
            timeout_s=flags.hang_timeout,
            recorder=recorder,
            heartbeat=heart,
            probes={
                "host_step": lambda: host_step,
                "async_checkpoint_in_flight": (
                    lambda: async_saver.in_flight if async_saver else False
                ),
                "prefetcher": _prefetch_probe,
            },
            config=flags,
        )
        if debug_dir
        else None
    )
    # Trace-on-anomaly: the FIRST anomaly arms a jax.profiler capture of
    # the next K steps. Mutually exclusive with a whole-run --profile_dir
    # trace (jax supports one active capture).
    tracer = (
        AnomalyTracer(
            os.path.join(debug_dir, "anomaly_trace"), flags.trace_on_anomaly
        )
        if flags.trace_on_anomaly > 0 and debug_dir and not flags.profile_dir
        else None
    )
    # Divergence checksums ride a SEPARATE jitted program so the train
    # step's HLO is byte-identical with the flag off (tests assert it).
    checksum_fn = (
        make_state_checksum() if flags.divergence_check_freq > 0 else None
    )
    if checksum_fn is not None:
        if jax.process_count() > 1 and heart is None:
            # cross-replica comparison rides the heartbeat files; without
            # them a multi-host run would pay for checksums that nothing
            # ever compares — the exact silent failure this flag exists
            # to catch. Fail loudly instead.
            raise ValueError(
                "--divergence_check_freq needs --heartbeat_dir on multi-"
                "process runs: checksums are compared across processes "
                "through the shared heartbeat files"
            )
        # Compile the checksum program NOW, before the watchdog ever arms:
        # its one-off trace+compile at the first check step would otherwise
        # run inside an armed iteration and a long compile could dump a
        # spurious "hang" bundle (and burn the once-per-run anomaly trace).
        jax.block_until_ready(checksum_fn(state)["params"])
    last_checksum: tuple[int, str] | None = None  # (step, hex)
    # (process, checksum_step, checksum) triples already reported: beats
    # republish the same mismatch every window until the next check step,
    # and one divergence must not spam the JSONL or drain the bundle budget
    reported_divergence: set = set()
    # Check-step dispatches are ASYNC (two u32 scalars in flight, the
    # producing state released at dispatch); the D2H read happens at the
    # window boundary, which syncs anyway — so a check step costs one
    # extra jitted pass, never a mid-window pipeline stall.
    pending_checks: list[tuple[int, dict]] = []
    hangs_logged = 0

    def flush_checks() -> None:
        nonlocal last_checksum
        for st, ck in pending_checks:
            cs = format_checksum(ck)  # the deferred D2H read
            last_checksum = (st, cs)
            recorder.record("divergence_check", step=st, checksum=cs)
            logger.log(kind="divergence_check", step=st, checksum=cs)
        pending_checks.clear()

    def note_anomaly(reason: str, step: int) -> None:
        """First anomaly arms the trace; every anomaly lands in the ring."""
        recorder.record("anomaly", reason=reason, step=step)
        if tracer is not None and tracer.trigger(reason):
            logger.log(
                kind="anomaly_trace", event="armed", reason=reason, step=step
            )

    def dump_bundle(reason: str, step: int, **ctx):
        if watchdog is None:
            return None
        path = watchdog.trigger(reason, step=step, **ctx)
        if path is not None:
            logger.log(
                kind="watchdog", event="bundle", reason=reason, step=step,
                bundle=str(path),
            )
        return path

    # ---- round-9 helpers: side-event drain, preemption, rollback --------

    def drain_side_events() -> None:
        """Surface retry/chaos events collected since the last drain (they
        fire on any thread: training, async-checkpoint writer, prefetch
        worker) into the JSONL + flight recorder, on the training thread."""
        for ev in retry_log.drain():
            logger.log(kind="retry", step=host_step, **ev)
            recorder.record("retry", step=host_step, **ev)
            if metric_reg is not None:
                metric_reg.inc("train_retries")
        if chaos_engine is not None:
            for ev in chaos_engine.drain_fired():
                rec = dict(ev)
                rec.setdefault("step", host_step)
                logger.log(kind="chaos", **rec)
                recorder.record("chaos", **rec)

    null_polls = [0]  # eval/generate-phase poll throttle (see below)

    def check_preempt(consumed: int | None, epoch_idx: int) -> None:
        """Graceful preemption (SIGTERM/SIGINT → exit code 75): polled at
        iteration boundaries, where device state is coherent. Writes a
        DURABLE checkpoint carrying resume metadata — the epoch and the
        number of batches consumed (`consumed=None` means the epoch's
        training phase is complete) — so `--resume latest` continues the
        interrupted epoch at the exact batch it stopped at, bit-exact."""
        sig = preempt_guard.pending
        if preempt_coord is not None:
            # Multi-process: collectivize through the heartbeat directory.
            # Ranks publish their pending signal as a request; process 0
            # turns the first request into a decision naming a window
            # boundary ≥ one full window ahead (host loops can run up to a
            # window past the collective frontier, so anything closer could
            # already be behind a rank); every rank's deterministic
            # host-step counter passes through that boundary's poll exactly
            # once, so the step-keyed collective save matches. A decision
            # whose boundary falls past the end of training is never
            # executed — all ranks uniformly finish clean (exit 0), which
            # is strictly better than a preempt exit anyway.
            boundary = consumed is None or host_step % PRINT_FREQ == 0
            if sig is not None:
                preempt_coord.request(sig)
            elif not boundary:
                return  # cheap poll: no signal here, not a boundary step
            elif consumed is None:
                # eval/generate call this per batch with host_step frozen:
                # a per-batch decision-file read (plus p0's request glob)
                # hammers a shared filesystem for nothing. Poll at the
                # window cadence instead — the counter advances identically
                # on every rank (same batch sequence), so a matching
                # decision is still executed by all ranks at the same poll.
                # An actual local signal (sig above) is never throttled.
                null_polls[0] += 1
                if null_polls[0] % PRINT_FREQ:
                    return
            dec = preempt_coord.read()
            if dec is None and p0 and boundary:
                req = sig or preempt_coord.any_request()
                if req is not None:
                    dec = preempt_coord.publish(
                        req,
                        execute_after=(
                            (host_step // PRINT_FREQ + 2) * PRINT_FREQ
                        ),
                    )
            if dec is None:
                return
            if host_step != int(dec["execute_after"]):
                # not the decision's boundary: keep training (epoch-end
                # polls included — the in-loop poll at execute_after is hit
                # by every rank, possibly in the next epoch)
                return
            sig = dec["signal"]
        elif sig is None:
            return
        if watchdog is not None:
            watchdog.disarm()
        if consumed is None:
            ep, nb = epoch_idx + 1, 0
        else:
            ep, nb = epoch_idx, consumed
            spe = (
                len(train_loader)
                if hasattr(train_loader, "__len__")
                else None
            )
            if spe is not None and nb >= spe:
                ep, nb = ep + 1, 0  # epoch boundary: resume starts the next
        meta = {
            "step": host_step, "epoch": ep, "batch_in_epoch": nb,
            "preempted": True, "signal": sig,
        }
        if chaos_engine is not None and chaos_engine.resize_target is not None:
            # resize@N:M chaos: record the world this run expects to come
            # back at, so the relaunch can assert it resharded to M
            meta["resize_to"] = chaos_engine.resize_target
        with spans.span("checkpoint"):
            path = save_checkpoint(state, meta=meta)
            if async_saver is not None:
                # the exit is imminent: the checkpoint must be durable NOW
                async_saver.wait()
        recorder.record("preempt", step=host_step, signal=sig)
        logger.log(
            kind="preempt", step=host_step, signal=sig, epoch=ep,
            batch_in_epoch=nb, checkpoint=str(path),
        )
        if metric_reg is not None:
            metric_reg.inc("train_preempts")
        if heart is not None:
            heart.beat(host_step, timeline=timeline)
        drain_side_events()
        publish_metrics()  # last snapshot before the exit below
        if p0:
            print(f"preempted by {sig} at step {host_step}; checkpoint {path}")
        logger.close()
        raise Preempted(
            f"{sig} at step {host_step}; checkpoint {path}; relaunch with "
            f"--resume latest to continue",
            checkpoint=path, step=host_step,
        )

    def abort_with(exc_cls, message: str):
        """The round-8 bundle-dump-and-abort tail shared by --spike_action
        abort and rollback-budget exhaustion: preserve the blown-up state
        for autopsy, then fail loudly with the documented exit code."""
        nonlocal checkpoint_path
        with spans.span("checkpoint"):
            checkpoint_path = save_checkpoint(state) or checkpoint_path
            if async_saver is not None:
                # abort must leave a DURABLE autopsy
                async_saver.wait()
        drain_side_events()
        publish_metrics()  # the autopsy snapshot: counters up to the abort
        # (the raise unwinds through _cleanup, which closes this epoch's
        # prefetcher and bar)
        logger.close()
        raise exc_cls(f"{message}; state checkpointed at {checkpoint_path}")

    # Jitted identity at the strategy's shardings, used by execute_rollback
    # below. Hoisted so every rollback reuses one traced/compiled program
    # (jit's cache is keyed on function identity — a fresh lambda per call
    # would re-trace inside the quiesce window).
    _relaunder = jax.jit(lambda s: s, out_shardings=state_sharding)

    def execute_rollback(plan) -> None:
        """Restore the plan's checkpoint in process and reset every piece
        of host state that belongs to the abandoned timeline segment. The
        input stream is NOT rewound: the loader/prefetcher keeps streaming
        forward, so the batch window that fired the anomaly is never
        replayed."""
        nonlocal state, host_step, running, win_n, norms, last_checksum
        nonlocal timeline, skip_save_step
        if watchdog is not None:
            watchdog.disarm()  # restore I/O may exceed the step deadline
        if async_saver is not None:
            # An in-flight async save of an abandoned-timeline step must
            # publish BEFORE the quarantine sweep, or it would land after
            # it — resurrecting a possibly-poisoned checkpoint that a later
            # rollback/resume could restore.
            async_saver.wait()
        quarantined = recovery.quarantine(plan, process_zero=p0)
        # Quiesce the prefetch worker across the restore: its batch
        # device_puts racing the restore's state placement corrupts the
        # CPU runtime (prefetch.HostPrefetcher.quiesce). Buffered batches
        # keep serving — production pauses, the stream position holds.
        pf = pf_live["pf"]
        pf_quiet = pf.quiesce() if pf is not None else contextlib.nullcontext()
        with pf_quiet, spans.span("checkpoint"):
            restored, was_sharded = ckpt_lib.restore_any(
                plan.target_path, state_shapes, state_sharding
            )
            state = (
                restored if was_sharded else _place_like(restored, state_sharding)
            )
            # Launder the restored pytree through a jitted identity: the
            # next train_step dispatch then sees ordinary jit-output
            # arrays and takes the fast path, instead of re-placing
            # host-restored arrays inside the dispatch — host-side work
            # that would land OUTSIDE this quiesce and race the prefetch
            # worker's device_put (same corruption the quiesce exists
            # for). Compiled once, cached across rollbacks.
            state = _relaunder(state)
            jax.block_until_ready(jax.tree_util.tree_leaves(state))
        host_step = plan.target_step
        skip_save_step = host_step  # the target step's checkpoint exists
        running, win_n, norms = None, 0, None
        if sentinel is not None:
            # post-restore losses revisit an OLDER point of the curve; the
            # pre-anomaly baseline would re-fire on a healthy recovery
            sentinel.reset()
        pending_checks.clear()
        last_checksum = None
        timeline += 1  # heartbeat checksums from before the rollback are
        # now a different timeline: equal step numbers, different data
        recovery.committed(plan)
        recovery.coordinator.ack(plan.seq, plan.target_step)
        rec = plan.record()
        logger.log(kind="rollback", timeline=timeline, quarantined=quarantined, **rec)
        recorder.record("rollback", **rec)
        if metric_reg is not None:
            metric_reg.inc("train_rollbacks")
        if heart is not None:
            heart.beat(host_step, timeline=timeline)
        if p0:
            print(
                f"rollback {plan.seq}/{recovery.max_rollbacks} "
                f"({plan.reason} at step {plan.anomaly_step}): restored "
                f"{plan.target_path} at step {plan.target_step}, "
                f"{plan.steps_lost} steps lost; input stream continues "
                f"forward"
            )

    def try_rollback(reason: str, anomaly_step: int) -> bool:
        """Immediate collective rollback — for anomalies EVERY process
        observes in lockstep (the sentinel's window loss is replicated).
        Each process computes the same plan from the shared checkpoint
        directory; process 0 publishes the decision record and the others
        confirm theirs against it before restoring. False = escalate."""
        if recovery is None:
            return False
        plan = recovery.plan(reason, anomaly_step, window=PRINT_FREQ)
        if plan is None:
            return False
        if jax.process_index() == 0:
            recovery.coordinator.publish(plan)
        else:
            recovery.coordinator.confirm(plan)
        execute_rollback(plan)
        return True

    pending_deferred: dict[int, Any] = {}  # p0's not-yet-executed decisions

    def defer_rollback(reason: str, anomaly_step: int) -> bool:
        """Deferred collective rollback — for anomalies only process 0
        observes (divergence). The decision file is published one window
        AHEAD of execution so every process discovers it on the shared
        heartbeat directory and executes at the same boundary."""
        seq = recovery.count + 1
        if seq in pending_deferred or recovery.coordinator.read(seq) is not None:
            # A decision for this anomaly is already in flight (a persistent
            # divergence re-fires at every boundary until the rollback
            # executes). Re-publishing would push execute_after back each
            # window — postponing the rollback forever — and a rank that
            # already read the old record would execute at the old boundary
            # while p0 waits for the new one: split-brain.
            return True
        plan = recovery.plan(reason, anomaly_step, window=PRINT_FREQ)
        if plan is None:
            return False
        recovery.coordinator.publish(
            plan, execute_after=anomaly_step + PRINT_FREQ
        )
        pending_deferred[plan.seq] = plan
        return True

    def poll_rollback(final: bool = False) -> None:
        """Window-boundary poll (every process, multi-process worlds):
        execute a published deferred decision once its execute_after step
        is reached. `final=True` (end of the last epoch's training phase)
        executes a still-pending decision regardless of its boundary — a
        decision published during the LAST window has no later boundary,
        and dropping it would eval, save, and exit 0 on the diverged
        state. Every rank reaches the final drain at the same host_step,
        so the restore's (or abort's) collectives still match. The drain
        itself is a rendezvous: process 0 publishes a final-drain marker
        AFTER anything it will ever publish is on disk, and other ranks
        wait (bounded) for it before trusting a None read — p0 detects
        divergence inside its last boundary block (heartbeat reads +
        hashing, slow), so a faster rank's lone read could land before
        the publish and sail into eval on the diverged state."""
        if recovery is None or jax.process_count() == 1:
            return
        seq = recovery.count + 1
        plan = pending_deferred.pop(seq, None)
        if plan is not None:  # process 0's own deferred decision
            if final or host_step >= plan.anomaly_step + PRINT_FREQ:
                if final:
                    # marker before the (long) restore: other ranks can
                    # read the already-published decision and restore
                    # concurrently instead of waiting out p0's I/O
                    recovery.coordinator.publish_final_drain(host_step)
                execute_rollback(plan)
            else:
                pending_deferred[seq] = plan
            return
        if final:
            if p0:
                recovery.coordinator.publish_final_drain(host_step)
            else:
                recovery.coordinator.wait_final_drain()
        rec = recovery.coordinator.read(seq)
        if rec is None or "execute_after" not in rec:
            return  # nothing pending (immediate decisions ran via confirm)
        if not final and host_step < int(rec["execute_after"]):
            return
        if rec.get("action") == "abort":
            # collective-abort decision (publish_abort): every process —
            # including the p0 that published it — reaches abort_with here
            # at the same boundary, so the autopsy checkpoint's collective
            # completes before the run exits 77
            abort_with(
                RollbackBudgetExhausted,
                f"{rec['reason']} at step {rec['anomaly_step']}: rollback "
                f"budget exhausted ({recovery.count}/"
                f"{recovery.max_rollbacks} used) or no integrity-verified "
                f"checkpoint to restore",
            )
        from tpukit.recovery import RollbackPlan

        execute_rollback(
            RollbackPlan(
                seq=int(rec["seq"]), reason=rec["reason"],
                anomaly_step=int(rec["anomaly_step"]),
                target_step=int(rec["target_step"]),
                target_path=rec["target_path"],
                steps_lost=int(rec["steps_lost"]),
            )
        )

    if heart is not None:
        heart.beat(host_step)  # liveness file exists before the first compile

    maybe_nojit = jax.disable_jit() if flags.disable_compile else contextlib.nullcontext()
    # Debug toolchain (SURVEY §5): abort with a traceback at the first
    # NaN/Inf inside any jitted computation. Scoped to this fit() so debug
    # mode does not leak into later runs in the same process.
    maybe_nans = (
        _debug_nans_scope() if flags.debug_nans else contextlib.nullcontext()
    )
    # First call of each compiled step function pays the jit compile —
    # minutes at pod scale — so the watchdog only arms once the function
    # is warm: --hang_timeout bounds the steady-state step, not the
    # compile.
    warm = {"train": False, "eval": False}

    def _close_obs():
        # runs on ANY exit of the training block (normal, spike abort,
        # debug_nans, KeyboardInterrupt): flush a partial anomaly trace
        # and stop the monitor thread before the final checkpoint I/O
        if tracer is not None and tracer.stop():
            logger.log(kind="anomaly_trace", event="stopped", step=host_step)
        if watchdog is not None:
            watchdog.close()

    # _cleanup: any exception unwinding the loop (debug_nans aborts, device
    # OOM, KeyboardInterrupt) must release the epoch's prefetch worker —
    # close() is idempotent, so registering each epoch's prefetcher is safe.
    # A run resumed AT the end of training (preempted during the final
    # epoch's eval phase → meta epoch == epochs) never enters the epoch
    # loop, so eval_metrics must exist before it.
    eval_metrics = {}
    with contextlib.ExitStack() as _obs_guard, maybe_nojit, maybe_nans, \
            profiler_trace(flags.profile_dir), contextlib.ExitStack() as _cleanup:
        _obs_guard.callback(_close_obs)
        for epoch in range(start_epoch, epochs):
            # ---- train ---------------------------------------------------
            train_loader.set_epoch(epoch)
            # Mid-epoch continuation of a preempted run: drop the batches
            # the interrupted run already trained on, so the resumed epoch
            # consumes exactly the remainder (bit-exact with the
            # uninterrupted run; the per-epoch shuffle is seeded, so the
            # stream is reproducible).
            skip = start_skip if epoch == start_epoch else 0
            # Exact global real-row schedule (VERDICT r4 #6): pure host math
            # (wrap-pad positions don't depend on the shuffle), so the meter
            # is exact on ragged final batches without a per-step cross-host
            # reduction that would re-serialize the async dispatch pipeline.
            # Custom loaders without the method fall back to the
            # per-shard x num_replicas approximation.
            global_rows = (
                train_loader.global_real_row_counts()
                if hasattr(train_loader, "global_real_row_counts")
                else None
            )
            # total=None for reduced-interface custom loaders (make_loaders
            # contract: iterable + set_epoch; __len__ optional)
            bar = tqdm(
                total=len(train_loader) if hasattr(train_loader, "__len__") else None,
                initial=skip,
                disable=not p0,
            )
            bar.set_description(f"[training] Epoch {epoch+1}/{epochs} | loss: ?????")
            # win_n counts the losses actually accumulated this window: a
            # mid-epoch resume (or chaos skip@N) starts i mid-window, so
            # the first boundary may close over fewer than PRINT_FREQ
            # steps — dividing by the nominal width would understate the
            # logged loss and seed the spike sentinel's baseline with it.
            running, win_n = None, 0
            norms = None  # on-device window norms when --log_grad_norms
            # Input source (round 7): with --prefetch N (default 2) a
            # background thread runs the whole host pipeline N batches
            # ahead, so loader wait + prepare + H2D assembly overlap the
            # in-flight compiled step; the measured wait is the residual
            # `prefetch_stall` span. --prefetch 0 is the synchronous
            # reference path, bit-identical losses (tests/test_prefetch.py).
            # One prefetcher per epoch: set_epoch has already run, and the
            # epoch boundary flushes instead of buffering across epochs.
            pf = (
                HostPrefetcher(
                    train_loader, host_pipeline, depth=flags.prefetch,
                    skip=skip,
                )
                if flags.prefetch > 0
                else None
            )
            pf_live["pf"] = pf  # bundle probe sees this epoch's prefetcher
            if pf is not None:
                _cleanup.callback(pf.close)
            _cleanup.callback(bar.close)
            if pf is None:
                it = iter(train_loader)
                for _ in range(skip):  # sync path's resume fast-forward
                    next(it, None)
            else:
                it = None
            i = skip - 1
            while True:
                # Preemption poll: SIGTERM/SIGINT landed since the last
                # iteration → graceful checkpoint-and-exit (code 75) at a
                # boundary where device state is coherent.
                check_preempt(i + 1, epoch)
                # The watchdog deadline covers the WHOLE iteration — input
                # wait, dispatch, window sync, periodic checkpoint — so a
                # hang in any of them trips it; re-arming each iteration
                # resets the clock.
                if watchdog is not None and warm["train"]:
                    watchdog.arm(host_step + 1)
                if tracer is not None and tracer.maybe_start():
                    logger.log(
                        kind="anomaly_trace", event="started",
                        step=host_step + 1, reason=tracer.reason,
                        dir=tracer.trace_dir,
                    )
                if pf is not None:
                    with spans.span("prefetch_stall"):
                        try:
                            raw, batch, targets = next(pf)
                        except StopIteration:
                            break
                    i += 1
                else:
                    # Explicit iterator so the loader wait is a measured
                    # span — a data-bound run shows up as a "data" slice of
                    # the window instead of silently deflating tokens/sec.
                    with spans.span("data"):
                        try:
                            raw = next(it)
                        except StopIteration:
                            break
                        i += 1
                        batch, targets = prepare_batch(raw, tokenizer.pad_token_id)
                        if host_batch is not None:
                            batch, targets = host_batch(batch, targets)
                    with spans.span("h2d"):
                        batch, targets = make_global_batch(batch_sh, batch, targets)
                bar.update(1)
                capture_xla("train_step", state_shapes, batch, targets)
                with spans.span("step"):
                    if flags.log_grad_norms:
                        state, loss, norms = train_step(state, batch, targets)
                    else:
                        state, loss = train_step(state, batch, targets)
                warm["train"] = True
                host_step += 1
                recorder.record("step", step=host_step, epoch=epoch)
                if chaos_engine is not None:
                    # deterministic fault injection at exactly this step:
                    # poisoned losses enter the window average below, a
                    # flipped bit enters the next divergence checksum, an
                    # injected signal is polled right here. A bitflip
                    # device_puts into the state on THIS thread, so it
                    # takes the same prefetcher quiesce the rollback
                    # restore does (two threads must never place at once).
                    _pf = pf_live["pf"]
                    _quiet = (
                        _pf.quiesce()
                        if _pf is not None
                        and chaos_engine.mutates_state_at(host_step)
                        else contextlib.nullcontext()
                    )
                    with _quiet:
                        state, loss, _fired = chaos_engine.on_step(
                            host_step, state, loss
                        )
                    if _fired:
                        check_preempt(i + 1, epoch)
                if tracer is not None and tracer.tracing and tracer.step():
                    logger.log(
                        kind="anomaly_trace", event="stopped", step=host_step
                    )
                if (
                    checksum_fn is not None
                    and host_step % flags.divergence_check_freq == 0
                ):
                    with spans.span("telemetry"):
                        pending_checks.append((host_step, checksum_fn(state)))
                running = loss if running is None else running + loss
                win_n += 1
                # Honest throughput (VERDICT r2 #8): count only original
                # dataset rows — wrap-padding duplicates train but are not
                # new tokens; the precomputed global schedule makes the
                # count exact on ragged multi-host batches (VERDICT r4 #6).
                real_rows = raw.get("real_rows") if isinstance(raw, dict) else None
                if global_rows is not None:
                    meter.update(int(global_rows[i]) * targets.shape[1])
                elif real_rows is None:
                    meter.update(targets.size)  # custom loader: no row info
                else:
                    meter.update(real_rows * loader_procs * targets.shape[1])
                if i > 0 and not i % PRINT_FREQ:
                    # Rollbacks executed inside this boundary block (an
                    # immediate divergence rollback above, or a deferred
                    # decision in poll_rollback) reset the sentinel and
                    # rewind host_step — `avg` then belongs to the
                    # abandoned timeline and must not seed the cleared
                    # history (a poisoned avg would even re-fire the NaN
                    # sentinel and burn a second budget slot).
                    pre_rollbacks = recovery.count if recovery is not None else 0
                    with spans.span("sync"):
                        avg = float(running) / win_n  # one D2H sync per window
                        norm_vals = (
                            {k: float(v) for k, v in norms.items()}
                            if norms is not None
                            else {}
                        )
                    win = spans.window()
                    bar.set_description(
                        f"[training] Epoch {epoch+1}/{epochs} | loss: {avg:.3f}"
                    )
                    record = dict(
                        kind="train", epoch=epoch, step=host_step, loss=avg,
                        tokens_per_sec=meter.tokens_per_sec, mfu=meter.mfu,
                        goodput=win["goodput"], spans=win["fractions"],
                        window_s=win["total_s"], **norm_vals,
                    )
                    hbm = live_memory_stats()
                    if hbm:
                        record["hbm"] = hbm
                    if pf is not None:
                        # buffer gauges: how long this thread actually
                        # blocked on input (the honest residual of the old
                        # data+h2d cost after overlap) and how full the
                        # prefetch buffer ran (0 = starved, depth = ahead)
                        pstats = pf.window_stats()
                        record["prefetch_stall_s"] = round(
                            win["seconds"].get("prefetch_stall", 0.0), 6
                        )
                        record["prefetch_occupancy"] = round(
                            pstats["occupancy"], 3
                        )
                    logger.log(**record)
                    recorder.record(
                        "window", step=host_step, epoch=epoch, loss=avg,
                        goodput=win["goodput"],
                        window_s=round(win["total_s"], 6),
                    )
                    if metric_reg is not None:
                        # Goodput-component walls: the window's per-span
                        # seconds the timeline already measured, one
                        # histogram per phase (step/data/h2d/sync/...).
                        for _ph, _secs in win["seconds"].items():
                            if _secs > 0:
                                metric_reg.observe(
                                    "train_span_s", _secs, phase=_ph
                                )
                        metric_reg.observe("train_window_s", win["total_s"])
                        metric_reg.gauge("train_goodput", win["goodput"])
                        metric_reg.gauge(
                            "train_tokens_per_sec", meter.tokens_per_sec
                        )
                        if meter.mfu:
                            metric_reg.gauge("train_mfu", meter.mfu)
                        metric_reg.inc("train_windows")
                        publish_metrics()
                    if (
                        watchdog is not None
                        and len(watchdog.hang_events) > hangs_logged
                    ):
                        # the monitor thread already dumped the bundle(s);
                        # surface the event in the JSONL from this thread
                        # and trace the recovery steps. hang_events pairs
                        # each overrun with ITS bundle (None if the dump
                        # budget was spent), so the record never points at
                        # an unrelated sentinel bundle.
                        new_events = watchdog.hang_events[hangs_logged:]
                        hangs_logged = len(watchdog.hang_events)
                        logger.log(
                            kind="watchdog", event="hang", step=host_step,
                            hangs=len(watchdog.hang_events),
                            bundles=[
                                e["bundle"] for e in new_events if e["bundle"]
                            ],
                        )
                        note_anomaly("hang", host_step)
                    running, win_n = None, 0
                    drain_side_events()
                    if pending_checks:
                        with spans.span("telemetry"):
                            flush_checks()
                    if heart is not None:
                        heart.beat(
                            host_step,
                            checksum=last_checksum[1] if last_checksum else None,
                            checksum_step=(
                                last_checksum[0] if last_checksum else None
                            ),
                            timeline=timeline,
                        )
                        if p0:
                            # step_lag = one window: SPMD lockstep keeps
                            # healthy processes equal, so a process a full
                            # window behind (e.g. restarted onto an old
                            # checkpoint) is worth naming
                            stragglers = heart.check(step_lag=PRINT_FREQ)
                            if stragglers:
                                logger.log(
                                    kind="straggler", step=host_step,
                                    stragglers=stragglers,
                                )
                                recorder.record(
                                    "straggler", step=host_step,
                                    stragglers=stragglers,
                                )
                                print(f"heartbeat: straggling processes {stragglers}")
                                note_anomaly("straggler", host_step)
                                dump_bundle(
                                    "straggler", host_step,
                                    stragglers=stragglers,
                                )
                            if checksum_fn is not None:
                                # beats republish their latest checksum
                                # every window; report each mismatching
                                # (process, step, checksum) ONCE
                                diverged = [
                                    m for m in heart.check_divergence()
                                    if (
                                        m["process"], m["checksum_step"],
                                        m["checksum"],
                                    ) not in reported_divergence
                                ]
                                if diverged:
                                    reported_divergence.update(
                                        (
                                            m["process"], m["checksum_step"],
                                            m["checksum"],
                                        )
                                        for m in diverged
                                    )
                                    logger.log(
                                        kind="divergence", step=host_step,
                                        mismatches=diverged,
                                    )
                                    recorder.record(
                                        "divergence", step=host_step,
                                        mismatches=diverged,
                                    )
                                    print(
                                        "divergence: replica checksum "
                                        f"mismatch {diverged}"
                                    )
                                    note_anomaly("divergence", host_step)
                                    dump_bundle(
                                        "divergence", host_step,
                                        mismatches=diverged,
                                    )
                                    if recovery is not None:
                                        # divergence is a p0-only
                                        # observation: single-process
                                        # rolls back right here;
                                        # multi-process publishes the
                                        # decision one window ahead and
                                        # poll_rollback executes it on
                                        # every process
                                        did = (
                                            try_rollback
                                            if jax.process_count() == 1
                                            else defer_rollback
                                        )("divergence", host_step)
                                        if not did:
                                            if jax.process_count() == 1:
                                                abort_with(
                                                    RollbackBudgetExhausted,
                                                    f"divergence at step "
                                                    f"{host_step}: rollback "
                                                    f"budget exhausted "
                                                    f"({recovery.count}/"
                                                    f"{recovery.max_rollbacks} "
                                                    f"used) or no integrity-"
                                                    f"verified checkpoint to "
                                                    f"restore",
                                                )
                                            else:
                                                # A lone-p0 abort_with would
                                                # strand the other ranks in
                                                # the autopsy checkpoint's
                                                # collective: publish the
                                                # abort one window ahead and
                                                # every process (p0 too)
                                                # executes it in
                                                # poll_rollback.
                                                recovery.coordinator.publish_abort(
                                                    recovery.count + 1,
                                                    "divergence", host_step,
                                                    execute_after=(
                                                        host_step + PRINT_FREQ
                                                    ),
                                                )
                    poll_rollback()
                    if sentinel is not None and (
                        recovery is None or recovery.count == pre_rollbacks
                    ):
                        event = sentinel.observe(avg, host_step)
                        if event is not None:
                            spike_events += 1
                            logger.log(
                                kind="spike", action=flags.spike_action,
                                **event.record(),
                            )
                            recorder.record(
                                "spike", step=event.step, event=event.kind,
                                action=flags.spike_action,
                            )
                            note_anomaly(event.kind, host_step)
                            dump_bundle(event.kind, host_step)
                            if p0:
                                print(
                                    f"loss sentinel: {event.kind} at step "
                                    f"{event.step} (loss {event.loss:.4g})"
                                )
                            if recovery is not None:
                                # Collective-consistent recovery: every
                                # process observed the same replicated
                                # window loss, so all reach this rollback
                                # in lockstep (process 0 publishes the
                                # decision record, the rest confirm).
                                # Budget exhausted (or nothing restorable)
                                # escalates to the round-8 bundle-dump-
                                # and-abort path with exit code 77.
                                if not try_rollback(event.kind, host_step):
                                    abort_with(
                                        RollbackBudgetExhausted,
                                        f"loss sentinel {event.kind} at "
                                        f"step {event.step} (loss "
                                        f"{event.loss:.6g}): rollback "
                                        f"budget exhausted "
                                        f"({recovery.count}/"
                                        f"{recovery.max_rollbacks} used) "
                                        f"or no integrity-verified "
                                        f"checkpoint to restore",
                                    )
                            elif flags.spike_action == "abort":
                                # Preserve the blown-up state for autopsy,
                                # then fail loudly (exit code 76).
                                # Collective-consistent: every process
                                # observed the same replicated loss and
                                # takes this branch together.
                                abort_with(
                                    AnomalyAbort,
                                    f"loss sentinel aborted training: "
                                    f"{event.kind} at step {event.step} "
                                    f"(loss {event.loss:.6g})",
                                )
                if (
                    flags.checkpoint_every
                    and host_step % flags.checkpoint_every == 0
                    # right after a rollback the restored step's checkpoint
                    # is exactly what is already on disk — re-saving it
                    # would only trip the sharded same-step-re-save warning
                    and host_step != skip_save_step
                ):
                    if watchdog is not None:
                        # checkpoint I/O (sync writer: encode + disk) may
                        # legitimately exceed the step deadline; the next
                        # iteration re-arms
                        watchdog.disarm()
                    # Async: only the snapshot is charged here; the encode +
                    # disk write overlaps the following steps.
                    with spans.span("checkpoint"):
                        checkpoint_path = (
                            save_checkpoint(state) or checkpoint_path
                        )
                    recorder.record("checkpoint", step=host_step)
                    prune_checkpoints()
            # Close THIS epoch's prefetcher + bar now (pop_all keeps the
            # fit-lifetime stack from accumulating dead objects across
            # epochs; the stack still covers exceptional unwinds above).
            _cleanup.pop_all().close()
            pf_live["pf"] = None
            if watchdog is not None:
                watchdog.disarm()
            if epoch == epochs - 1:
                # A deferred decision (divergence rollback or collective
                # abort) published during the run's LAST training window
                # names a boundary no training poll will ever reach. Drain
                # it here — before eval and the final save — or the run
                # would evaluate, checkpoint, and exit 0 on the diverged
                # state. (Earlier epochs need no drain: host_step keeps
                # advancing, so the next epoch's boundary polls reach it.)
                poll_rollback(final=True)

            # ---- validation ---------------------------------------------
            bar = tqdm(validation_loader, disable=not p0)
            bar.set_description(
                f"[validation] Epoch {epoch+1}/{epochs} | loss: ?????, accuracy: ?????"
            )
            total_loss, total_acc, total_weight = 0.0, 0.0, 0.0
            eval_metrics = {"loss": float("nan"), "accuracy": float("nan")}
            for i, raw in enumerate(bar):
                # the epoch's training phase is complete: a preemption here
                # checkpoints end-of-epoch state and resumes at epoch+1
                check_preempt(None, epoch)
                # eval steps hang in the same collectives train steps do;
                # same deadline, same first-call compile exemption
                if watchdog is not None and warm["eval"]:
                    watchdog.arm(host_step)
                with spans.span("eval"):
                    batch, targets = prepare_batch(raw, tokenizer.pad_token_id)
                    if host_batch is not None:
                        batch, targets = host_batch(batch, targets)
                    batch, targets = make_global_batch(batch_sh, batch, targets)
                    capture_xla("eval_step", state_shapes, batch, targets)
                    # Token-weighted epoch aggregate (VERDICT r3 #9): each
                    # batch's mean loss/accuracy weighs by its valid-token
                    # count, so a padded final batch no longer weighs like a
                    # full one (the reference's mean-of-batch-means,
                    # main-single.py:128-137, is exact only when batches
                    # divide evenly). Counted on the GLOBAL targets (a jitted
                    # reduction over the sharded array), so every process
                    # aggregates with the same weights — a host-local count
                    # would make ranks disagree about the epoch metric
                    # (caught by tests/test_multiprocess.py).
                    weight = float(_valid_count(targets))
                    loss, acc = eval_step(state, batch, targets)
                    warm["eval"] = True
                    if weight > 0.0:
                        total_loss += float(loss) * weight
                        total_acc += float(acc) * weight
                        total_weight += weight
                    if total_weight > 0.0:
                        eval_metrics = {
                            "loss": total_loss / total_weight,
                            "accuracy": total_acc / total_weight,
                        }
                bar.set_description(
                    f"[validation] Epoch {epoch+1}/{epochs} | "
                    f"loss: {eval_metrics['loss']:.3f}, accuracy: {eval_metrics['accuracy']:.2f}"
                )
            logger.log(kind="validation", epoch=epoch, **eval_metrics)
            recorder.record("validation", epoch=epoch, **eval_metrics)
            if watchdog is not None:
                # generation + epoch-end checkpointing have their own (much
                # longer) natural durations; the next epoch's loop re-arms
                watchdog.disarm()

            # ---- qualitative eval (all processes compute — the replication
            # inside generate_samples is collective — process 0 prints) ----
            # clamp the decode budget so tiny --sequence_length debug
            # runs still fit a prompt in the position table
            gen_tokens = min(20, cfg.max_position_embeddings - 2)
            check_preempt(None, epoch)
            with spans.span("generate"):
                texts = generate_samples(
                    strategy, state, cfg, tokenizer, max_new_tokens=gen_tokens
                )
            if p0:
                print("Argmax sampling from model")
                for text in texts:
                    print(text)

            # ---- epoch wall-clock summary (span timeline): where the
            # epoch's host time went, and the goodput fraction (share spent
            # inside/waiting on the compiled steps) ------------------------
            ep = spans.epoch()
            logger.log(
                kind="epoch", epoch=epoch, goodput=ep["goodput"],
                total_s=ep["total_s"], seconds=ep["seconds"],
                fractions=ep["fractions"],
            )
            recorder.record(
                "epoch", epoch=epoch, goodput=ep["goodput"],
                total_s=round(ep["total_s"], 6),
            )
            if pending_checks:
                flush_checks()  # checks taken since the last window
            if heart is not None:
                heart.beat(
                    host_step,
                    checksum=last_checksum[1] if last_checksum else None,
                    checksum_step=last_checksum[0] if last_checksum else None,
                    timeline=timeline,
                )
            if p0:
                print(f"epoch {epoch+1} wallclock: {format_breakdown(ep)}")

    # ---- final checkpoint (twin of main-single.py:146-151; format routed
    # by save_auto so sharded multi-host state never hits the consolidated
    # gather, VERDICT r2 #1) ----------------------------------------------
    checkpoint_path = save_checkpoint(state) or checkpoint_path
    if async_saver is not None:
        # exit barrier: fit() must not return before the last write is
        # durable (the caller may read or delete the checkpoint next)
        async_saver.wait()
    prune_checkpoints()
    # Retries/chaos firings since the last window boundary — the epoch tail
    # (validation/generation loader fetches) and the final save above — must
    # reach the JSONL before the logger closes.
    drain_side_events()
    if metric_reg is not None:
        # Metrics epilogue (round 22): one kind="metrics" summary record —
        # cumulative counters + per-histogram count/sum/p50/p99 — so
        # tools/report.py --compare can diff two runs without replaying
        # every window. The final snapshot publish lands the complete run
        # in --metrics_dir for external scrapers.
        rec_m = dict(kind="metrics", source="train", **metric_reg.summary())
        logger.log(**rec_m)
        recorder.record(
            "metrics", source="train", hists=len(rec_m.get("hists", {})),
        )
        publish_metrics()
    if cache_stats is not None and p0:
        cs = cache_stats.stats()
        logger.log(kind="compile_cache", **cs)
        print(
            f"compile cache {cs['dir']}: "
            f"{cs.get('hits', 0)} hits, "
            f"{cs.get('misses', cs['new_entries'])} misses, "
            f"{cs['entries']} entries (+{cs['new_entries']})"
        )
    logger.close()

    metrics = {
        "eval": eval_metrics,
        "tokens_per_sec": meter.tokens_per_sec,
        "tokens_per_sec_per_chip": meter.tokens_per_sec_per_chip,
        "mfu": meter.mfu,
        # exact global count (VERDICT r4 #6) — multi-process tests assert
        # ranks agree and match the dataset's real row total
        "train_tokens": meter.total_tokens,
        "spike_events": spike_events,
    }
    if p0 and meter.tokens_per_sec:
        print(
            f"throughput: {meter.tokens_per_sec:,.0f} tok/s "
            f"({meter.tokens_per_sec_per_chip:,.0f} tok/s/chip)"
            + (f", MFU {meter.mfu*100:.1f}%" if meter.mfu else "")
        )
    return FitResult(
        state=state, tokenizer=tokenizer, config=cfg,
        checkpoint_path=checkpoint_path, metrics=metrics,
    )
