"""Elastic world resize: reshard a checkpoint saved at world N onto M devices.

The recovery stack (rollback, collective preemption, integrity-verified
checkpoints, the 0/75/76/77 exit-code contract) assumed the world that
comes back after a failure is the world that left. Production fleets
shrink and grow — spot/preemptible capacity is the cheapest route to
scale — so round 13 makes `--resume` elastic:

  - every save's `meta` sidecar records the SAVING world
    (`current_world`: nprocs, device count, strategy name, mesh axes,
    global batch), so a relaunch can detect a topology change instead of
    failing on a shard-count mismatch or silently misloading;
  - `reshard_restore` reads a checkpoint of either format and lands it on
    the CURRENT run's `state_sharding` specs. The sharded path streams
    leaf-block by leaf-block: for each leaf it plans, from the shard
    files' npy HEADERS alone, which saved blocks intersect each target
    device shard, reads only those, and assembles per-device host buffers
    — no host ever materializes the full global state (at most one
    leaf's addressable target blocks at a time). The checkpoint format
    already separates global shape from per-leaf placement (the
    SimpleFSDP-style portability property), so DDP<->FSDP<->EP and
    N<->M device-count changes are all the same operation: re-slice the
    recorded global leaves along the new world's PartitionSpecs. FSDP's
    `min_shard_size` threshold and divisibility rules re-derive at the
    new world automatically — the target specs come from the CURRENT
    strategy, never from the checkpoint;
  - `sweep_stale_world` clears the previous incarnation's coordination
    state (heartbeat beat files, rollback decision/ack files, preemption
    request/decision files) when a resize is detected: step numbers,
    checksums and process indices from the old world must never be
    compared against the new one's (a stale beat from process 7 of an
    8-process world would poison the 4-process world's divergence check
    forever — its file is never overwritten by a process that no longer
    exists).

Resharding moves data, never math: the restored state is bit-identical to
the saved one, leaf for leaf. Loss-trajectory parity after a resize is
therefore the parity of the COMPUTATION at the new world — reduction
order across a different mesh — which the multichip dryrun's resize
family and tests/test_reshard.py pin at the dense tolerance (hold the
global batch constant across the resize: per-shard batch x shards, not
per-shard batch, is what the trajectory depends on).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import numpy as np

from tpukit import checkpoint as ckpt_lib

# ---------------------------------------------------------------------------
# World metadata: what a save records, what a resume compares.
# ---------------------------------------------------------------------------

# Keys that participate in the mismatch decision. `global_batch` is
# deliberately NOT compared: a batch-size change alone reshapes the input
# stream, not the state layout — the plain restore path handles it (with a
# mid-epoch-position warning in fit()).
_COMPARED = ("nprocs", "device_count", "strategy", "mesh_axes")


def current_world(strategy, global_batch: int | None = None) -> dict:
    """The world descriptor a save's meta sidecar records: process count,
    device count, strategy name and mesh axes — everything a relaunch
    needs to decide "same world, plain restore" vs "resized, reshard"."""
    import jax

    mesh = strategy.mesh
    world = {
        "nprocs": int(jax.process_count()),
        "device_count": int(mesh.devices.size),
        "strategy": str(strategy.name),
        "mesh_axes": {
            ax: int(s) for ax, s in zip(mesh.axis_names, mesh.devices.shape)
        },
    }
    if global_batch is not None:
        world["global_batch"] = int(global_batch)
    return world


def saved_world(path) -> dict | None:
    """The world a checkpoint was saved by: the meta sidecar's `world`
    record (round 13+), falling back to the sharded manifest's `nprocs`
    for older sharded checkpoints. None for consolidated checkpoints
    without metadata — those carry no world signal at all (and need none:
    the consolidated format is world-agnostic by construction)."""
    meta = ckpt_lib.read_meta(path)
    if meta and isinstance(meta.get("world"), dict):
        return meta["world"]
    path = Path(path)
    if path.is_dir():
        try:
            manifest = json.loads((path / "manifest.json").read_text())
            return {"nprocs": int(manifest["nprocs"])}
        except (OSError, ValueError, KeyError):
            return None
    return None


def describe_mismatch(saved: dict | None, current: dict) -> str | None:
    """Named detail of how the saving world differs from the current one,
    or None when they match (or when the saved record predates world
    metadata on every compared key — legacy checkpoints never trigger a
    spurious reshard)."""
    if not saved:
        return None
    diffs = []
    for key in _COMPARED:
        if key not in saved:
            continue
        if saved[key] != current.get(key):
            diffs.append(f"{key} {saved[key]} -> {current.get(key)}")
    return "; ".join(diffs) or None


# ---------------------------------------------------------------------------
# Stale-incarnation sweep.
# ---------------------------------------------------------------------------

# Everything the old world published into the shared heartbeat directory.
# The coordinators' own construction sweeps (RollbackCoordinator /
# PreemptCoordinator) only run on multi-process worlds and only clear what
# the NEW world's ranks own — a resize that shrinks the world leaves the
# vanished ranks' files forever, so the resize path sweeps the whole
# namespace once, before any new-world reader is constructed.
_STALE_PATTERNS = (
    "heartbeat-p*.json",
    "rollback-*.json",
    "preempt-request-p*.json",
    "preempt-decision.json",
)


def sweep_stale_world(directory) -> list[str]:
    """Remove the previous incarnation's heartbeat/rollback/preemption
    state from the shared coordination directory. Called (process 0) when
    `--resume` detects a topology change, BEFORE the new world's
    Heartbeat/coordinators are constructed: a stale beat file from a rank
    that no longer exists would otherwise feed the straggler check and the
    divergence comparison with another world's steps and checksums
    forever. Returns the removed names."""
    directory = Path(directory)
    removed = []
    if not directory.is_dir():
        return removed
    for pattern in _STALE_PATTERNS:
        for path in sorted(directory.glob(pattern)):
            try:
                path.unlink()
            except OSError:
                continue  # racing another sweep: a miss costs nothing
            removed.append(path.name)
    return removed


# ---------------------------------------------------------------------------
# The reshard pass.
# ---------------------------------------------------------------------------


def _copy_overlap(dest, dest_start, block, block_start) -> int:
    """Copy the overlap of `block` (sitting at global offset `block_start`)
    into `dest` (a local buffer whose [0...] corner sits at global offset
    `dest_start`). Returns the number of elements copied (0 = disjoint)."""
    if dest.ndim == 0:
        dest[()] = block
        return 1
    src_idx, dst_idx, n = [], [], 1
    for d0, ds, b0, bs in zip(dest_start, dest.shape, block_start, block.shape):
        lo = max(d0, b0)
        hi = min(d0 + ds, b0 + bs)
        if hi <= lo:
            return 0
        src_idx.append(slice(lo - b0, hi - b0))
        dst_idx.append(slice(lo - d0, hi - d0))
        n *= hi - lo
    dest[tuple(dst_idx)] = block[tuple(src_idx)]
    return n


def _overlaps(dest_start, dest_shape, block_start, block_shape) -> bool:
    """Header-only intersection test — decides whether a saved block must
    be READ at all for a given target shard."""
    for d0, ds, b0, bs in zip(dest_start, dest_shape, block_start, block_shape):
        if min(d0 + ds, b0 + bs) <= max(d0, b0):
            return False
    return True


def _index_bounds(idx, shape):
    """Normalize a sharding index (tuple of slices, possibly with None
    bounds for unsharded dims) into (starts, sizes)."""
    starts, sizes = [], []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        starts.append(start)
        sizes.append(stop - start)
    return starts, sizes


def _place_full(full, want, sharding, path_str):
    """Fallback placement for a leaf that needed whole-leaf materialization
    (identity-padded layer-axis adaptation, or no target sharding)."""
    import jax

    from tpukit.mesh import place_host_array

    shape = tuple(full.shape)
    if want != shape:
        adapted = ckpt_lib._adapt_layer_axis(path_str, full, want)
        if adapted is None:
            raise ValueError(
                f"reshard: leaf {path_str} was saved with shape {shape} but "
                f"the target expects {want}. {ckpt_lib._VOCAB_PAD_HINT}"
            )
        full = adapted
    if sharding is None:
        return jax.numpy.asarray(full)
    return place_host_array(full, sharding)


def _reshard_sharded(base: Path, template, sharding_tree, info: dict):
    """Stream a sharded checkpoint onto the target shardings, leaf-block by
    leaf-block. For each leaf, the target sharding's addressable device
    indices are computed, the saved blocks that intersect each target
    shard are identified from npz HEADERS (no data read), and only the
    intersecting blocks are read and copied into per-device host buffers
    — so host memory is bounded by one leaf's addressable target blocks,
    never the global state (the round-9 lazy-reader discipline, extended
    from per-leaf to per-target-shard)."""
    import jax

    manifest, shard_files = ckpt_lib._read_shard_manifest(base)
    flat, treedef = jax.tree_util.tree_flatten(template)
    shardings = ckpt_lib._sharding_leaves(flat, sharding_tree)
    if len(flat) != len(manifest["leaves"]):
        raise ValueError(
            f"template has {len(flat)} leaves, checkpoint has "
            f"{len(manifest['leaves'])} ({base})"
        )
    readers = [ckpt_lib._ShardReader(f) for f in shard_files]
    # One header pass over every shard builds the global block index:
    # leaf -> [(reader, key, starts, shape)]. Duplicate (leaf, starts)
    # keys across shard files are rejected here — a duplicate would be
    # copied twice and its element count could mask a missing block
    # exactly (the same rule verify_checkpoint's geometry check enforces).
    by_leaf: dict[int, list] = {}
    seen_blocks: set[tuple[int, tuple[int, ...]]] = set()
    for reader in readers:
        for key, (bshape, _) in reader.block_headers().items():
            i, starts = ckpt_lib._parse_block_key(key)
            block_id = (i, tuple(starts))
            if block_id in seen_blocks:
                raise ValueError(
                    f"checkpoint {base}: duplicate block {key!r} across "
                    f"shard files — shards from a different world mixed in?"
                )
            seen_blocks.add(block_id)
            by_leaf.setdefault(i, []).append(
                (reader, key, starts, tuple(bshape))
            )

    # Per-LEAF block cache: a saved block can intersect several distinct
    # target shards (every shard, on a grow or a reshard onto a replicated
    # layout), and re-reading it from the zip once per buffer would
    # multiply restore I/O by the target shard count. The cache lives for
    # one leaf's assembly and is dropped with it, so the host-memory bound
    # stays one leaf — and each byte is read exactly once (bench.py's
    # elastic_restore record asserts bytes_read against that invariant).
    block_cache: dict[tuple, np.ndarray] = {}

    def read_block(reader, key):
        cached = block_cache.get((id(reader), key))
        if cached is not None:
            return cached
        block = reader.read(key)
        block_cache[(id(reader), key)] = block
        info["bytes_read"] += int(block.nbytes)
        info["blocks_read"] += 1
        return block

    restored = []
    for i, (leaf, lmeta, sharding) in enumerate(
        zip(flat, manifest["leaves"], shardings)
    ):
        block_cache.clear()  # the cache bounds host memory per LEAF
        shape, dtype = tuple(lmeta["shape"]), np.dtype(lmeta["dtype"])
        want = tuple(getattr(leaf, "shape", shape))
        blocks = by_leaf.get(i, [])
        if want != shape or sharding is None:
            # layer-axis adaptation (identity-padded pipeline stacks) or an
            # untargeted leaf: assemble the whole leaf, then adapt + place —
            # the one case where per-shard streaming cannot apply, because
            # the adaptation is a function of the full layer axis.
            full = np.empty(shape, dtype)
            covered = 0
            for reader, key, starts, bshape in blocks:
                block = read_block(reader, key)
                covered += _copy_overlap(
                    full, [0] * full.ndim, block, starts or []
                )
            _check_covered(covered, shape, base, i, manifest)
            restored.append(
                _place_full(full, want, sharding, manifest["paths"][i])
            )
            continue
        # streaming path: one host buffer per DISTINCT target index (all
        # replicas of a shard share the buffer; device_put copies per
        # device), filled from exactly the saved blocks that intersect it.
        idx_map = sharding.addressable_devices_indices_map(shape)
        buffers: dict[tuple, np.ndarray] = {}
        arrays = []
        for device, idx in idx_map.items():
            starts_d, sizes_d = _index_bounds(idx or (), shape)
            bkey = tuple(zip(starts_d, sizes_d))
            buf = buffers.get(bkey)
            if buf is None:
                buf = np.empty(sizes_d, dtype)
                covered = 0
                for reader, key, bstarts, bshape in blocks:
                    if buf.ndim and not _overlaps(
                        starts_d, sizes_d, bstarts, bshape
                    ):
                        continue
                    block = read_block(reader, key)
                    covered += _copy_overlap(buf, starts_d, block, bstarts)
                _check_covered(covered, tuple(sizes_d), base, i, manifest)
                buffers[bkey] = buf
            arrays.append(jax.device_put(buf, device))
        restored.append(
            jax.make_array_from_single_device_arrays(shape, sharding, arrays)
        )
    for reader in readers:
        reader.close()
    return jax.tree_util.tree_unflatten(treedef, restored)


def _check_covered(covered: int, shape: tuple, base, i: int, manifest) -> None:
    expected = 1
    for d in shape:
        expected *= int(d)
    if covered != expected:
        raise ValueError(
            f"checkpoint {base}: leaf {i} ({manifest['paths'][i]}) assembled "
            f"{covered}/{expected} elements — a shard block is missing or "
            f"overlapping (saved from {manifest['nprocs']} processes; "
            f"verify_checkpoint names the offending shard)"
        )


def _reshard_consolidated(path: Path, template, sharding_tree, info: dict):
    """Consolidated checkpoints are world-agnostic host pytrees already:
    read, shape-validate against the template (restore handles the
    identity-padded layer-axis adaptation), place at the target
    shardings. The blob is one msgpack — the format's memory floor is the
    full state on each restoring host, which is exactly why `save_auto`
    only picks it when the state is host-gatherable in the first place."""
    import jax

    from tpukit.mesh import place_host_array

    restored = ckpt_lib.restore(template, path)
    info["bytes_read"] = int(path.stat().st_size)
    info["blocks_read"] = 1
    if sharding_tree is None:
        return restored
    return jax.tree.map(place_host_array, restored, sharding_tree)


def reshard_restore(path, template, sharding_tree=None):
    """Restore a checkpoint of either format onto the CURRENT world's
    shardings, resharding as needed. Returns `(state, info)` where state's
    leaves are placed at `sharding_tree` (host arrays when None) and info
    records `{format, bytes_read, blocks_read, wall_s}` for the resize
    JSONL record and bench.py's `elastic_restore` probe.

    The target shardings need not match the ones the checkpoint was
    written under in world size, strategy, or both — resharding is pure
    data movement (bit-identical leaves), so a checkpoint written by
    FSDP@N restores into DDP@M exactly."""
    path = Path(path)
    info = {
        "format": "sharded" if path.is_dir() else "consolidated",
        "bytes_read": 0,
        "blocks_read": 0,
    }
    t0 = time.perf_counter()
    if path.is_dir():
        state = _reshard_sharded(path, template, sharding_tree, info)
    else:
        state = _reshard_consolidated(path, template, sharding_tree, info)
    info["wall_s"] = round(time.perf_counter() - t0, 6)
    return state, info
