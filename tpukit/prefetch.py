"""Depth-N background host-pipeline prefetcher (round-7 host overlap).

PR 1's span timeline showed the fit() loop fully synchronous on the host:
every step paid loader wait + `prepare_batch` + `make_global_batch` (H2D
device_put) inline BEFORE dispatching the compiled step, so none of that
host work overlapped the previous step's device compute — it all showed up
as the `data`/`h2d` slices of the goodput breakdown. `HostPrefetcher` moves
the whole host side of the input pipeline onto a background thread that
runs `depth` batches ahead of consumption; the training thread blocks only
when the buffer is empty, and that wait is the new `prefetch_stall` span —
the honest residual input cost after overlap, directly comparable to the
old `data + h2d` share.

Contract (tests/test_prefetch.py):
  - item order and values are EXACTLY the wrapped iterable's — the same
    `process` fn runs on the same raw batches in the same order, just
    earlier, so losses are bit-identical to the synchronous path;
  - a worker exception (in the iterable or in `process`) propagates to the
    consumer at the `next()` where the failed item would have appeared —
    never swallowed, never reordered ahead of already-buffered good items;
  - epoch boundaries flush cleanly: the iterator raises StopIteration after
    the LAST item, buffers nothing across epochs (one prefetcher per
    epoch), and `close()` releases the worker even mid-epoch;
  - depth only changes timing, never the stream (depth-1 == depth-4).

Thread-safety note: the worker calls `jax.device_put` /
`jax.make_array_from_process_local_data` — both are array-construction
APIs with no collective or dispatch-order dependency, safe to run
concurrently with the training thread's step dispatch. Nothing here may
run device COLLECTIVES off the training thread: two threads racing
enqueues onto the same devices can interleave differently across
processes and deadlock a multi-host program.
"""

from __future__ import annotations

import contextlib
import queue
import threading
from typing import Any, Callable, Iterable

_ITEM, _DONE, _ERROR = "item", "done", "error"


class HostPrefetcher:
    """Iterator pulling (and host-processing) up to `depth` batches ahead.

    `iterable` is consumed on a daemon worker thread; each raw element is
    passed through `process` (identity when None) and buffered. Iterate
    like any iterator; call `close()` to release the worker early (safe to
    call more than once, and called automatically at exhaustion/error).
    """

    def __init__(
        self,
        iterable: Iterable,
        process: Callable[[Any], Any] | None = None,
        depth: int = 2,
        name: str = "tpukit-prefetch",
        skip: int = 0,
    ):
        """`skip` drops the first N raw items BEFORE `process` runs (round
        9: the mid-epoch resume fast-forward) — the skipped batches never
        pay host prep or H2D placement, and the drop happens on the worker
        thread, overlapping the restore/compile the training thread is
        busy with."""
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if skip < 0:
            raise ValueError(f"prefetch skip must be >= 0, got {skip}")
        self.depth = depth
        self._skip = skip
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._host_lock = threading.Lock()
        self._exhausted = False
        self._producer_done = False
        # window-resettable occupancy gauge (window_stats): how full the
        # buffer ran. Consumer STALL time is the caller's to measure (the
        # trainer's `prefetch_stall` span wraps next()) — one clock, not two.
        self._occ_sum = 0
        self._occ_n = 0
        self._thread = threading.Thread(
            target=self._worker, args=(iter(iterable), process),
            daemon=True, name=name,
        )
        self._thread.start()

    # -- worker ------------------------------------------------------------

    def _put(self, item) -> bool:
        """Blocking put that stays responsive to `close()`; False = closed."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, it, process):
        _end = object()
        try:
            for _ in range(self._skip):
                if self._stop.is_set():
                    return
                if next(it, _end) is _end:
                    break  # skipping past the end: the stream is just empty
            for raw in it:
                if self._stop.is_set():
                    return
                if process is None:
                    item = raw
                else:
                    # serialized against quiesce(): the host pipeline ends
                    # in device_put, and a training-thread placement (a
                    # rollback's checkpoint restore) racing it can corrupt
                    # the runtime — two threads must never place at once
                    with self._host_lock:
                        item = process(raw)
                if not self._put((_ITEM, item)):
                    return
            self._producer_done = True
            self._put((_DONE, None))
        except BaseException as exc:  # noqa: BLE001 — delivered to consumer
            self._producer_done = True
            self._put((_ERROR, exc))

    # -- consumer ----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        kind, val = self._queue.get()
        if kind is _ITEM:
            # occupancy sampled right after an item take: batches still
            # ready beyond the one just consumed (0 = starved, up to depth
            # = producer ahead). The terminal sentinel is not a batch —
            # exclude it once the producer has finished.
            q = self._queue.qsize()
            if self._producer_done and q > 0:
                q -= 1
            self._occ_sum += q
            self._occ_n += 1
            return val
        self._exhausted = True
        self.close()
        if kind is _ERROR:
            raise val
        raise StopIteration

    @property
    def buffered(self) -> int:
        """Batches currently ready in the buffer (non-resetting gauge —
        the diagnostics-bundle probe; window_stats owns the per-window
        occupancy average)."""
        return self._queue.qsize()

    @contextlib.contextmanager
    def quiesce(self):
        """Hold the worker between host-pipeline items while the body runs
        (an in-flight item completes first — the acquire waits for it).

        Round 9: a rollback restores a checkpoint MID-stream, and its
        training-thread `device_put`s racing the worker's batch placement
        segfault the CPU runtime (observed on jax 0.4.x; resume-time
        restores never raced because they run before the first prefetcher
        exists). Any other training-thread placement concurrent with a
        live prefetcher needs the same bracket. The buffer keeps serving
        already-prepared batches throughout — quiesce pauses production,
        not consumption."""
        with self._host_lock:
            yield

    def window_stats(self) -> dict:
        """Mean buffer occupancy since the last call (the per-window JSONL
        gauge), then reset."""
        out = {
            "occupancy": self._occ_sum / self._occ_n if self._occ_n else 0.0,
        }
        self._occ_sum = 0
        self._occ_n = 0
        return out

    def close(self):
        """Release the worker (idempotent). Drains the buffer so a worker
        blocked on a full queue observes the stop flag and exits; a closed
        prefetcher iterates as exhausted rather than blocking."""
        self._exhausted = True
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
