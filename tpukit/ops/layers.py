"""Primitive neural-net ops shared by the model and the pipeline stages.

These are the TPU-native building blocks for the reference's torch primitives
(`nn.Linear`, `nn.LayerNorm`, `nn.Dropout`, `F.cross_entropy`). Numerics
policy follows torch autocast semantics on which the reference relies
(reference `main-single.py:88-96`): matmuls run in the compute dtype
(bfloat16 by default — TPUs are bf16-native), while LayerNorm, softmax and
the loss run in float32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

IGNORE_INDEX = -100  # twin of torch F.cross_entropy ignore_index (reference main-single.py:96)


def linear(x: jax.Array, params: dict, compute_dtype=None) -> jax.Array:
    """y = x @ kernel + bias. kernel: [in, out]; bias optional."""
    dtype = compute_dtype or x.dtype
    y = jnp.matmul(x.astype(dtype), params["kernel"].astype(dtype))
    if "bias" in params:
        y = y + params["bias"].astype(dtype)
    return y


def layer_norm(x: jax.Array, params: dict, eps: float = 1e-5) -> jax.Array:
    """LayerNorm over the last axis, computed in float32 (autocast-faithful).

    Twin of `nn.LayerNorm(dim)` used at reference models/gpt.py:119,122,217.
    Returns float32; callers cast back to the compute dtype before matmuls.
    """
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    return normed * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)


def dropout(x: jax.Array, rate: float, rng: jax.Array | None, deterministic: bool) -> jax.Array:
    """Inverted dropout, twin of `nn.Dropout` (reference models/gpt.py:31,65).

    The reference recipes never expose a dropout flag and the model default is
    0.0, so in practice this is the identity; it exists for capability parity.
    """
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def _ce_terms(logits: jax.Array, targets: jax.Array):
    valid = targets != IGNORE_INDEX
    safe_targets = jnp.where(valid, targets, 0)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    target_logit = jnp.take_along_axis(
        logits, safe_targets[..., None], axis=-1
    )[..., 0].astype(jnp.float32)
    loss_sum = jnp.sum(jnp.where(valid, lse - target_logit, 0.0))
    count = jnp.sum(valid).astype(jnp.float32)
    return loss_sum, count, lse


@jax.custom_vjp
def cross_entropy_sum(logits: jax.Array, targets: jax.Array):
    """(loss_sum, valid_count) of token cross-entropies with IGNORE_INDEX
    masking, float32 accumulation.

    Shared by every loss path (default strategies, the pipeline's per-stage
    loss, ring-attention CP). Hand-written VJP for TPU memory behavior:

      - forward is `logsumexp - target_logit`, so no `[B, S, V]` float32
        log-softmax tensor materializes (the f32 cast fuses into the
        reductions);
      - backward is `(softmax - onehot) * g` where the onehot is an iota
        comparison — pure elementwise, fused into the consuming matmuls.
        Autodiff of the gather would instead scatter-add into a fresh f32
        `[B, S, V]` buffer, which dominates the step (and OOMs the compile)
        at the GPT-2 vocab for per-chip batches >= 256.
    """
    loss_sum, count, _ = _ce_terms(logits, targets)
    return loss_sum, count


def _ce_fwd(logits, targets):
    loss_sum, count, lse = _ce_terms(logits, targets)
    return (loss_sum, count), (logits, targets, lse)


def _ce_bwd(residuals, g):
    logits, targets, lse = residuals
    g_sum = g[0]  # count depends only on (non-diff) targets
    valid = targets != IGNORE_INDEX
    probs = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    vocab = logits.shape[-1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (vocab,), 0)
        == jnp.where(valid, targets, -1)[..., None]
    )
    scale = jnp.where(valid, g_sum, 0.0)[..., None]
    dlogits = (probs - onehot.astype(jnp.float32)) * scale
    return dlogits.astype(logits.dtype), None


cross_entropy_sum.defvjp(_ce_fwd, _ce_bwd)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean token cross-entropy with IGNORE_INDEX masking.

    Twin of `F.cross_entropy(logits.view(-1, V), targets.view(-1),
    ignore_index=-100)` (reference main-single.py:95-96): the mean is taken
    over non-ignored positions only. See cross_entropy_sum for the TPU
    memory design.
    """
    loss_sum, count = cross_entropy_sum(logits, targets)
    return loss_sum / jnp.maximum(count, 1.0)


# ---------------------------------------------------------------------------
# Vocab-parallel primitives (used by the pipeline's sharded embedding / head,
# tpukit/pipeline.py). No reference counterpart: the reference replicates the
# full embedding table and head on every pipeline stage via torch Pipe's
# module placement (main-pipe.py:75-77 puts them on first/last GPU but the
# optimizer state still rides each stage's module copy); here the vocab
# dimension is sharded over the `stage` mesh axis so no device ever holds a
# full table.
# ---------------------------------------------------------------------------


def _psum_bcast_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _psum_bcast_bwd(axis, _, ct):
    return (jax.lax.psum(ct, axis),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_bcast(x: jax.Array, axis):
    """`lax.psum` whose transpose is also a psum.

    Inside shard_map, JAX transposes psum to an identity per device, which is
    only correct when the cotangent is device-invariant. Here the summed
    value is consumed *divergently* (e.g. only the last pipeline stage's CE
    contribution is nonzero at a given schedule step), so the mathematically
    correct input cotangent is the sum of every device's output cotangent —
    exactly Megatron's paired f/g collectives, written as one custom VJP.
    """
    return jax.lax.psum(x, axis)


psum_bcast.defvjp(_psum_bcast_fwd, _psum_bcast_bwd)


def _vp_terms(local_logits, targets, offset, axis):
    lf = local_logits.astype(jnp.float32)
    v_local = lf.shape[-1]
    valid = targets != IGNORE_INDEX
    rel = jnp.where(valid, targets, 0) - offset
    own = valid & (rel >= 0) & (rel < v_local)
    safe = jnp.where(own, rel, 0)

    gmax = jax.lax.pmax(jnp.max(lf, axis=-1), axis)
    sumexp = jax.lax.psum(jnp.sum(jnp.exp(lf - gmax[..., None]), axis=-1), axis)
    lse = gmax + jnp.log(sumexp)
    target_logit = jax.lax.psum(
        jnp.where(own, jnp.take_along_axis(lf, safe[..., None], axis=-1)[..., 0], 0.0),
        axis,
    )
    loss_sum = jnp.sum(jnp.where(valid, lse - target_logit, 0.0))
    count = jnp.sum(valid).astype(jnp.float32)
    return loss_sum, count, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def vocab_parallel_ce(local_logits: jax.Array, targets: jax.Array, offset, axis):
    """(loss_sum, valid_count) of the global cross-entropy, computed from
    vocab-sharded logits — each device holds `[..., V/axis_size]` columns
    starting at `offset`. Must be called by every device on the axis (the
    reductions are collective). Returns the same (replicated) values on
    every device.

    The backward pass is local: `(softmax - onehot) * g` per vocab slice
    from the saved global logsumexp — no full-vocab tensor and no backward
    collectives (the Megatron vocab-parallel CE), mirroring
    `cross_entropy_sum`'s memory design.
    """
    loss_sum, count, _ = _vp_terms(local_logits, targets, offset, axis)
    return loss_sum, count


def _vp_fwd(local_logits, targets, offset, axis):
    loss_sum, count, lse = _vp_terms(local_logits, targets, offset, axis)
    return (loss_sum, count), (local_logits, targets, offset, lse)


def _vp_bwd(axis, residuals, g):
    local_logits, targets, offset, lse = residuals
    # The CE returns the same replicated loss_sum on every device of `axis`,
    # and callers typically accumulate it on every device and psum — so the
    # cotangent arriving HERE is 1/axis_size of the logical loss cotangent
    # (shard_map transposes psum to a per-device identity). Summing it over
    # the axis recovers the full cotangent regardless of how the caller
    # distributed it; the local gradient formula below then needs no
    # backward collective on the logits themselves.
    g_sum = jax.lax.psum(g[0], axis)  # count depends only on (non-diff) targets
    lf = local_logits.astype(jnp.float32)
    v_local = lf.shape[-1]
    valid = targets != IGNORE_INDEX
    rel = jnp.where(valid, targets, 0) - offset
    own = valid & (rel >= 0) & (rel < v_local)
    probs = jnp.exp(lf - lse[..., None])
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (v_local,), 0)
        == jnp.where(own, rel, -1)[..., None]
    )
    dlocal = (probs - onehot.astype(jnp.float32)) * jnp.where(valid, g_sum, 0.0)[..., None]
    return (
        dlocal.astype(local_logits.dtype),
        np.zeros(targets.shape, jax.dtypes.float0),
        np.zeros(jnp.shape(offset), jax.dtypes.float0),
    )


vocab_parallel_ce.defvjp(_vp_fwd, _vp_bwd)


def masked_accuracy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Accuracy over non-ignored positions, x100.

    Twin of the eval metric at reference main-single.py:128-131:
    `(logits.argmax(-1)[mask] == targets[mask]).float().mean() * 100`.
    """
    valid = targets != IGNORE_INDEX
    preds = jnp.argmax(logits, axis=-1)
    correct = jnp.where(valid, preds == targets, False)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(correct) / denom * 100.0
