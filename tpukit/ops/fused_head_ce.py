"""Fused LM-head + cross-entropy (Pallas TPU kernels).

The unfused path materializes the logits `[B*S, V]` in HBM (bf16: 3.3GB at
the S=2048 bench shape), then streams them twice more through the CE custom
VJP (`ops/layers.py cross_entropy_sum`) — ~13GB of HBM traffic per step at
GPT-2 vocab, and the logits buffer is what OOMs batch 64 at long sequence.
These kernels never materialize logits: the head matmul runs tile-by-tile
([T tokens x Vc vocab] in VMEM, K=dim fills the MXU) with an online
logsumexp/argmax over vocab tiles, and the backward recomputes each tile to
produce `dh` (accumulated in VMEM across vocab tiles) and per-token-tile
`dW` partials (summed by one cheap XLA reduction).

Semantics exactly match `apply_head` + `cross_entropy_sum` +
`masked_accuracy` (reference main-single.py:95-96,128-131 twins): vocab-pad
columns are forced to -1e9 (zero probability, zero gradient), IGNORE_INDEX
targets contribute nothing, and the argmax tie-breaks to the first index.

No reference counterpart: the reference computes full logits and calls
F.cross_entropy (models/gpt.py:229-231, main-single.py:95-96) — viable at
S=256, not at the long-context shapes this framework targets.

On non-TPU backends the kernels run in Pallas interpreter mode (the CPU
test mesh exercises the exact kernel code path).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.experimental.pallas import tpu as pltpu

from tpukit.compat import def_partition as compat_def_partition
from tpukit.ops.layers import IGNORE_INDEX  # one sentinel for every loss path
from tpukit.ops.pallas_attention import _interpret, tpu_compiler_params

NEG_INF = -1e9  # same pad-column clamp as apply_head (model/gpt.py)

# Tile edges, env-sweepable like TPUKIT_FLASH_BLOCK. t=2048/v=2048 measured
# fastest at the S=2048 bench shape on v5e (tools/sweep_long_context.py;
# the sweep is near-flat +-4%, so these are not load-bearing). Values are
# rounded up to the hardware tile multiples (8 sublanes / 128 lanes) so a
# misaligned sweep value cannot die in Mosaic lowering.
_T_BLK = -(-max(8, int(os.environ.get("TPUKIT_CE_T_BLOCK", "2048"))) // 8) * 8
_V_BLK = -(-max(128, int(os.environ.get("TPUKIT_CE_V_BLOCK", "2048"))) // 128) * 128


def _pads(n_tokens: int, v_pad: int) -> tuple[int, int, int, int]:
    t_blk = min(_T_BLK, -(-n_tokens // 8) * 8)
    n_pad = -(-n_tokens // t_blk) * t_blk
    v_blk = _V_BLK if v_pad >= _V_BLK else -(-v_pad // 128) * 128
    v_pad2 = -(-v_pad // v_blk) * v_blk
    return t_blk, n_pad, v_blk, v_pad2


def _tile_cols(vi, v_blk):
    return vi * v_blk + jax.lax.broadcasted_iota(jnp.int32, (1, v_blk), 1)


def _fwd_kernel(tgt_ref, h_ref, w_ref, lse_ref, tgtl_ref, best_ref,
                m_scr, l_scr, tl_scr, bv_scr, bi_scr,
                *, t_blk, v_blk, num_v, vocab_size, with_argmax):
    """Per-token vectors ride as (1, t_blk) ROWS (an [N, 1] f32 column in
    HBM pads its minor dim to 128 lanes — a 128x memory expansion that cost
    1.5GB at the batch-64 bench shape); rows are reshaped to columns in
    VMEM where the math needs them. `with_argmax` is static: training steps
    (no accuracy) compile the online-argmax passes out entirely."""
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        tl_scr[:] = jnp.zeros_like(tl_scr)
        if with_argmax:
            bv_scr[:] = jnp.full_like(bv_scr, -jnp.inf)
            bi_scr[:] = jnp.zeros_like(bi_scr)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = _tile_cols(vi, v_blk)  # (1, Vc) global column ids
    # NB: a closed-form pad-column correction (zero the pad columns of w,
    # skip this where, subtract pad_cnt*exp(-m) from l) was tried and
    # REVERTED: when every real logit is far below 0 the pad columns anchor
    # m at 0 and the real mass cancels below the f32 ulp of the pad mass —
    # lse collapses to -inf for any token with true logsumexp < ~-9.7.
    logits = jnp.where(cols < vocab_size, logits, NEG_INF)

    # online logsumexp over vocab tiles
    m_prev = m_scr[:, :1]
    row_max = jnp.max(logits, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, row_max)  # lint: allow(online-softmax-spelling): online LOGSUMEXP for the CE loss — streams lse + argmax tie-break state, not the owner's (m, l, correction, p) contract
    corr = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, :1] * corr + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # target logit: one-hot select (no in-kernel gather); cols are GLOBAL
    # column ids, so compare against the global target id — at most one
    # tile hits
    tgt_col = jnp.reshape(tgt_ref[...], (t_blk, 1))  # (T, 1)
    hit = cols == tgt_col  # (T, Vc) broadcast compare
    tl_scr[:, :1] += jnp.sum(jnp.where(hit, logits, 0.0), axis=1, keepdims=True)

    if with_argmax:
        # online argmax, first-index tie-break (matches jnp.argmax): within
        # the tile the smallest column achieving the row max; across tiles
        # strict > keeps the earliest tile's winner
        in_tile_idx = jnp.min(
            jnp.where(logits == row_max, cols, vocab_size), axis=1, keepdims=True
        )
        better = row_max > bv_scr[:, :1]
        bv_scr[:, :1] = jnp.where(better, row_max, bv_scr[:, :1])
        bi_scr[:, :1] = jnp.where(better, in_tile_idx, bi_scr[:, :1])

    @pl.when(vi == num_v - 1)
    def _():
        lse_ref[...] = jnp.reshape(m_scr[:, :1] + jnp.log(l_scr[:, :1]), (1, 1, t_blk))
        tgtl_ref[...] = jnp.reshape(tl_scr[:, :1], (1, 1, t_blk))
        if with_argmax:
            best_ref[...] = jnp.reshape(bi_scr[:, :1], (1, 1, t_blk))
        else:  # output must still be defined; the caller discards it
            best_ref[...] = jnp.zeros_like(best_ref)


def _bwd_kernel(tgt_ref, glse_ref, gtgt_ref, lse_ref, h_ref, w_ref, dhp_ref,
                dw_ref, *, t_blk, v_blk, vocab_size):
    """Grid (num_v, num_t), TOKEN axis innermost: consecutive t steps
    revisit the same dw output block, so dw accumulates IN the output
    (Pallas only keeps revisited blocks resident across consecutive grid
    steps) and never needs per-tile partials in HBM — the f32
    [num_t, dim, V_pad] partial buffer the previous (t, v) grid wrote was
    ~1.5x LARGER than the logits tensor this kernel exists to avoid. dh
    needs accumulation over the now-outer v axis instead; its per-v
    partials go to a [num_v, N_pad, dim] output in h's (bf16) dtype —
    v_blk/ (2*t_blk) ~ 8x smaller than the old dw partials — and one XLA
    reduction finishes the sum."""
    vi = pl.program_id(0)

    @pl.when(pl.program_id(1) == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = _tile_cols(vi, v_blk)
    logits = jnp.where(cols < vocab_size, logits, NEG_INF)
    lse_col = jnp.reshape(lse_ref[...], (t_blk, 1))
    p = jnp.exp(logits - lse_col)  # pad cols: exp(-1e9 - lse) == 0.0
    hit = cols == jnp.reshape(tgt_ref[...], (t_blk, 1))  # global vs global
    # d logits = softmax * d(lse) + onehot * d(tgt_logit)  (for the CE loss
    # the two cotangents are equal and opposite, but the rule is general)
    d = (
        p * jnp.reshape(glse_ref[...], (t_blk, 1))
        + hit.astype(jnp.float32) * jnp.reshape(gtgt_ref[...], (t_blk, 1))
    )
    d16 = d.astype(h_ref.dtype)

    dhp_ref[0] = jax.lax.dot_general(
        d16, w_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dhp_ref.dtype)
    dw_ref[...] += jax.lax.dot_general(
        h_ref[...], d16,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _prep(h, w, targets, vocab_size):
    n, dim = h.shape
    v_pad = w.shape[1]
    t_blk, n_pad, v_blk, v_pad2 = _pads(n, v_pad)
    h_p = jnp.pad(h, ((0, n_pad - n), (0, 0)))
    w_p = jnp.pad(w.astype(h.dtype), ((0, 0), (0, v_pad2 - v_pad)))
    tgt_p = jnp.pad(
        targets.astype(jnp.int32), (0, n_pad - n), constant_values=IGNORE_INDEX
    ).reshape(n_pad // t_blk, 1, t_blk)
    return h_p, w_p, tgt_p, t_blk, n_pad, v_blk, v_pad2


def _fused_fwd_arrays(h, w, targets, vocab_size, with_argmax):
    """Returns (lse [N], tgt_logit [N], best [N] int32) — per-token values;
    the caller assembles loss/accuracy (keeping outputs token-sharded means
    GSPMD handles any batch sharding without custom partitioning rules)."""
    n, dim = h.shape
    h_p, w_p, tgt_p, t_blk, n_pad, v_blk, v_pad2 = _prep(h, w, targets, vocab_size)
    num_t, num_v = n_pad // t_blk, v_pad2 // v_blk

    lse, tgtl, best = pl.pallas_call(
        functools.partial(
            _fwd_kernel, t_blk=t_blk, v_blk=v_blk, num_v=num_v,
            vocab_size=vocab_size, with_argmax=with_argmax,
        ),
        grid=(num_t, num_v),
        in_specs=[
            pl.BlockSpec((1, 1, t_blk), lambda t, v: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t_blk, dim), lambda t, v: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dim, v_blk), lambda t, v: (0, v), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, t_blk), lambda t, v: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t_blk), lambda t, v: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t_blk), lambda t, v: (t, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((num_t, 1, t_blk), jnp.float32),
            jax.ShapeDtypeStruct((num_t, 1, t_blk), jnp.float32),
            jax.ShapeDtypeStruct((num_t, 1, t_blk), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((t_blk, 128), jnp.float32)] * 4
        + [pltpu.VMEM((t_blk, 128), jnp.int32)],
        compiler_params=tpu_compiler_params("parallel", "arbitrary"),
        interpret=_interpret(),
    )(tgt_p, h_p, w_p)
    return (
        lse.reshape(-1)[:n],
        tgtl.reshape(-1)[:n],
        best.reshape(-1)[:n],
    )


def _fused_bwd_arrays(h, w, targets, lse, g_lse, g_tgt, vocab_size):
    """Returns (dh [N, dim], dw [dim, V_pad]) for one token shard. dw is
    the LOCAL tokens' contribution — the partitioned wrapper psums it."""
    n, dim = h.shape
    h_p, w_p, tgt_p, t_blk, n_pad, v_blk, v_pad2 = _prep(h, w, targets, vocab_size)
    num_t, num_v = n_pad // t_blk, v_pad2 // v_blk
    lse_p = jnp.pad(lse, (0, n_pad - n)).reshape(num_t, 1, t_blk)
    glse_p = jnp.pad(g_lse.astype(jnp.float32), (0, n_pad - n)).reshape(num_t, 1, t_blk)
    gtgt_p = jnp.pad(g_tgt.astype(jnp.float32), (0, n_pad - n)).reshape(num_t, 1, t_blk)

    dhp, dw = pl.pallas_call(
        functools.partial(
            _bwd_kernel, t_blk=t_blk, v_blk=v_blk, vocab_size=vocab_size,
        ),
        grid=(num_v, num_t),
        in_specs=[
            pl.BlockSpec((1, 1, t_blk), lambda v, t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t_blk), lambda v, t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t_blk), lambda v, t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t_blk), lambda v, t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t_blk, dim), lambda v, t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dim, v_blk), lambda v, t: (0, v), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, t_blk, dim), lambda v, t: (v, t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dim, v_blk), lambda v, t: (0, v), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((num_v, n_pad, dim), h.dtype),
            jax.ShapeDtypeStruct((dim, v_pad2), jnp.float32),
        ],
        compiler_params=tpu_compiler_params("parallel", "arbitrary"),
        interpret=_interpret(),
    )(tgt_p, glse_p, gtgt_p, lse_p, h_p, w_p)

    dh = jnp.sum(dhp.astype(jnp.float32), axis=0)
    return dh[:n].astype(h.dtype), dw[:, : w.shape[1]].astype(w.dtype)


# ---------------------------------------------------------------------------
# GSPMD partitioning (mirrors pallas_attention's treatment): the token axis
# (h/targets dim 0) is freely shardable — each device runs the kernels on its
# local tokens — while dim and vocab must be whole per device (the online
# logsumexp sweeps all vocab tiles and the matmul contracts all of dim). The
# forward's per-token outputs inherit the token sharding; the backward's dw
# is a sum over tokens, so each shard contributes its local partial and the
# lowered body psums over the token mesh axes. Without these rules a real-TPU
# GSPMD trace would treat the tpu_custom_call as unpartitionable and
# all-gather the whole batch onto every device (the CPU tests can't catch
# that: interpreter mode lowers to plain HLO, which partitions fine).
# ---------------------------------------------------------------------------


def _token_axes(sharding):
    """Mesh axes of h's dim-0 sharding (None if unsharded). dim-1 shardings
    are dropped (GSPMD all-gathers them) with a warning, as in
    pallas_attention._batch_head_spec."""
    if sharding is None or not hasattr(sharding, "spec"):
        return None
    spec = list(sharding.spec) + [None] * 2
    if spec[1]:
        import warnings

        warnings.warn(
            f"fused_head_ce: hidden states sharded over the feature dim "
            f"({sharding.spec}); the kernel contracts the full dim per "
            f"device, so GSPMD will all-gather it.",
            stacklevel=2,
        )
    return spec[0]


def _fused_shardings(mesh, tok):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return {
        "h": NamedSharding(mesh, P(tok, None)),
        "w": NamedSharding(mesh, P(None, None)),
        "tok": NamedSharding(mesh, P(tok)),
    }


def _fwd_partition(vocab_size, with_argmax, mesh, arg_infos, result_infos):
    tok = _token_axes(arg_infos[0].sharding)
    sh = _fused_shardings(mesh, tok)
    arg_sh = (sh["h"], sh["w"], sh["tok"])
    out_sh = (sh["tok"],) * 3

    def lower(h, w, t):
        return _fused_fwd_arrays(h, w, t, vocab_size, with_argmax)

    return mesh, lower, out_sh, arg_sh


def _fwd_infer(vocab_size, with_argmax, mesh, arg_infos, result_infos):
    tok = _token_axes(arg_infos[0].sharding)
    return (_fused_shardings(mesh, tok)["tok"],) * 3


_fwd_cp = custom_partitioning(_fused_fwd_arrays, static_argnums=(3, 4))
compat_def_partition(_fwd_cp, 
    partition=_fwd_partition,
    infer_sharding_from_operands=_fwd_infer,
    sharding_rule="n d, d v, n -> n, n, n",
)


def _bwd_partition(vocab_size, mesh, arg_infos, result_infos):
    tok = _token_axes(arg_infos[0].sharding)
    sh = _fused_shardings(mesh, tok)
    arg_sh = (sh["h"], sh["w"], sh["tok"], sh["tok"], sh["tok"], sh["tok"])
    out_sh = (sh["h"], sh["w"])
    axes = (tok,) if isinstance(tok, str) else tuple(tok or ())

    def lower(h, w, t, lse, gl, gt):
        dh, dw = _fused_bwd_arrays(h, w, t, lse, gl, gt, vocab_size)
        if axes:  # token-sharded: dw partials live per shard
            dw = jax.lax.psum(dw, axes)
        return dh, dw

    return mesh, lower, out_sh, arg_sh


def _bwd_infer(vocab_size, mesh, arg_infos, result_infos):
    tok = _token_axes(arg_infos[0].sharding)
    sh = _fused_shardings(mesh, tok)
    return (sh["h"], sh["w"])


_bwd_cp = custom_partitioning(_fused_bwd_arrays, static_argnums=(6,))
compat_def_partition(_bwd_cp, 
    partition=_bwd_partition,
    infer_sharding_from_operands=_bwd_infer,
    sharding_rule="n d, d v, n, n, n, n -> n d, d v",
)


# custom_vjp sits OUTSIDE the partitioned ops (custom_partitioning has no
# autodiff rule — same layering as pallas_attention's _flash wrapper)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_terms(h, w, targets, vocab_size, with_argmax):
    return _fwd_cp(h, w, targets, vocab_size, with_argmax)


def _fused_terms_fwd(h, w, targets, vocab_size, with_argmax):
    lse, tgtl, best = _fwd_cp(h, w, targets, vocab_size, with_argmax)
    return (lse, tgtl, best), (h, w, targets, lse)


def _fused_terms_bwd(vocab_size, with_argmax, residuals, g):
    h, w, targets, lse = residuals
    g_lse, g_tgt = g[0], g[1]  # best (int) has no cotangent
    dh, dw = _bwd_cp(h, w, targets, lse, g_lse, g_tgt, vocab_size)
    return dh, dw, np.zeros(targets.shape, jax.dtypes.float0)


_fused_terms.defvjp(_fused_terms_fwd, _fused_terms_bwd)


def fused_head_ce(h, w, targets, vocab_size, with_accuracy: bool = False):
    """(loss_sum, count, correct) of the LM head + masked CE, computed from
    hidden states `h [N, dim]` and the (vocab-padded) head kernel
    `w [dim, V_pad]` without materializing logits. `targets [N]` uses
    IGNORE_INDEX masking; `correct` is 0 unless with_accuracy.

    Equivalent to `cross_entropy_sum(apply_head-logits, targets)` (+
    masked_accuracy) — equivalence-tested against that path."""
    lse, tgt_logit, best = _fused_terms(h, w, targets, vocab_size, with_accuracy)
    valid = targets != IGNORE_INDEX
    loss_sum = jnp.sum(jnp.where(valid, lse - tgt_logit, 0.0))
    count = jnp.sum(valid).astype(jnp.float32)
    if with_accuracy:
        correct = jnp.sum(jnp.where(valid, best == targets, False)).astype(jnp.float32)
    else:
        correct = jnp.float32(0)
    return loss_sum, count, correct
