"""Mixture-of-experts expert dispatch/combine implementations.

Three interchangeable dataflows sit behind `GPTConfig.moe_dispatch`; all
compute the SAME math (routing, per-row capacity, expert FFN, gated
combine, load-balance aux) so they are loss/grad-parity-equal and the
parity goldens in tests/test_moe.py hold across any of them:

  - "xla" (default): the original global one-hot einsum formulation.
    Dispatch is `[B,S,E,C] x [B,S,D] -> [E,B,C,D]`, combine is the
    transposed einsum. On one device (or pure DP) this is the fastest
    spelling — everything is a batched matmul. Under ExpertParallel it
    is also what GSPMD must partition, and the round-5 multichip dryrun
    showed it CANNOT: the backward of the dispatch einsum
    (`jvp(bsec,bsd->ebcd)/transpose`) makes the SPMD partitioner fall
    back to "[SPMD] Involuntary full rematerialization" — it replicates
    the tensor and re-partitions it, exactly the all-traffic pattern
    expert parallelism exists to avoid (MULTICHIP_r05.json).

  - "a2a": the explicit shard_map formulation for ExpertParallel.
    Inside the per-device block each device packs its LOCAL rows into
    `[E, C_local, D]` capacity buffers (laid out `[E, B_local, C, D]` —
    C_local = B_local*C, the per-row capacity C of the xla path so token
    dropping is identical), exchanges them with a hand-placed
    `lax.all_to_all` over the `expert` mesh axis, runs the local expert
    shard's FFN on `[E_local, ep*B_local, C, D]`, and returns results
    with the mirrored all_to_all. No custom VJP is needed: the
    formulation is symmetric — `lax.all_to_all`'s transpose is the
    inverse all_to_all and the pack/combine einsums transpose to local
    einsums — so the BACKWARD is also exactly one all_to_all pair per
    layer, never a GSPMD replicate-repartition (asserted against the
    optimized HLO in tests/test_moe.py and the multichip dryrun).

  - "pallas" (tpukit/ops/moe_gemm.py, round 11): the fused grouped-expert
    GEMM. Meshless it sorts token rows by assigned expert and runs a
    blocked segment GEMM — no `[E, B, C, D]` capacity buffer, no padding
    FLOPs, dropless unless `cfg.moe_capacity` is explicitly set. Under
    ExpertParallel it composes AFTER the a2a exchange: the same shard_map
    block as "a2a" (same collectives, same byte audit) with the local
    expert FFN routed through the kernel. The exchange block is shared
    code (`_moe_ffn_exchange`, parametrized over the local expert-FFN
    implementation), so the collective schedule — and the closed-form
    byte audit against it — cannot drift between the two.

Collectives are hand-scheduled rather than compiler-inferred — the core
lesson of the collectives literature (PAPERS.md: "The Big Send-off",
GC3). `expected_a2a` is the audit half: the closed-form per-device
all-to-all payload the compiled HLO must show, consumed by fit()'s xla
telemetry record, bench.py's `moe_ep_comm` probe and the dryrun audit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpukit.compat import shard_map
from tpukit.ops import quant_comm


def moe_capacity(cfg, seq_len: int) -> int:
    """Per-row expert capacity. Derived from the STATIC position-table size
    (width invariance: a row's dispatch is identical whatever buffer padding
    surrounds it) scaled by the routed-experts count (top-k generates k*S
    assignments per row — the GShard convention), then clamped to the call
    width: a row position can never reach seq_len, so the clamp is
    output-identical while keeping short decode buffers cheap.

    `cfg.moe_capacity > 0` overrides the factor-derived value (still
    clamped to the call width) for EVERY dispatch impl, so an explicit
    capacity produces the same drop set on "xla", "a2a" and the capacity
    mode of "pallas" — the bit-identical drop-parity contract
    tests/test_moe.py asserts."""
    if cfg.moe_capacity > 0:
        return min(cfg.moe_capacity, seq_len)
    top_k = cfg.router_top_k
    capacity = max(
        1,
        int(
            -(-cfg.max_position_embeddings * top_k * cfg.expert_capacity_factor
              // cfg.num_experts)
        ),
    )
    return min(capacity, seq_len)


def _route_topk(x, router_kernel, cfg):
    """Shared routing front half: f32 router softmax and the top-k choice.
    Row-local math — identical whether `x` is the global batch (xla/pallas
    paths) or one device's shard (a2a path). This is the ONE place the
    discrete choice is computed, so every dispatch impl routes each token
    to bit-identical experts.

    Returns (xc, top_idx, top_vals, probs, assign):
      xc       [B,S,D]  x in the compute dtype
      top_idx  [B,S,K]  int32 chosen expert ids
      top_vals [B,S,K]  f32 raw router probability of each chosen expert
      probs    [B,S,E]  f32 full softmax (aux statistics)
      assign   [B,S,E]  f32 0/1 chosen-expert mask (aux statistics + drops)
    """
    xc = x.astype(cfg.compute_dtype)
    # router math is f32 (softmax stability under bf16 compute)
    logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), router_kernel.astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [B, S, E] f32
    top_vals, top_idx = jax.lax.top_k(probs, cfg.router_top_k)  # [B, S, K]
    # per-(token, expert) assignment; the k chosen experts are distinct,
    # so the one-hot sum stays 0/1-valued
    choice_oh = jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.float32)
    assign = jnp.sum(choice_oh, axis=2)  # [B, S, E]
    return xc, top_idx, top_vals, probs, assign


def _slot_positions(assign):
    """[B,S,E] position of each token in its expert's per-row buffer
    (cumsum along the sequence is causal: later tokens never evict earlier
    ones); -1 where unassigned. The single spelling of the buffer-position
    rule — both the kept mask and the slot one-hot derive from it."""
    return jnp.cumsum(assign, axis=1) * assign - 1.0


def _kept_mask(assign, capacity: int):
    """[B,S,E] 0/1 mask of assignments that SURVIVE the per-row capacity
    (position >= capacity drops). The single spelling of the drop rule —
    the pallas path's capacity mode reuses it verbatim, which is what
    makes its drop set bit-identical to the xla/a2a buffers'."""
    return assign * (_slot_positions(assign) < capacity)


def _route(x, router_kernel, cfg):
    """Routing + the per-row fixed-capacity dispatch one-hot (the buffer
    formulations: "xla" and the a2a exchange).

    Returns (xc, dispatch, gate_map, probs, assign):
      xc       [B,S,D]  x in the compute dtype
      dispatch [B,S,E,C] 0/1 (compute dtype): token (b,s) -> slot c of expert e
      gate_map [B,S,E]  f32 raw router probability of each chosen expert
      probs    [B,S,E]  f32 full softmax (aux statistics)
      assign   [B,S,E]  f32 0/1 chosen-expert mask (aux statistics)
    """
    capacity = moe_capacity(cfg, x.shape[1])
    xc, top_idx, top_vals, probs, assign = _route_topk(x, router_kernel, cfg)
    choice_oh = jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.float32)
    gate_map = jnp.sum(top_vals[..., None] * choice_oh, axis=2)  # [B, S, E]

    kept = _kept_mask(assign, capacity)
    slot = jnp.clip(_slot_positions(assign), 0, capacity - 1).astype(jnp.int32)
    dispatch = (
        kept[..., None] * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
    ).astype(cfg.compute_dtype)  # [B, S, E, C]
    return xc, dispatch, gate_map, probs, assign


def _expert_ffn(experts, expert_in, dtype):
    """The reference FFN (up -> relu -> down -> relu, the double-relu quirk,
    models/gpt.py:33-41) as batched matmuls over an expert-major buffer
    `[E(,_local), b, C, D]`. Works on the full bank or one device's shard."""
    h = jnp.einsum(
        "ebcd,edf->ebcf", expert_in, experts["up"]["kernel"].astype(dtype)
    ) + experts["up"]["bias"].astype(dtype)[:, None, None, :]
    h = jax.nn.relu(h)
    h = jnp.einsum(
        "ebcf,efd->ebcd", h, experts["down"]["kernel"].astype(dtype)
    ) + experts["down"]["bias"].astype(dtype)[:, None, None, :]
    return jax.nn.relu(h)


def _aux_stats(probs, assign, pad_mask, cfg):
    """Switch load-balance statistics as a (numerator, denominator) pair of
    row sums, so the a2a path can psum the pair across row shards and both
    paths finish with `aux = E * num / max(den, 1)`.

    With a pad_mask and cfg.moe_aux_mask_pads (the Switch convention,
    ADVICE r5 #2): statistics over REAL tokens only, per-row normalization
    by the real-token count, all-pad rows dropped from the mean. Otherwise:
    the pre-round-8 any-position average (den = row count)."""
    top_k = cfg.router_top_k
    if pad_mask is not None and cfg.moe_aux_mask_pads:
        real = (~pad_mask).astype(jnp.float32)  # [B, S]
        count = jnp.maximum(jnp.sum(real, axis=1), 1.0)  # [B]
        frac_tokens = (
            jnp.einsum("bse,bs->be", assign, real) / count[:, None] / top_k
        )
        mean_prob = jnp.einsum("bse,bs->be", probs, real) / count[:, None]
        row_real = (jnp.sum(real, axis=1) > 0).astype(jnp.float32)  # [B]
        num = jnp.sum(jnp.sum(frac_tokens * mean_prob, axis=-1) * row_real)
        den = jnp.sum(row_real)
        return num, den
    # any-position average (cfg.moe_aux_mask_pads=False, or call sites
    # without a mask — the cached decode path), kept selectable so
    # pre-masking training curves stay reproducible
    frac_tokens = jnp.mean(assign, axis=1) / top_k  # [B, E]
    mean_prob = jnp.mean(probs, axis=1)  # [B, E]
    num = jnp.sum(jnp.sum(frac_tokens * mean_prob, axis=-1))
    den = jnp.float32(assign.shape[0])
    return num, den


def moe_ffn_xla(layer, cfg, x, pad_mask=None):
    """The einsum formulation: global one-hot dispatch/combine, partitioning
    left to GSPMD. Returns (out [B,S,D], aux scalar). The right spelling on
    one device and under pure data parallelism; see the module docstring for
    why ExpertParallel routes around it."""
    experts = layer["ffn"]["experts"]
    xc, dispatch, gate_map, probs, assign = _route(
        x, layer["ffn"]["router"]["kernel"], cfg
    )
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xc)
    h = _expert_ffn(experts, expert_in, cfg.compute_dtype)
    # combine weighted by each (token, expert)'s gate — for top_k=1 this
    # is the Switch combine exactly (one expert, raw top prob)
    out = jnp.einsum(
        "ebcd,bsec->bsd", h,
        dispatch * gate_map.astype(cfg.compute_dtype)[..., None],
    )
    num, den = _aux_stats(probs, assign, pad_mask, cfg)
    aux = cfg.num_experts * num / jnp.maximum(den, 1.0)
    return out, aux


def moe_ffn_a2a(layer, cfg, x, pad_mask=None):
    """The explicit shard_map formulation for ExpertParallel (see module
    docstring). Requires `cfg.moe_mesh` (the strategy's `(data?, expert)`
    mesh — ExpertParallel.loss_fn injects it alongside moe_dispatch="a2a").

    Per-device block: route local rows -> pack `[E, B_local, C, D]` ->
    all_to_all over `expert` -> local expert shard FFN on
    `[E_local, ep*B_local, C, D]` -> mirrored all_to_all -> gated local
    combine. The aux statistics are local row sums psummed over the row
    axes, so the scalar matches the global formula. Degenerate axes
    (expert mesh size 1) skip the collective but keep the same block, so
    single-group meshes still share one code path."""
    return _moe_ffn_exchange(layer, cfg, x, pad_mask, _expert_ffn, "a2a")


def _moe_ffn_exchange(layer, cfg, x, pad_mask, expert_ffn, name):
    """The shared ExpertParallel exchange block (docstring at moe_ffn_a2a).
    `expert_ffn(experts_l, expert_in, dtype)` computes the local expert
    shard's FFN on the post-exchange `[E_local, ep*B_local, C, D]` buffer:
    the batched einsums for "a2a", the grouped segment GEMM of
    tpukit/ops/moe_gemm.py for "pallas". Everything around it — pack,
    collectives, combine, aux — is ONE copy of code, so the byte audit
    (`expected_a2a`) holds for both by construction."""
    mesh = cfg.moe_mesh
    if mesh is None:
        raise ValueError(
            f"moe_dispatch={name!r} under ExpertParallel needs cfg.moe_mesh "
            f"(a mesh with an 'expert' axis) — ExpertParallel injects it; "
            f"set moe_dispatch='xla' for meshless buffer execution"
        )
    if "expert" not in mesh.axis_names:
        raise ValueError(
            f"moe_dispatch={name!r} needs an 'expert' axis in cfg.moe_mesh, "
            f"got axes {mesh.axis_names}"
        )
    ep = mesh.shape["expert"]
    if cfg.num_experts % ep:
        raise ValueError(
            f"num_experts {cfg.num_experts} must divide over the {ep}-way "
            f"expert mesh axis for {name} dispatch"
        )
    # rows shard over every available mesh axis — ExpertParallel.batch_spec
    row_axes = tuple(a for a in ("data", "expert") if a in mesh.axis_names)
    x_spec = P(row_axes, None, None)
    mask_spec = P(row_axes, None)
    has_mask = pad_mask is not None
    mask_arr = pad_mask if has_mask else jnp.zeros(x.shape[:2], bool)

    def block(x_l, mask_l, router_kernel, experts_l):
        xc, dispatch, gate_map, probs, assign = _route(x_l, router_kernel, cfg)
        # pack local rows into per-expert capacity buffers [E, B_local, C, D]
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, xc)
        if ep > 1:
            # exchange: send the expert-block destined for peer j, receive
            # every peer's block for OUR experts -> [E_local, ep*B_local, C, D].
            # cfg.comm_dtype selects the wire payload (quant_comm round 12):
            # "f32" emits the exact pre-round-12 lax.all_to_all; "int8"
            # moves block-scaled payloads (scale sidecar packed into the
            # same op, custom vjp keeps the backward a mirrored exchange —
            # op schedule unchanged). Routing happened BEFORE the exchange
            # on exact local values, so quantization perturbs expert
            # activations, never the discrete routing decisions.
            expert_in = quant_comm.exchange_all_to_all(
                expert_in, "expert", ep, "dispatch", dtype=cfg.comm_dtype,
                stochastic=cfg.quant_stochastic,
            )
        h = expert_ffn(experts_l, expert_in, cfg.compute_dtype)
        if ep > 1:
            # mirrored return trip -> [E, B_local, C, D] back on the source
            h = quant_comm.exchange_all_to_all(
                h, "expert", ep, "combine", dtype=cfg.comm_dtype,
                stochastic=cfg.quant_stochastic,
            )
        out = jnp.einsum(
            "ebcd,bsec->bsd", h,
            dispatch * gate_map.astype(cfg.compute_dtype)[..., None],
        )
        num, den = _aux_stats(probs, assign, mask_l if has_mask else None, cfg)
        num = jax.lax.psum(num, row_axes)
        den = jax.lax.psum(den, row_axes)
        aux = cfg.num_experts * num / jnp.maximum(den, 1.0)
        return out, aux

    out, aux = shard_map(
        block,
        mesh=mesh,
        in_specs=(x_spec, mask_spec, P(), P("expert")),
        out_specs=(x_spec, P()),
        check_vma=False,
    )(x, mask_arr, layer["ffn"]["router"]["kernel"], layer["ffn"]["experts"])
    return out, aux


def expected_a2a(cfg, data_size: int, expert_size: int, global_batch: int,
                 seq: int, backend: str | None = None) -> dict | None:
    """Closed-form per-device all-to-all payload of the a2a dispatch — what
    the optimized HLO of one step must show (the audit side of
    hand-scheduling the collective). Round 16: reaches the hlolint rule
    engine through `ExpertParallel.dispatch_comm` →
    `analysis.plan.train_comm_plan` (DESIGN.md §15); the `wire` marker
    below doubles as the wire-upcast rule's declared payload dtype.

    Per layer each device moves its `[E, B_local, C, D]` buffer out and the
    results back: 2 all_to_alls forward, and — because the formulation is
    its own transpose — exactly 2 more in the backward (6 with
    cfg.remat_layers: the checkpointed forward re-runs). Counts are HLO *op
    instances*: the scanned layer stack (cfg.scan_layers) emits each op
    once in the scan body regardless of depth, so `layers_visible` is 1
    there. A 1-way expert axis moves nothing (the block skips the
    collective). Returns {"buffer_bytes", "train": {count, bytes, wire},
    "eval": {...}} — eval uses bf16 (the always-on eval autocast) and is
    forward-only.

    Payload dtype (round 12): with cfg.comm_dtype "int8" every exchange op
    moves the PACKED block-scaled buffer (int8 values + bitcast f32 scale
    sidecar, quant_comm.packed_bytes — op counts unchanged); "bf16" casts
    the buffer; "f32" is the raw compute-dtype buffer. `backend` resolves
    the dtype each payload actually travels at: XLA:CPU's float
    normalization upcasts bf16 buffers to f32 on the wire (the round-10
    eval-audit divergence, now priced into the formula instead of excused
    by the renderer), while int8 payloads audit exactly everywhere. Pass
    backend=None for nominal accelerator sizes (the pre-round-12
    behavior)."""
    if cfg.num_experts <= 0:
        return None
    zero = {"count": 0, "bytes": 0}
    if expert_size <= 1:
        return {"buffer_bytes": 0, "train": dict(zero), "eval": dict(zero)}
    capacity = moe_capacity(cfg, seq)
    rows = data_size * expert_size
    if global_batch % rows:
        return None  # undividable batch never reaches the a2a path
    b_local = global_batch // rows
    n_buf = cfg.num_experts * b_local * capacity * cfg.dim  # buffer elems
    layers_visible = 1 if cfg.scan_layers else cfg.num_layers
    train_ops = 6 if cfg.remat_layers else 4
    comm = getattr(cfg, "comm_dtype", "f32")

    def op_bytes(compute_dtype):
        """Result bytes of ONE exchange op, comm/backend-aware."""
        if comm == "int8":
            # ep packed rows, each covering the destination group's elems
            return expert_size * quant_comm.packed_bytes(n_buf // expert_size)
        if comm == "bf16":
            return n_buf * quant_comm.wire_itemsize("bf16", backend)
        name = jnp.dtype(compute_dtype).name
        if name == "bfloat16":
            return n_buf * quant_comm.wire_itemsize("bf16", backend)
        return n_buf * jnp.dtype(compute_dtype).itemsize

    def wire_name(compute_dtype):
        if comm == "int8":
            return "s8-packed"
        if comm == "bf16" or jnp.dtype(compute_dtype).name == "bfloat16":
            return "f32" if backend == "cpu" else "bf16"
        return jnp.dtype(compute_dtype).name

    def entry(compute_dtype, ops_per_layer):
        count = ops_per_layer * layers_visible
        rec = {"count": count, "bytes": count * op_bytes(compute_dtype)}
        if backend is not None:
            # marker: this expectation already prices in the backend's
            # wire dtype — renderers must compare EXACTLY, no CPU excuse
            rec["wire"] = wire_name(compute_dtype)
        return rec

    return {
        "buffer_bytes": n_buf * jnp.dtype(cfg.compute_dtype).itemsize,
        "train": entry(cfg.compute_dtype, train_ops),
        "eval": entry(jnp.bfloat16, 2),
    }
