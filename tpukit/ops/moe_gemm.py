"""Fused grouped-expert matmul (Pallas TPU kernels) — the "pallas" MoE
dispatch.

The buffer dataflows ("xla"/"a2a", tpukit/ops/moe_dispatch.py) materialize
an `[E, B, C, D]` capacity tensor and run EVERY expert over mostly-padding
rows: at the bench e8 shape the dispatch/combine one-hot einsums plus the
~25% capacity padding are why `moe_e8` sat ~100k tok/s/chip under the
dense model (BENCH_r02..r05, ROADMAP #3). This module removes the buffer
entirely:

  1. SORT: the `[B*S*K]` top-k expert assignments are stably argsorted by
     expert id on device, giving a permutation into expert-contiguous
     segments plus per-expert offsets (one `cumsum` of a bincount).
  2. SEGMENT GEMM: a blocked kernel walks the sorted rows `BT` at a time.
     Each row block statically unrolls over the expert bank and executes
     — via `pl.when`, so non-overlapping experts cost nothing at runtime
     — the reference FFN (up -> relu -> down -> relu, the double-relu
     quirk, models/gpt.py:33-41) for exactly the experts whose segment
     intersects the block, switching weight tiles at group boundaries.
     A block away from any boundary runs precisely one expert's two
     matmuls: no capacity padding, no one-hot dispatch FLOPs.
  3. COMBINE: the inverse permutation (an argsort of the sort order)
     gathers results back to `(token, k)` order for the gated top-k sum.
     No scatter in the forward; the gather's transpose is the scatter-add
     the backward needs and XLA emits it as such.

Dropless semantics: every routed token computes (the megablocks/dropless
convention) — `moe_e8` FLOPs become exactly `top_k` expert rows per token.
Setting `cfg.moe_capacity > 0` restores capacity-drop semantics by
zeroing the gates of assignments the buffer paths would drop — the mask
is the SAME `_kept_mask` cumsum the xla path uses, so the dropped token
set is bit-identical (tests/test_moe.py::test_pallas_drop_semantics).

Backward is a custom VJP over the SAME sorted layout (no re-sort, no
GSPMD transpose guesswork): one kernel recomputes each block's hidden
activations flash-style, accumulates dW/db per expert in revisited output
blocks (expert segments are contiguous in the sorted order, so dW
accumulation is consecutive — the Pallas revisit rule), and emits dX via
the mirrored masked walk. `relu` masks come from the saved forward output
(`y > 0  <=>  z > 0`, with relu'(0) = 0 matching jax).

Under ExpertParallel the kernel composes AFTER the hand-placed all_to_all
exchange (`moe_dispatch._moe_ffn_exchange`): each device's post-exchange
`[E_local, ep*B_local, C, D]` buffer is already expert-contiguous — the
sorted layout with static equal segments — so the kernel replaces the
batched expert einsums while the collective schedule and its byte audit
are byte-for-byte the "a2a" path's. (The exchange needs static per-peer
payloads, so capacity buffers — and their drop semantics — are structural
there; the dropless win is the meshless/single-chip path, which is what
the bench `moe_e8` probe measures.)

VMEM budget: the whole expert bank (`[E, D, F]` + `[E, F, D]` + biases)
stays resident in VMEM across the row walk — at the bench e8 shape ~8 MiB
bf16, well under the 100 MiB kernel budget, but it bounds this kernel to
banks that fit on-chip (E ~<= 32 at GPT-small widths). The static expert
unroll likewise targets small expert counts; both limits are asserted at
call time rather than discovered as Mosaic errors.

On non-TPU backends the kernels run in Pallas interpreter mode (the
`pallas_attention.py` convention), so the CPU tier-1 suite exercises the
exact kernel code path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpukit.ops.moe_dispatch import (
    _aux_stats,
    _kept_mask,
    _moe_ffn_exchange,
    _route_topk,
    moe_capacity,
)
from tpukit.ops.pallas_attention import _interpret, tpu_compiler_params

# Sorted-row block edge (sublane-aligned). 512 keeps the per-block hidden
# activation [BT, F] at 2 MiB f32 for the bench shape while amortizing the
# per-block expert-switch overhead; sweepable like TPUKIT_FLASH_BLOCK.
_BLOCK_ROWS = max(8, -(-int(os.environ.get("TPUKIT_MOE_BLOCK", "512")) // 8) * 8)

# The expert bank stays VMEM-resident and the kernel unrolls over it —
# both scale with E. Fail with a named limit instead of a Mosaic OOM.
_MAX_VMEM_EXPERTS = 32


def _plan_rows(n_rows: int) -> tuple[int, int]:
    """(block_rows, padded_rows): sublane-aligned block edge and the row
    count padded to a whole number of blocks."""
    bt = min(_BLOCK_ROWS, -(-n_rows // 8) * 8)
    return bt, -(-n_rows // bt) * bt


# ---------------------------------------------------------------------------
# Kernels. Grid is (num_row_blocks,); the per-expert segment offsets ride in
# SMEM and every block statically unrolls over the expert bank with pl.when
# gating, so only experts whose segment intersects the block execute. Every
# VMEM ref read keeps rank >= 2 (bias rows are sliced `[e:e+1, :]`) — the
# Mosaic layout rule pallas_attention documents.
# ---------------------------------------------------------------------------


def _fwd_kernel(offs_ref, x_ref, wu_ref, bu_ref, wd_ref, bd_ref, y_ref, *,
                block_rows, num_experts):
    b = pl.program_id(0)
    # zero-init: rows of experts that do not reach this block (and the
    # sort-padding tail) must read as exact zeros downstream
    y_ref[...] = jnp.zeros_like(y_ref)
    base = b * block_rows
    rows = base + jax.lax.broadcasted_iota(jnp.int32, (block_rows, 1), 0)
    x_blk = x_ref[...]
    for e in range(num_experts):
        start = offs_ref[e]
        end = offs_ref[e + 1]

        @pl.when((start < base + block_rows) & (end > base))
        def _():
            # the reference FFN for this expert over the WHOLE block (MXU
            # work is per-block; the row mask only gates the write), f32
            # accumulation, intermediates rounded to the compute dtype at
            # the same points as the einsum paths
            h = jax.lax.dot_general(
                x_blk, wu_ref[e],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            h = jnp.maximum(h + bu_ref[e:e + 1, :].astype(jnp.float32), 0.0)
            z = jax.lax.dot_general(
                h.astype(x_blk.dtype), wd_ref[e],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            z = jnp.maximum(z + bd_ref[e:e + 1, :].astype(jnp.float32), 0.0)
            mask = (rows >= start) & (rows < end)
            y_ref[...] += jnp.where(mask, z, 0.0).astype(y_ref.dtype)


def _bwd_kernel(offs_ref, x_ref, g_ref, y_ref, wu_ref, bu_ref, wd_ref,
                dx_ref, dwu_ref, dbu_ref, dwd_ref, dbd_ref, *,
                block_rows, num_experts):
    """The mirrored walk: recompute each block's hidden activations once
    (flash-style — cheaper than saving the [M, F] tensor), mask the
    incoming cotangent to the expert's segment FIRST so every downstream
    product is segment-exact, then accumulate dW/db into the
    expert-indexed output blocks (revisited consecutively: segments are
    contiguous in the sorted order) and dX into the row block. relu masks:
    y > 0 for the down relu (y is the saved forward output), h > 0 for the
    up relu (h is the recomputation)."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        dwu_ref[...] = jnp.zeros_like(dwu_ref)
        dbu_ref[...] = jnp.zeros_like(dbu_ref)
        dwd_ref[...] = jnp.zeros_like(dwd_ref)
        dbd_ref[...] = jnp.zeros_like(dbd_ref)

    dx_ref[...] = jnp.zeros_like(dx_ref)
    base = b * block_rows
    rows = base + jax.lax.broadcasted_iota(jnp.int32, (block_rows, 1), 0)
    x_blk = x_ref[...]
    for e in range(num_experts):
        start = offs_ref[e]
        end = offs_ref[e + 1]

        @pl.when((start < base + block_rows) & (end > base))
        def _():
            mask = (rows >= start) & (rows < end)
            dz2 = jnp.where(
                mask & (y_ref[...] > 0), g_ref[...].astype(jnp.float32), 0.0
            )
            h = jax.lax.dot_general(
                x_blk, wu_ref[e],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            h = jnp.maximum(h + bu_ref[e:e + 1, :].astype(jnp.float32), 0.0)
            h16 = h.astype(x_blk.dtype)
            dz2_16 = dz2.astype(x_blk.dtype)
            dwd_ref[e] += jax.lax.dot_general(
                h16, dz2_16,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dbd_ref[e:e + 1, :] += jnp.sum(dz2, axis=0, keepdims=True)
            dh = jax.lax.dot_general(
                dz2_16, wd_ref[e],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dz1 = jnp.where(h > 0, dh, 0.0)
            dz1_16 = dz1.astype(x_blk.dtype)
            dwu_ref[e] += jax.lax.dot_general(
                x_blk, dz1_16,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dbu_ref[e:e + 1, :] += jnp.sum(dz1, axis=0, keepdims=True)
            dx_ref[...] += jnp.where(
                mask,
                jax.lax.dot_general(
                    dz1_16, wu_ref[e],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ),
                0.0,
            ).astype(dx_ref.dtype)


def _check_bank(num_experts: int) -> None:
    if num_experts > _MAX_VMEM_EXPERTS:
        raise ValueError(
            f"moe_dispatch='pallas' keeps the whole expert bank VMEM-"
            f"resident and unrolls over it: num_experts={num_experts} "
            f"exceeds the supported {_MAX_VMEM_EXPERTS} (shard experts "
            f"over an ExpertParallel mesh, or use the buffer dispatches)"
        )


def _bank_spec(e, d, f):
    """The expert bank rides whole and constant-indexed, so Pallas fetches
    it into VMEM once and keeps it resident across the row walk."""
    return [
        pl.BlockSpec((e, d, f), lambda b: (0, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((e, f), lambda b: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((e, f, d), lambda b: (0, 0, 0), memory_space=pltpu.VMEM),
    ]


def _row_spec(bt, d):
    return pl.BlockSpec((bt, d), lambda b: (b, 0), memory_space=pltpu.VMEM)


def _grouped_ffn_fwd_call(xs, wu, bu, wd, bd, offsets):
    m, d = xs.shape
    e, _, f = wu.shape
    _check_bank(e)
    bt, m_pad = _plan_rows(m)
    assert m_pad == m, "caller pads the sorted rows to a block multiple"
    kernel = functools.partial(_fwd_kernel, block_rows=bt, num_experts=e)
    return pl.pallas_call(
        kernel,
        grid=(m // bt,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [_row_spec(bt, d)]
        + _bank_spec(e, d, f)
        + [pl.BlockSpec((e, d), lambda b: (0, 0), memory_space=pltpu.VMEM)],
        out_specs=_row_spec(bt, d),
        out_shape=jax.ShapeDtypeStruct((m, d), xs.dtype),
        compiler_params=tpu_compiler_params("arbitrary"),
        interpret=_interpret(),
    )(offsets, xs, wu, bu, wd, bd)


def _grouped_ffn_bwd_call(xs, g, ys, wu, bu, wd, offsets):
    m, d = xs.shape
    e, _, f = wu.shape
    bt, _ = _plan_rows(m)
    kernel = functools.partial(_bwd_kernel, block_rows=bt, num_experts=e)
    return pl.pallas_call(
        kernel,
        grid=(m // bt,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [_row_spec(bt, d)] * 3
        + _bank_spec(e, d, f),
        out_specs=[
            _row_spec(bt, d),
            pl.BlockSpec((e, d, f), lambda b: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((e, f), lambda b: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((e, f, d), lambda b: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((e, d), lambda b: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, d), xs.dtype),
            jax.ShapeDtypeStruct((e, d, f), jnp.float32),
            jax.ShapeDtypeStruct((e, f), jnp.float32),
            jax.ShapeDtypeStruct((e, f, d), jnp.float32),
            jax.ShapeDtypeStruct((e, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params("arbitrary"),
        interpret=_interpret(),
    )(offsets, xs, g, ys, wu, bu, wd)


@jax.custom_vjp
def grouped_ffn(xs, wu, bu, wd, bd, offsets):
    """Segment FFN over expert-sorted rows.

    xs [M, D] sorted rows (M a multiple of the block edge); wu/bu/wd/bd the
    stacked expert bank in the compute dtype; offsets [E+1] int32 cumulative
    segment boundaries with offsets[-1] == M (sort-padding rows fold into
    the last segment — their cotangent is zero by construction, so they
    never pollute dW). Returns [M, D] in xs.dtype; rows outside every
    segment are exact zeros.
    """
    return _grouped_ffn_fwd_call(xs, wu, bu, wd, bd, offsets)


def _grouped_ffn_fwd(xs, wu, bu, wd, bd, offsets):
    ys = _grouped_ffn_fwd_call(xs, wu, bu, wd, bd, offsets)
    return ys, (xs, wu, bu, wd, bd, offsets, ys)


def _grouped_ffn_bwd(res, g):
    xs, wu, bu, wd, bd, offsets, ys = res
    dx, dwu, dbu, dwd, dbd = _grouped_ffn_bwd_call(
        xs, g, ys, wu, bu, wd, offsets
    )
    return (
        dx,
        dwu.astype(wu.dtype),
        dbu.astype(bu.dtype),
        dwd.astype(wd.dtype),
        dbd.astype(bd.dtype),
        np.zeros(offsets.shape, jax.dtypes.float0),
    )


grouped_ffn.defvjp(_grouped_ffn_fwd, _grouped_ffn_bwd)


# ---------------------------------------------------------------------------
# The dropless sorted dataflow (meshless path).
# ---------------------------------------------------------------------------


def sort_plan(cfg, top_idx):
    """Device-side sort plan over the flattened `[B*S*K]` assignments.

    Returns (src, inv, offsets):
      src     [M]   int32 flat token index feeding each sorted row (M = NK
                    padded to a block multiple; padding rows re-read row 0
                    — they are fed to the LAST expert's segment tail and
                    their output is never gathered, their cotangent never
                    nonzero)
      inv     [NK]  int32 position of each (token, k) pair in the sorted
                    buffer (the unsort gather)
      offsets [E+1] int32 cumulative expert segment boundaries, with the
                    sort padding folded into expert E-1 so the row space
                    [0, M) is fully covered
    """
    b, s, k = top_idx.shape
    nk = b * s * k
    _, m = _plan_rows(nk)
    ids = top_idx.reshape(nk)
    if m > nk:
        ids = jnp.concatenate(
            [ids, jnp.full((m - nk,), cfg.num_experts - 1, jnp.int32)]
        )
    # stable: within an expert, rows stay in (b, s, k) order — the same
    # order the buffer paths' causal cumsum slots them in
    order = jnp.argsort(ids, stable=True)
    counts = jnp.zeros((cfg.num_experts,), jnp.int32).at[ids].add(1)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    inv = jnp.argsort(order, stable=True)[:nk].astype(jnp.int32)
    src = jnp.where(order < nk, order // k, 0).astype(jnp.int32)
    return src, inv, offsets


def pallas_kept_mask(cfg, x, router_kernel):
    """[B,S,E] 0/1 mask of assignments the pallas dispatch KEEPS — the
    drop-parity test surface. Dropless (cfg.moe_capacity == 0) keeps every
    routed assignment; capacity mode reuses the xla path's `_kept_mask`
    verbatim, so the dropped token set is bit-identical."""
    _, _, _, _, assign = _route_topk(x, router_kernel, cfg)
    if cfg.moe_capacity > 0:
        return _kept_mask(assign, moe_capacity(cfg, x.shape[1]))
    return assign


def _grouped_expert_ffn(experts, expert_in, dtype):
    """`_expert_ffn` twin over the post-exchange `[E_local, R, C, D]`
    buffer, computed by the grouped kernel: the buffer is already
    expert-contiguous, i.e. the sorted layout with static equal segments
    (R*C rows per local expert; block padding folds into the last
    segment and is sliced off)."""
    e_l, r, c, d = expert_in.shape
    n = e_l * r * c
    rows = expert_in.reshape(n, d)
    _, m = _plan_rows(n)
    if m > n:
        rows = jnp.pad(rows, ((0, m - n), (0, 0)))
    offs = np.arange(e_l + 1, dtype=np.int32) * (r * c)
    offs[-1] = m
    ys = grouped_ffn(
        rows,
        experts["up"]["kernel"].astype(dtype),
        experts["up"]["bias"].astype(dtype),
        experts["down"]["kernel"].astype(dtype),
        experts["down"]["bias"].astype(dtype),
        jnp.asarray(offs),
    )
    return ys[:n].reshape(e_l, r, c, d)


def moe_ffn_pallas(layer, cfg, x, pad_mask=None):
    """The grouped-GEMM MoE FFN. Returns (out [B,S,D], aux scalar) — the
    same contract as moe_ffn_xla / moe_ffn_a2a.

    Meshless (cfg.moe_mesh is None): the dropless sorted dataflow — route,
    sort by expert, segment GEMM, unsort, gated top-k combine. With
    `cfg.moe_capacity > 0` the xla drop mask zeroes the dropped
    assignments' gates: their FFN output, their gradient to x/W and their
    router gradient are all exact zeros, reproducing the buffer paths'
    residual-passthrough bit-for-bit while the routing, aux statistics and
    kept-token math stay shared code with the other dispatches.

    Under ExpertParallel (mesh injected): the "a2a" exchange block with
    the local expert FFN swapped for the grouped kernel — collectives and
    byte audit unchanged (see module docstring).
    """
    if cfg.moe_mesh is not None:
        return _moe_ffn_exchange(
            layer, cfg, x, pad_mask, _grouped_expert_ffn, "pallas"
        )
    _check_bank(cfg.num_experts)
    experts = layer["ffn"]["experts"]
    xc, top_idx, top_vals, probs, assign = _route_topk(
        x, layer["ffn"]["router"]["kernel"], cfg
    )
    b, s, d = x.shape
    k = cfg.router_top_k

    gates = top_vals  # [B,S,K] f32, raw router probability (GShard gates)
    if cfg.moe_capacity > 0:
        kept = _kept_mask(assign, moe_capacity(cfg, s))
        gates = gates * jnp.take_along_axis(kept, top_idx, axis=-1)

    src, inv, offsets = sort_plan(cfg, top_idx)
    xs = jnp.take(xc.reshape(b * s, d), src, axis=0)
    ys = grouped_ffn(
        xs,
        experts["up"]["kernel"].astype(cfg.compute_dtype),
        experts["up"]["bias"].astype(cfg.compute_dtype),
        experts["down"]["kernel"].astype(cfg.compute_dtype),
        experts["down"]["bias"].astype(cfg.compute_dtype),
        offsets,
    )
    # unsort (pure gather — its transpose is the scatter-add the backward
    # needs) and combine weighted by each (token, expert)'s gate
    y_pairs = jnp.take(ys, inv, axis=0).reshape(b, s, k, d)
    out = jnp.einsum(
        "bskd,bsk->bsd", y_pairs, gates.astype(cfg.compute_dtype)
    )
    num, den = _aux_stats(probs, assign, pad_mask, cfg)
    aux = cfg.num_experts * num / jnp.maximum(den, 1.0)
    return out, aux
