"""Fused paged-attention decode kernel (Pallas TPU, round 21 — ROADMAP #3).

The unfused paged decode path (`gpt._apply_attention_paged`) pays one
XLA gather PER LAYER to materialize the full `[N, H, MP*P, D]` view of
every slot's pages before attention — and int8 pools dequantize that
whole view up front, paying the f32 expansion in HBM for positions the
causal window then masks away. This kernel removes the materialized view
entirely: the block tables are dereferenced INSIDE the kernel (scalar-
prefetch SMEM reads feeding VMEM page-tile copies), int8 pages
dequantize tile-by-tile in VMEM via the quant_comm 256-element block
layout, and the softmax/value mix runs flash-style over the assembled
window — the only HBM traffic is the page pool itself, once per head.

Decode-only by design: one query token per slot (the serve decode tick),
no VJP — training attention is `pallas_attention.py`'s job. The pool
WRITE-BACK also stays outside (the shared `paged.write_token` spelling):
the kernel is a pure read, which is what keeps the TP comm audit the
unfused plan unchanged (see `fused_paged_attention`).

Exactness (the parity bar, tests/test_paged_attention.py): the kernel is
`gpt._attend_over_cache` over the gathered view OPERATION-FOR-OPERATION
— same dots on the same operands in the same dtypes, algebraically
identical softmax (below). The one residual is reassociation, not math:
XLA compiles the kernel's per-(head, slot) dots inside the grid program
(interpret mode scans the grid; Mosaic tiles it) while the reference
einsum is a standalone batched GEMM, and the two accumulation orders
differ at the ~1-ULP level (measured max 5e-7 on XLA:CPU f32 at test
shapes). The tests therefore pin what is actually invariant: attention
outputs within a few ULPs, and TOKEN streams (greedy and fixed-seed
sampled, through the full engine) exactly identical. Two deliberate
choices keep the math itself identical:

  - ONE online-softmax block over the whole window. The decode window is
    statically bounded (`MP * P` positions — pages_per_slot is a config
    constant), so the flash recurrence degenerates to a single call of
    the shared `online_softmax_update` helper from `-inf`/`0` state:
    `m = maximum(-inf, max(s))` IS the plain softmax max and
    `l = 0 * exp(-inf) + sum(p)` IS the plain normalizer, exactly.
    A page-blocked multi-call recurrence would trade that exactness
    for nothing here — the whole window already fits VMEM.
  - Divide BEFORE the value dot: `o = (p / l) @ v`, matching
    `jax.nn.softmax(...).astype(cdt) @ v` operation-for-operation (the
    reference casts probabilities to the compute dtype before the mix,
    and so does this kernel).

int8 pages dequantize with the exact `quant_comm.dequantize_blocks`
arithmetic (f32 cast, per-256-block scale multiply) per page tile, so
the fused int8 path is elementwise-identical to gather_view's dequant —
the existing >=90% token-agreement gate transfers unchanged.

Grid is `(H, N)` with slots innermost: the per-head pool slab
`[NP, 1, P, D]` stays VMEM-resident while every slot's window is
assembled against it — the pool is fetched H times total, not N*H.
Every test runs on this container via `interpret=_interpret()` (the
pallas_attention convention); the VMEM footprint of the head slab is
asserted with a named error (TPUKIT_PAGED_VMEM_MB) instead of a Mosaic
OOM.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from tpukit.ops import quant_comm
from tpukit.ops.pallas_attention import (
    NEG_INF,
    _interpret,
    online_softmax_update,
    tpu_compiler_params,
)

# The per-head VMEM working set (both pool slabs + scale rows + the two
# assembled windows) is bounded with a NAMED error instead of a Mosaic
# OOM — the moe_gemm._MAX_VMEM_EXPERTS discipline. Sweepable.
_PAGED_VMEM_BYTES = (
    int(os.environ.get("TPUKIT_PAGED_VMEM_MB", "64")) * 1024 * 1024
)


def _check_vmem(num_pages, page, head_dim, mp, pool_itemsize, quant, cdt):
    window = mp * page * head_dim * jnp.dtype(cdt).itemsize
    slab = num_pages * page * head_dim * pool_itemsize
    total = 2 * slab + 2 * window
    if quant:
        nb = (page * head_dim) // quant_comm.DEFAULT_BLOCK
        total += 2 * num_pages * nb * 4
    if total > _PAGED_VMEM_BYTES:
        raise ValueError(
            f"fused paged attention keeps one head's K+V pool slab VMEM-"
            f"resident: {num_pages} pages x {page} x {head_dim} needs "
            f"{total // (1024 * 1024)} MiB, over the "
            f"{_PAGED_VMEM_BYTES // (1024 * 1024)} MiB budget "
            f"(TPUKIT_PAGED_VMEM_MB) — shrink the pool or use the "
            f"unfused path (fused_decode=False)"
        )


def _paged_kernel(bt_ref, start_ref, *refs, page, mp, head_dim, quant,
                  scale):
    """One (head, slot) step: assemble the slot's `[MP*P, D]` K/V window
    from its block-table pages (SMEM-prefetched ids -> VMEM tile copies,
    dequantizing int8 tiles in place), insert the fresh K/V at the
    cursor, and run the single-block flash softmax + value mix."""
    if quant:
        (pool_k_ref, pool_v_ref, sk_ref, sv_ref, q_ref, kn_ref, vn_ref,
         o_ref, k_win, v_win) = refs
    else:
        (pool_k_ref, pool_v_ref, q_ref, kn_ref, vn_ref, o_ref, k_win,
         v_win) = refs
    n = pl.program_id(1)
    w = mp * page
    st = start_ref[n]

    def load_tile(pool_ref, scale_ref, pid):
        tile = pool_ref[pl.ds(pid, 1), 0]  # (1, P, D), pool storage dtype
        if quant:
            nb = (page * head_dim) // quant_comm.DEFAULT_BLOCK
            srow = scale_ref[pl.ds(pid, 1), 0]  # (1, nb) f32
            # dequantize_blocks verbatim per (page, head) row: f32 cast,
            # per-256-element-block scale multiply — elementwise-identical
            # to the gathered view's dequant
            xb = tile.astype(jnp.float32).reshape(nb, quant_comm.DEFAULT_BLOCK)
            tile = (xb * srow.reshape(nb, 1)).reshape(1, page, head_dim)
        return tile.reshape(page, head_dim).astype(k_win.dtype)

    for j in range(mp):  # MP is static and small: unrolled page walk
        pid = bt_ref[n, j]
        k_win[pl.ds(j * page, page), :] = load_tile(
            pool_k_ref, sk_ref if quant else None, pid
        )
        v_win[pl.ds(j * page, page), :] = load_tile(
            pool_v_ref, sv_ref if quant else None, pid
        )

    # fresh-token insert at the cursor — the same clamp semantics as the
    # unfused path's dynamic_update_slice (start is < W for every lane
    # the engine dispatches; the clamp only guards degenerate inputs)
    idx = jnp.minimum(st, w - 1)
    k_win[pl.ds(idx, 1), :] = kn_ref[0]
    v_win[pl.ds(idx, 1), :] = vn_ref[0]

    # scores in the COMPUTE dtype (no preferred_element_type — the
    # reference einsum's accumulation), scale + causal mask applied in
    # the same dtype/order as _attend_over_cache, THEN the f32 cast
    s = jax.lax.dot_general(
        q_ref[0], k_win[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
    ) * scale  # (1, W)
    key_pos = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
    s = jnp.where(key_pos <= st, s, jnp.asarray(NEG_INF, s.dtype))
    s32 = s.astype(jnp.float32)

    # ONE shared-helper call over the full window: degenerate flash ==
    # plain softmax exactly (module docstring); divide-before-dot matches
    # softmax(...).astype(cdt) @ v operation-for-operation
    m0 = jnp.full((1, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((1, 1), jnp.float32)
    _, l, _, p = online_softmax_update(m0, l0, s32)
    probs = (p / l).astype(v_win.dtype)
    o = jax.lax.dot_general(
        probs, v_win[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
    )
    o_ref[0] = o.astype(o_ref.dtype)


def paged_attend(pool_k, pool_v, scale_k, scale_v, bt, start, q, k_new,
                 v_new):
    """Fused paged decode attention over one layer's pools (meshless /
    per-shard form — see `fused_paged_attention` for the TP wrapper).

    pool_k/pool_v: `[NP, H, P, D]` page pools (f32/bf16 storage, or int8
    with `scale_k`/`scale_v` `[NP, H, nb]` f32 sidecars; pass None scales
    for unquantized pools); bt `[N, MP]` int32 block tables; start `[N]`
    int32 cursors; q/k_new/v_new `[N, H, D]` in the compute dtype (the
    decode tick's single token per slot). Returns `[N, H, D]` attention
    outputs — pre-projection, `_attend_over_cache` on the gathered view
    op-for-op (module docstring: identical math, ~1-ULP dot
    reassociation, exact token parity)."""
    num_pages, heads, page, head_dim = pool_k.shape
    n, mp = bt.shape
    quant = scale_k is not None
    cdt = q.dtype
    if quant and (page * head_dim) % quant_comm.DEFAULT_BLOCK:
        raise ValueError(
            f"int8 pages need page_size x head_dim ({page} x {head_dim}) "
            f"to tile into {quant_comm.DEFAULT_BLOCK}-element quant blocks "
            f"(paged.validate_kv_layout enforces this upstream)"
        )
    _check_vmem(num_pages, page, head_dim, mp, pool_k.dtype.itemsize,
                quant, cdt)
    w = mp * page

    kernel = functools.partial(
        _paged_kernel, page=page, mp=mp, head_dim=head_dim, quant=quant,
        scale=1.0 / head_dim**0.5,
    )
    # per-head pool slab, constant across the inner slot axis — fetched
    # into VMEM once per head and reused for every slot's window
    slab = pl.BlockSpec((num_pages, 1, page, head_dim),
                        lambda h, n, *_: (0, h, 0, 0))
    vec = pl.BlockSpec((1, 1, head_dim), lambda h, n, *_: (n, h, 0))
    in_specs = [slab, slab]
    operands = [pool_k, pool_v]
    if quant:
        nb = (page * head_dim) // quant_comm.DEFAULT_BLOCK
        srow = pl.BlockSpec((num_pages, 1, nb), lambda h, n, *_: (0, h, 0))
        in_specs += [srow, srow]
        operands += [scale_k, scale_v]
    in_specs += [vec, vec, vec]
    operands += [q, k_new, v_new]

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # bt + start ride SMEM, read per slot
            grid=(heads, n),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, head_dim),
                                   lambda h, n, *_: (n, h, 0)),
            scratch_shapes=[
                pltpu.VMEM((w, head_dim), cdt),
                pltpu.VMEM((w, head_dim), cdt),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, heads, head_dim), cdt),
        compiler_params=tpu_compiler_params("parallel", "arbitrary"),
        interpret=_interpret(),
    )(bt, start, *operands)


def fused_paged_attention(pool_k, pool_v, scale_k, scale_v, bt, start, q,
                          k_new, v_new, mesh=None):
    """`paged_attend` under the serving mesh. GSPMD cannot partition a
    pallas_call — left alone it would replicate the kernel and bolt
    resharding collectives around it, breaking the plan-exactness bar —
    so under a model axis the kernel runs inside shard_map at exactly the
    pools' serving layout: heads sharded over `model`, block tables and
    cursors replicated, zero collectives inside the body. The per-step
    comm therefore stays the unfused `decode_step_comm(paged=True)`
    closed form unchanged (the fused HLO audit, tests)."""
    if mesh is None or mesh.shape.get("model", 1) <= 1:
        return paged_attend(pool_k, pool_v, scale_k, scale_v, bt, start,
                            q, k_new, v_new)
    m = mesh.shape["model"]
    heads = pool_k.shape[1]
    if heads % m:
        raise ValueError(
            f"fused paged attention shards heads over the model axis: "
            f"heads={heads} must divide model={m} (the paged serving grid "
            f"picker guarantees this)"
        )
    from tpukit.compat import shard_map

    pool_spec = P(None, "model", None, None)
    head_spec = P(None, "model", None)
    if scale_k is None:
        fn = lambda pk, pv, b, s, qq, kn, vn: paged_attend(
            pk, pv, None, None, b, s, qq, kn, vn
        )
        return shard_map(
            fn, mesh=mesh,
            in_specs=(pool_spec, pool_spec, P(), P(), head_spec,
                      head_spec, head_spec),
            out_specs=head_spec, check_rep=False,
        )(pool_k, pool_v, bt, start, q, k_new, v_new)
    scale_spec = P(None, "model", None)
    return shard_map(
        paged_attend, mesh=mesh,
        in_specs=(pool_spec, pool_spec, scale_spec, scale_spec, P(), P(),
                  head_spec, head_spec, head_spec),
        out_specs=head_spec, check_rep=False,
    )(pool_k, pool_v, scale_k, scale_v, bt, start, q, k_new, v_new)
