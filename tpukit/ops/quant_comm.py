"""Block-scaled int8 quantized collectives (`--comm_dtype`, round 12).

EQuARX (PAPERS.md: *Efficient Quantized AllReduce in XLA*) shows that a
gradient all-reduce carrying block-scaled int8 payloads recovers near-full
training quality at ~4x fewer bytes on the wire; *The Big Send-off* argues
the collective SCHEDULE should not change while the payload shrinks. This
module is tpukit's implementation of both rules, built on the substrate the
earlier rounds created: every compressible collective is already hand-placed
inside shard_map (the MoE a2a dispatch of ops/moe_dispatch.py) or becomes so
here (the DP grad psum, the FSDP grad reduce-scatter), so compression is a
payload rewrite at a known call site — never a compiler heuristic.

Quantization scheme (the EQuARX block layout):

  - Values flatten to blocks of `DEFAULT_BLOCK` (256) elements. Each block
    carries one f32 scale = max|x| / 127; payload is `round(x / scale)`
    clipped to [-127, 127] as int8. An all-zero block quantizes to scale 0
    and dequantizes to exact zeros.
  - The f32 scale sidecar is BITCAST to int8 and concatenated onto the
    payload row, so one collective op moves payload + scales together: the
    op COUNT of the compiled program is identical to the unquantized
    schedule (the audit's "schedule unchanged" bar), and the wire cost of
    the sidecar is explicit — 4 bytes per 256-element block, a 1.6%
    overhead on the 4x win.
  - Rounding is round-to-nearest-even by default; `rng`/`stochastic`
    switches to stochastic rounding (floor(x/scale + U[0,1)) — unbiased,
    the EQuARX option for long-horizon drift), default OFF behind
    `--quant_stochastic`.

Collective wrappers (all called INSIDE shard_map, axis sizes passed as
static Python ints — `lax.axis_size` is not static on every supported jax):

  - `quantized_all_reduce`: the EQuARX two-shot shape — quantize per
    destination, all_to_all (the reduce-scatter phase), dequantize and
    accumulate in f32, re-quantize the reduced chunk, all_gather,
    dequantize. Accumulation is ALWAYS f32; only the wire is int8.
  - `quantized_reduce_scatter` / `quantized_all_gather`: the two phases as
    standalone wrappers (dim-aware, for FSDP-style layouts).
  - `all_gather_qgrad`: custom-vjp param gather — forward is a FULL
    PRECISION lax.all_gather (params-at-use stay exact; "grads-only
    first"), backward compresses the cotangent through the quantized
    reduce-scatter. Gather-at-use FSDP forward + int8 grad wire, in one
    primitive.
  - `psum_grad`: identity forward, full-precision psum backward — the
    replicated-leaf companion of `all_gather_qgrad` (sub-threshold tensors
    move few bytes; compressing them buys noise, not bandwidth).
  - `exchange_all_to_all`: the MoE dispatch/combine exchange of
    ops/moe_dispatch.py with a quantized payload mode. int8 rides a
    custom vjp whose backward is the mirrored quantized exchange — the
    a2a formulation stays its own transpose, so the op schedule (4 x
    layers train, 6 remat, 2 eval) is byte-for-byte the audit the f32
    path already proves.

Bucket scheduler (`--grad_buckets`, round 18 — ROADMAP #5): the serial
payloads above fire AFTER backward completes, so wire time adds directly
to step time. The bucket spellings below partition the grad tree into
~equal-byte buckets in layer-reversed (backward-completion) order and
issue one exchange per bucket the moment that bucket's grads exist in
the dataflow — each bucket's collective depends only on its own leaves'
backward, so the remaining backward compute is schedulable between the
collective's start and done (XLA's latency-hiding scheduler on TPU; the
hlolint `overlap` rule audits the independence structurally on every
backend). This module is the ONE home of that machinery
(tools/lint_invariants.py's collective-spelling rule keeps it so):

  - `grad_bucket_plan`: the deterministic partition (leaf indices per
    bucket) shared by the value_and_grad blocks AND the closed-form
    byte audits — predicting bucket bytes requires agreeing on buckets.
  - `bucket_all_reduce` / `bucketed_psum_tree`: the DDP bucket wire —
    the EQuARX two-shot per bucket at EVERY comm dtype (f32 keeps the
    two-shot shape rather than lax.psum, so the f32 bucket schedule is
    the same auditable a2a+all_gather pair and bit-identical across
    bucket counts: element sums run in fixed device order).
  - `bucket_gather_qgrad`: the FSDP bucket wire — per-leaf FULL
    PRECISION forward gathers (unchanged vs the serial path), ONE
    packed reduce-scatter a2a per bucket in the backward (the serial
    path pays one a2a per leaf; bucketing amortizes per-op latency and
    creates the independent payloads overlap needs).

`comm_dtype` modes: "f32" = passthrough (the exact pre-round-12 HLO);
"bf16" = payload cast to bf16, f32 accumulation, no sidecar; "int8" =
block-scaled payload + packed scale sidecar. Because quantization is lossy
by construction, the correctness contract is a LOSS-TRAJECTORY tolerance
gate (quantized-vs-f32 final-loss delta per strategy, tests/
test_quant_comm.py + bench.py's quant_comm record), not bit parity.

The audit half mirrors ops/moe_dispatch.expected_a2a: `packed_bytes` /
`expected_all_reduce` are the closed-form payload+sidecar sizes the
compiled HLO must show (consumed by `Strategy.grad_comm`, the dryrun and
tests), and `wire_itemsize` resolves the dtype a payload actually travels
at per backend — XLA:CPU's float normalization upcasts bf16 buffers to f32
on the wire (the round-10 eval-audit divergence), while int8 payloads are
upcast-immune, which is what lets the quantized audits assert EXACT bytes
on every backend.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256  # elements per scale block (f32 sidecar: 4B / block)

COMM_DTYPES = ("f32", "bf16", "int8")


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def wire_itemsize(dtype: str, backend: str | None = None) -> int:
    """Bytes per element a payload of `dtype` occupies ON THE WIRE of the
    compiled program. The one subtlety: backends without native bf16
    execution (XLA:CPU) run their float-normalization pass over the whole
    module, upcasting bf16 collectives to f32 — so a bf16 payload audits
    at 4 bytes there and 2 on TPU. int8 payloads are integer ops outside
    that pass: 1 byte everywhere, which is why the int8 audits are exact
    on every backend."""
    if dtype in ("int8", "s8"):
        return 1
    if dtype in ("bf16", "bfloat16"):
        return 4 if backend == "cpu" else 2
    return 4


def packed_bytes(n: int, block: int = DEFAULT_BLOCK) -> int:
    """Wire bytes of one packed int8 payload covering `n` f32 elements:
    exactly `n` int8 values plus the bitcast f32 scale sidecar (one scale
    per started block — block padding never travels: pad positions
    quantize to exact zeros, so the payload is sliced to `n` before the
    collective and re-padded after)."""
    n = max(n, 1)
    return n + 4 * (-(-n // block))


# -- block quantize / dequantize -------------------------------------------


def quantize_blocks(x, block: int = DEFAULT_BLOCK, rng=None):
    """Quantize `x` [..., chunk] (chunk % block == 0) to
    (q int8 [..., chunk], scales f32 [..., chunk // block]). Leading axes
    are PRESERVED, never merged — a sharded leading axis (the paged KV
    pools' head axis, round 15) stays sharded through the quantizer
    instead of forcing a GSPMD reshard around a rows-merge.

    Per-block max-abs scaling: scale = max|x| / 127 over each block;
    q = round(x / scale) in [-127, 127]. `rng` switches round-to-nearest
    to stochastic rounding (floor(v + U[0,1)) — unbiased per element)."""
    chunk = x.shape[-1]
    lead = x.shape[:-1]
    xb = x.astype(jnp.float32).reshape(*lead, chunk // block, block)
    amax = jnp.max(jnp.abs(xb), axis=-1)  # [..., S]
    scales = amax / 127.0
    inv = jnp.where(amax > 0, 127.0 / jnp.where(amax > 0, amax, 1.0), 0.0)
    v = xb * inv[..., None]
    if rng is not None:
        v = jnp.floor(v + jax.random.uniform(rng, v.shape))
    else:
        v = jnp.round(v)
    q = jnp.clip(v, -127, 127).astype(jnp.int8).reshape(x.shape)
    return q, scales


def dequantize_blocks(q, scales, block: int = DEFAULT_BLOCK):
    """Inverse of quantize_blocks: f32 [..., chunk] (leading axes
    preserved, same sharding rationale)."""
    chunk = q.shape[-1]
    xb = q.astype(jnp.float32).reshape(*q.shape[:-1], chunk // block, block)
    return (xb * scales[..., None]).reshape(q.shape)


def quantize_blockwise(x, block: int = DEFAULT_BLOCK, rng=None):
    """Flat convenience API: quantize an arbitrary array to
    (q int8 [n_pad], scales f32 [n_pad // block]) with zero padding to a
    block multiple. Round-trips through dequantize_blockwise."""
    n = x.size
    chunk = _ceil_to(max(n, 1), block)
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, chunk - n))
    q, scales = quantize_blocks(flat[None], block, rng)
    return q[0], scales[0]


def dequantize_blockwise(q, scales, shape, block: int = DEFAULT_BLOCK):
    """Inverse of quantize_blockwise at the original `shape`."""
    n = 1
    for d in shape:
        n *= d
    return dequantize_blocks(q[None], scales[None], block)[0, :n].reshape(shape)


def pack_quantized(parts, block: int = DEFAULT_BLOCK, rng=None):
    """Quantize [rows, n_c] f32 rows (ANY n_c) into wire-ready packed int8
    rows of exactly `packed_bytes(n_c, block)` bytes each: block padding
    is internal to the quantizer (pad positions are exact zeros) and is
    SLICED OFF before the wire — the payload carries n_c values plus one
    bitcast f32 scale per started block."""
    rows, n_c = parts.shape
    chunk = _ceil_to(max(n_c, 1), block)
    padded = jnp.pad(parts.astype(jnp.float32), ((0, 0), (0, chunk - n_c)))
    q, scales = quantize_blocks(padded, block, rng)
    sb = jax.lax.bitcast_convert_type(scales, jnp.int8).reshape(rows, -1)
    return jnp.concatenate([q[:, :n_c], sb], axis=1)


def unpack_dequantized(packed, n_c: int, block: int = DEFAULT_BLOCK):
    """Inverse of pack_quantized -> f32 [rows, n_c]."""
    rows = packed.shape[0]
    chunk = _ceil_to(max(n_c, 1), block)
    q = jnp.pad(packed[:, :n_c], ((0, 0), (0, chunk - n_c)))
    sb = packed[:, n_c:].reshape(rows, chunk // block, 4)
    scales = jax.lax.bitcast_convert_type(sb, jnp.float32)
    return dequantize_blocks(q, scales, block)[:, :n_c]


def _fallback_key(axis_name: str | None, sample):
    """Stochastic-rounding key for call sites without a threaded rng (the
    custom-vjp backwards): a fixed base folded with the device's mesh
    position (decorrelates replicas) and a data word derived from the
    WHOLE tensor being quantized (its f32 sum — decorrelates steps: the
    word changes whenever any element does, unlike a single probe element
    which can be structurally constant, e.g. a never-touched embedding
    row's zero gradient, and would replay identical noise every step)."""
    key = jax.random.PRNGKey(0x51C0)
    if axis_name is not None:
        key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
    word = jax.lax.bitcast_convert_type(
        jnp.sum(sample.astype(jnp.float32)), jnp.int32
    )
    return jax.random.fold_in(key, word)


def _check_dtype(dtype: str) -> None:
    if dtype not in COMM_DTYPES:
        raise ValueError(
            f"comm dtype must be one of {COMM_DTYPES}, got {dtype!r}"
        )


# -- collective wrappers (call inside shard_map) ---------------------------


def quantized_all_reduce(x, axis_name: str, world: int, dtype: str = "int8",
                         block: int = DEFAULT_BLOCK, rng=None):
    """Sum `x` over `axis_name` with a compressed payload — the EQuARX
    two-shot all-reduce: quantize per destination chunk -> all_to_all (the
    reduce-scatter phase, int8/bf16 on the wire) -> dequantize and
    ACCUMULATE IN F32 -> re-quantize the reduced chunk -> all_gather ->
    dequantize. "f32" is an exact lax.psum passthrough. world == 1 keeps
    the quantize/dequantize numerics (representative of the wire) but
    skips the collectives."""
    _check_dtype(dtype)
    if dtype == "f32":
        return jax.lax.psum(x, axis_name)
    shape, n = x.shape, x.size
    chunk = _ceil_to(max(n, 1), world) // world  # per-destination elems
    total = world * chunk
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, total - n))
    parts = flat.reshape(world, chunk)
    if dtype == "bf16":
        payload = parts.astype(jnp.bfloat16)
        if world > 1:
            payload = jax.lax.all_to_all(payload, axis_name, 0, 0, tiled=True)
        red = jnp.sum(payload.astype(jnp.float32), axis=0)  # f32 accumulate
        out = red.astype(jnp.bfloat16)
        if world > 1:
            gathered = jax.lax.all_gather(out, axis_name, axis=0, tiled=False)
        else:
            gathered = out[None]
        res = gathered.astype(jnp.float32).reshape(total)
    else:
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        packed = pack_quantized(parts, block, r1)
        if world > 1:
            packed = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
        vals = unpack_dequantized(packed, chunk, block)
        red = jnp.sum(vals, axis=0)  # [chunk] f32 accumulate
        row = pack_quantized(red[None], block, r2)[0]
        if world > 1:
            gathered = jax.lax.all_gather(row, axis_name, axis=0, tiled=False)
        else:
            gathered = row[None]
        res = unpack_dequantized(gathered, chunk, block).reshape(total)
    return res[:n].reshape(shape).astype(x.dtype)


def quantized_psum_tree(tree, axis_name: str, world: int, dtype: str = "int8",
                        block: int = DEFAULT_BLOCK, rng=None):
    """quantized_all_reduce over a whole pytree, flattened into ONE payload
    (one a2a + one all_gather per step, however many leaves) — the DP grad
    psum spelling. Leaf dtypes/shapes are restored on the way out."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])
    red = quantized_all_reduce(flat, axis_name, world, dtype, block, rng)
    out, off = [], 0
    for leaf in leaves:
        out.append(red[off:off + leaf.size].reshape(leaf.shape).astype(leaf.dtype))
        off += leaf.size
    return jax.tree_util.tree_unflatten(treedef, out)


def quantized_reduce_scatter(x, axis_name: str, world: int, dim: int = 0,
                             dtype: str = "int8", block: int = DEFAULT_BLOCK,
                             rng=None):
    """Sum `x` over `axis_name` and keep this device's slice of dimension
    `dim` (which must divide by `world`). Payload compressed per
    destination; accumulation f32. "f32" = exact lax.psum_scatter."""
    _check_dtype(dtype)
    if dtype == "f32":
        return jax.lax.psum_scatter(
            x, axis_name, scatter_dimension=dim, tiled=True
        )
    if x.shape[dim] % world:
        raise ValueError(
            f"reduce-scatter dim {dim} of shape {x.shape} must divide by "
            f"the {world}-way axis"
        )
    moved = jnp.moveaxis(x, dim, 0)
    shard_shape = (moved.shape[0] // world,) + moved.shape[1:]
    parts = moved.astype(jnp.float32).reshape(world, -1)  # [w, n_c]
    n_c = parts.shape[1]
    if dtype == "bf16":
        payload = parts.astype(jnp.bfloat16)
        if world > 1:
            payload = jax.lax.all_to_all(payload, axis_name, 0, 0, tiled=True)
        red = jnp.sum(payload.astype(jnp.float32), axis=0)
    else:
        packed = pack_quantized(parts, block, rng)
        if world > 1:
            packed = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
        red = jnp.sum(unpack_dequantized(packed, n_c, block), axis=0)
    return jnp.moveaxis(
        red.reshape(shard_shape), 0, dim
    ).astype(x.dtype)


def quantized_all_gather(x, axis_name: str, world: int, dim: int = 0,
                         dtype: str = "int8", block: int = DEFAULT_BLOCK,
                         rng=None):
    """Gather every device's `x` concatenated along `dim`, payload
    compressed (each source's block scales ride the packed row). "f32" =
    exact lax.all_gather."""
    _check_dtype(dtype)
    if dtype == "f32":
        return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
    moved = jnp.moveaxis(x, dim, 0)
    n = moved.size
    if dtype == "bf16":
        payload = moved.astype(jnp.bfloat16)
        if world > 1:
            gathered = jax.lax.all_gather(payload, axis_name, axis=0, tiled=False)
        else:
            gathered = payload[None]
        vals = gathered.astype(jnp.float32)
    else:
        row = pack_quantized(moved.reshape(1, -1), block, rng)[0]
        if world > 1:
            gathered = jax.lax.all_gather(row, axis_name, axis=0, tiled=False)
        else:
            gathered = row[None]
        vals = unpack_dequantized(gathered, n, block).reshape(
            (world,) + moved.shape
        )
    full = vals.reshape((world * moved.shape[0],) + moved.shape[1:])
    return jnp.moveaxis(full, 0, dim).astype(x.dtype)


# -- custom-vjp primitives: full-precision forward, compressed grad wire ---


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def all_gather_qgrad(x, axis_name: str, world: int, dim: int, dtype: str,
                     block: int, stochastic: bool):
    """FSDP gather-at-use with a quantized gradient wire: forward is a
    FULL-PRECISION lax.all_gather of the param shard along `dim` (the
    "grads-only first" contract — params at use stay exact, so the forward
    is bit-identical to the unquantized math); backward compresses the
    cotangent through quantized_reduce_scatter, which is exactly the FSDP
    grad reduce-scatter with an int8/bf16 payload."""
    if world <= 1:
        return x
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _agq_fwd(x, axis_name, world, dim, dtype, block, stochastic):
    return all_gather_qgrad(x, axis_name, world, dim, dtype, block, stochastic), None


def _agq_bwd(axis_name, world, dim, dtype, block, stochastic, _, g):
    if world <= 1:
        return (g,)
    rng = _fallback_key(axis_name, g) if stochastic and dtype == "int8" else None
    return (
        quantized_reduce_scatter(g, axis_name, world, dim, dtype, block, rng),
    )


all_gather_qgrad.defvjp(_agq_fwd, _agq_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_grad(x, axis_name: str):
    """Identity forward, FULL-PRECISION psum backward: the replicated-leaf
    companion of all_gather_qgrad inside a manual FSDP block. Sub-threshold
    tensors (norms, biases) move few bytes; their grads stay f32."""
    return x


def _psg_fwd(x, axis_name):
    return x, None


def _psg_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


psum_grad.defvjp(_psg_fwd, _psg_bwd)


# -- MoE dispatch exchange --------------------------------------------------


def _qa2a_impl(x, axis_name: str, world: int, mode: str, block: int,
               stochastic: bool):
    """One quantized MoE exchange hop. mode "dispatch": [E, B, C, D] ->
    [E/w, w*B, C, D] (the forward token send, lax.all_to_all split 0 /
    concat 1); mode "combine": the inverse (split 1 / concat 0). Payload
    is quantized per DESTINATION group, packed with its scale sidecar, and
    moved by ONE int8 all_to_all — same op count as the f32 exchange."""
    out_dtype = x.dtype
    if mode == "dispatch":
        e, b, c, d = x.shape
        el = e // world
        parts = x.astype(jnp.float32).reshape(world, el * b * c * d)
    else:
        el, wb, c, d = x.shape
        b = wb // world
        parts = (
            x.astype(jnp.float32)
            .reshape(el, world, b, c, d)
            .transpose(1, 0, 2, 3, 4)
            .reshape(world, el * b * c * d)
        )
    n_g = parts.shape[1]
    rng = _fallback_key(axis_name, parts) if stochastic else None
    packed = pack_quantized(parts, block, rng)
    recv = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
    vals = unpack_dequantized(recv, n_g, block)
    if mode == "dispatch":
        out = (
            vals.reshape(world, el, b, c, d)
            .transpose(1, 0, 2, 3, 4)
            .reshape(el, world * b, c, d)
        )
    else:
        out = vals.reshape(world * el, b, c, d)
    return out.astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _quant_a2a(x, axis_name: str, world: int, mode: str, block: int,
               stochastic: bool):
    return _qa2a_impl(x, axis_name, world, mode, block, stochastic)


def _qa2a_fwd(x, axis_name, world, mode, block, stochastic):
    return _quant_a2a(x, axis_name, world, mode, block, stochastic), None


def _qa2a_bwd(axis_name, world, mode, block, stochastic, _, g):
    # The a2a formulation is its own transpose: the cotangent of a
    # dispatch hop travels the mirrored combine hop (and vice versa),
    # quantized the same way — one a2a per backward hop, so the compiled
    # schedule keeps the f32 path's op counts exactly.
    inverse = "combine" if mode == "dispatch" else "dispatch"
    return (_qa2a_impl(g, axis_name, world, inverse, block, stochastic),)


_quant_a2a.defvjp(_qa2a_fwd, _qa2a_bwd)


def exchange_all_to_all(x, axis_name: str, world: int, mode: str,
                        dtype: str = "f32", block: int = DEFAULT_BLOCK,
                        stochastic: bool = False):
    """The MoE token exchange of ops/moe_dispatch._moe_ffn_exchange with a
    selectable payload dtype. "f32" emits the exact lax.all_to_all of the
    pre-round-12 path (byte-identical HLO); "bf16" casts around it (the
    transpose rules keep the backward payload bf16 too); "int8" rides the
    block-scaled custom-vjp exchange above."""
    _check_dtype(dtype)
    if mode not in ("dispatch", "combine"):
        raise ValueError(f"mode must be 'dispatch' or 'combine', got {mode!r}")
    split, concat = (0, 1) if mode == "dispatch" else (1, 0)
    if dtype == "f32":
        return jax.lax.all_to_all(
            x, axis_name, split_axis=split, concat_axis=concat, tiled=True
        )
    if dtype == "bf16":
        out = jax.lax.all_to_all(
            x.astype(jnp.bfloat16), axis_name, split_axis=split,
            concat_axis=concat, tiled=True,
        )
        return out.astype(x.dtype)
    return _quant_a2a(x, axis_name, world, mode, block, stochastic)


# -- bucket scheduler (--grad_buckets, round 18) ----------------------------


def _backward_rank(path) -> tuple[int, int]:
    """Backward-completion rank of one param-tree path: lower = its grads
    exist EARLIER in the backward sweep (head -> norm_out -> layers in
    reverse index order -> embeddings). Buckets are contiguous runs of
    this order, so bucket 0's collective can launch while the rest of the
    backward still runs. A stacked (scan_layers) layer leaf has no
    per-layer index and rides as one run."""
    names, layer_idx = [], 0
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            names.append(k.key)
        elif isinstance(k, jax.tree_util.SequenceKey):
            layer_idx = k.idx
    if "lm_head" in names:
        return (0, 0)
    if "norm_out" in names:
        return (0, 1)
    if "layers" in names:
        return (1, -layer_idx)  # layer L-1's backward completes first
    return (2, 0)  # embeddings: the very last grads of the sweep


def grad_bucket_plan(tree, n_buckets: int, include=None) -> list[list[int]]:
    """Partition `tree`'s flat leaf indices into <= n_buckets contiguous
    buckets of ~equal bytes, ordered by backward completion (layer-
    reversed). The ONE partition spelling: the value_and_grad bucket
    blocks and the closed-form byte audits (`expected_bucketed_*`,
    `Strategy.grad_comm`) must agree on it, or the audit predicts a
    schedule the program does not run. `include` (a set of flat indices)
    restricts the partition — FSDP buckets only its SHARDED leaves;
    replicated sub-threshold leaves stay on the f32 psum path."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    items = [
        (i, _backward_rank(path), _tree_leaf_size(leaf))
        for i, (path, leaf) in enumerate(paths)
        if include is None or i in include
    ]
    if not items:
        return []
    items.sort(key=lambda it: it[1])  # stable: ties keep tree order
    total = sum(size for _, _, size in items) or 1
    n_b = min(n_buckets, len(items))
    buckets: list[list[int]] = [[]]
    acc = 0
    for pos, (i, _, size) in enumerate(items):
        b = len(buckets) - 1
        if (
            buckets[b]
            and b < n_b - 1
            and (
                acc >= total * (b + 1) / n_b
                or len(items) - pos == n_b - 1 - b
            )
        ):
            buckets.append([])
        buckets[-1].append(i)
        acc += size
    return buckets


def _tree_leaf_size(leaf) -> int:
    """BYTES of one leaf (the partition's balance unit — the contract is
    ~equal wire bytes, and a mixed-dtype tree balanced by element count
    would skew buckets by the itemsize ratio). Leaves without a dtype
    (plain shapes) price as f32."""
    n = 1
    for d in leaf.shape:
        n *= int(d)
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return n * 4
    try:
        return n * int(np.dtype(dtype).itemsize)
    except TypeError:  # exotic/opaque dtypes (e.g. PRNG keys): price as f32
        return n * 4


def bucket_all_reduce(x, axis_name: str, world: int, dtype: str = "f32",
                      block: int = DEFAULT_BLOCK, rng=None):
    """Sum one flat bucket payload over `axis_name` as the two-shot
    exchange at EVERY dtype — unlike quantized_all_reduce, "f32" keeps
    the a2a + all_gather shape (f32 rows, no packing) instead of
    lax.psum: the f32 bucket schedule is then the same pair of auditable,
    mutually-independent collectives the quantized one is, and the
    reduced value of every element is a fixed-device-order f32 sum —
    bit-identical under ANY bucket partition (the f32 parity bar)."""
    _check_dtype(dtype)
    shape, n = x.shape, x.size
    chunk = _ceil_to(max(n, 1), world) // world
    total = world * chunk
    flat = jnp.pad(x.reshape(-1).astype(jnp.float32), (0, total - n))
    parts = flat.reshape(world, chunk)
    if dtype == "int8":
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        packed = pack_quantized(parts, block, r1)
        if world > 1:
            packed = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
        red = jnp.sum(unpack_dequantized(packed, chunk, block), axis=0)
        row = pack_quantized(red[None], block, r2)[0]
        if world > 1:
            gathered = jax.lax.all_gather(row, axis_name, axis=0, tiled=False)
        else:
            gathered = row[None]
        res = unpack_dequantized(gathered, chunk, block).reshape(total)
    else:
        payload = parts if dtype == "f32" else parts.astype(jnp.bfloat16)
        if world > 1:
            payload = jax.lax.all_to_all(payload, axis_name, 0, 0, tiled=True)
        red = jnp.sum(payload.astype(jnp.float32), axis=0)  # f32 accumulate
        out = red if dtype == "f32" else red.astype(jnp.bfloat16)
        if world > 1:
            gathered = jax.lax.all_gather(out, axis_name, axis=0, tiled=False)
        else:
            gathered = out[None]
        res = gathered.astype(jnp.float32).reshape(total)
    return res[:n].reshape(shape).astype(x.dtype)


def bucketed_psum_tree(tree, axis_name: str, world: int, n_buckets: int,
                       dtype: str = "f32", block: int = DEFAULT_BLOCK,
                       rng=None):
    """The DDP bucket grad wire: partition `tree`'s leaves via
    grad_bucket_plan and run one bucket_all_reduce per bucket. Each
    bucket's exchange depends only on its own leaves' backward, so the
    collectives are mutually independent — the overlap the serial
    quantized_psum_tree (one payload after the whole backward) cannot
    express. Stochastic-rounding keys fold per bucket index so buckets
    never share rounding noise."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buckets = grad_bucket_plan(tree, n_buckets)
    out = list(leaves)
    for b, idxs in enumerate(buckets):
        flat = jnp.concatenate(
            [leaves[i].reshape(-1).astype(jnp.float32) for i in idxs]
        )
        b_rng = jax.random.fold_in(rng, b) if rng is not None else None
        red = bucket_all_reduce(flat, axis_name, world, dtype, block, b_rng)
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = red[off:off + n].reshape(leaves[i].shape).astype(
                leaves[i].dtype
            )
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _bucket_scatter_grads(g, axis_name: str, world: int, dims, dtype: str,
                          block: int, stochastic: bool):
    """Backward half of bucket_gather_qgrad: concatenate the bucket's
    cotangents (each the FULL gathered-shape grad) into one [world, n_c]
    payload, move it through ONE reduce-scatter-shaped all_to_all
    (packed at int8, raw rows at f32/bf16, f32 accumulation always), and
    split each leaf's shard back out. The per-element sum runs in fixed
    device order, so the f32 result is bit-identical under any bucket
    partition."""
    parts, metas = [], []
    for gi, dim in zip(g, dims):
        moved = jnp.moveaxis(gi, dim, 0)
        shard_shape = (moved.shape[0] // world,) + moved.shape[1:]
        parts.append(moved.astype(jnp.float32).reshape(world, -1))
        metas.append((shard_shape, dim))
    row = jnp.concatenate(parts, axis=1)  # [world, n_c]
    n_c = row.shape[1]
    if dtype == "int8":
        rng = _fallback_key(axis_name, row) if stochastic else None
        packed = pack_quantized(row, block, rng)
        if world > 1:
            packed = jax.lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
        red = jnp.sum(unpack_dequantized(packed, n_c, block), axis=0)
    else:
        payload = row if dtype == "f32" else row.astype(jnp.bfloat16)
        if world > 1:
            payload = jax.lax.all_to_all(payload, axis_name, 0, 0, tiled=True)
        red = jnp.sum(payload.astype(jnp.float32), axis=0)
    out, off = [], 0
    for gi, (shard_shape, dim) in zip(g, metas):
        n = 1
        for d in shard_shape:
            n *= d
        seg = red[off:off + n].reshape(shard_shape)
        out.append(jnp.moveaxis(seg, 0, dim).astype(gi.dtype))
        off += n
    return tuple(out)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def bucket_gather_qgrad(xs, axis_name: str, world: int, dims, dtype: str,
                        block: int, stochastic: bool):
    """FSDP gather-at-use for one BUCKET of sharded leaves: forward is
    the per-leaf FULL-PRECISION lax.all_gather (identical ops and bytes
    to the serial all_gather_qgrad path — params at use stay exact);
    backward compresses the bucket's cotangents through ONE packed
    reduce-scatter a2a instead of one per leaf. The vjp node consumes
    every leaf's cotangent at once, which in the backward sweep is the
    moment the bucket's LAST (earliest-layer) grad lands — exactly the
    "launch when the bucket's grads are ready" schedule. `dims` is the
    per-leaf sharded dimension (static)."""
    if world <= 1:
        return tuple(xs)
    return tuple(
        jax.lax.all_gather(x, axis_name, axis=d, tiled=True)
        for x, d in zip(xs, dims)
    )


def _bgq_fwd(xs, axis_name, world, dims, dtype, block, stochastic):
    return bucket_gather_qgrad(
        xs, axis_name, world, dims, dtype, block, stochastic
    ), None


def _bgq_bwd(axis_name, world, dims, dtype, block, stochastic, _, g):
    if world <= 1:
        return (tuple(g),)
    return (_bucket_scatter_grads(
        g, axis_name, world, dims, dtype, block, stochastic
    ),)


bucket_gather_qgrad.defvjp(_bgq_fwd, _bgq_bwd)


# -- closed-form expected bytes (the audit half) ----------------------------


def expected_all_reduce(n: int, world: int, dtype: str,
                        block: int = DEFAULT_BLOCK,
                        backend: str | None = None) -> dict | None:
    """Expected per-device HLO result-payload of one quantized two-shot
    all-reduce over `n` f32 elements: {op: {count, bytes}} for the compiled
    program — ONE all_to_all (the reduce-scatter phase) and ONE all_gather,
    both [world, row]. None when nothing is compressed (f32, or a 1-way
    axis where the wrappers skip the collectives)."""
    if dtype == "f32" or world <= 1:
        return None
    chunk = _ceil_to(max(n, 1), world) // world
    if dtype == "int8":
        row = packed_bytes(chunk, block)
    else:
        row = chunk * wire_itemsize("bf16", backend)
    return {
        "all-to-all": {"count": 1, "bytes": world * row},
        "all-gather": {"count": 1, "bytes": world * row},
    }


def expected_reduce_scatter(n: int, world: int, dtype: str,
                            block: int = DEFAULT_BLOCK,
                            backend: str | None = None) -> dict | None:
    """Expected result-payload of ONE quantized reduce-scatter over an
    `n`-element leaf (the FSDP grad wire): one all_to_all of [world, row]."""
    if dtype == "f32" or world <= 1:
        return None
    n_c = -(-n // world)
    if dtype == "int8":
        row = packed_bytes(n_c, block)
    else:
        row = n_c * wire_itemsize("bf16", backend)
    return {"all-to-all": {"count": 1, "bytes": world * row}}


def _bucket_row_bytes(n_c: int, dtype: str, block: int,
                      backend: str | None) -> int:
    """Wire bytes of one per-destination row covering n_c f32 elements at
    the bucket payload dtype (f32 rows travel raw — the f32 bucket
    schedule keeps the two-shot shape)."""
    if dtype == "int8":
        return packed_bytes(n_c, block)
    if dtype == "bf16":
        return n_c * wire_itemsize("bf16", backend)
    return n_c * 4


def expected_bucketed_all_reduce(sizes, world: int, dtype: str,
                                 block: int = DEFAULT_BLOCK,
                                 backend: str | None = None) -> dict | None:
    """Expected per-device HLO result payload of the DDP bucket wire:
    `sizes` = element count per bucket (from grad_bucket_plan) — one
    two-shot exchange each, so len(sizes) all_to_alls + all_gathers of
    [world, row]. Unlike expected_all_reduce this prices f32 too: the
    bucket schedule keeps the two-shot shape at every dtype."""
    sizes = [s for s in sizes if s > 0]
    if not sizes or world <= 1:
        return None
    a2a = ag = 0
    for n in sizes:
        chunk = _ceil_to(max(n, 1), world) // world
        row = _bucket_row_bytes(chunk, dtype, block, backend)
        a2a += world * row
        ag += world * row
    return {
        "all-to-all": {"count": len(sizes), "bytes": a2a},
        "all-gather": {"count": len(sizes), "bytes": ag},
    }


def expected_bucketed_reduce_scatter(sizes, world: int, dtype: str,
                                     block: int = DEFAULT_BLOCK,
                                     backend: str | None = None) -> dict | None:
    """Expected result payload of the FSDP bucket grad wire: `sizes` =
    TOTAL element count per bucket (sum of the bucket's leaf sizes, every
    leaf's sharded dim dividing `world`) — one packed reduce-scatter
    all_to_all of [world, row] per bucket."""
    sizes = [s for s in sizes if s > 0]
    if not sizes or world <= 1:
        return None
    total = 0
    for n in sizes:
        n_c = n // world
        total += world * _bucket_row_bytes(n_c, dtype, block, backend)
    return {"all-to-all": {"count": len(sizes), "bytes": total}}
