"""Causal multi-head attention core.

TPU-native twin of the attention math in reference `models/gpt.py:68-105`
(`SelfAttention.forward`). Behavioral parity with two deliberate divergences,
both flagged in the reference's own TODOs (`models/gpt.py:81-82`):

- The reference materializes a full `[N, h, S, S]` additive causal mask every
  forward (`1e9 * (tril(ones) - 1)` then `repeat`, models/gpt.py:83-88) —
  O(N*h*S^2) memory traffic. Here the causal constraint is a broadcast
  `jnp.where` over a `[S, S]` boolean, which XLA fuses into the logits
  computation; no mask tensor ever hits HBM.
- Softmax runs in float32 regardless of compute dtype (torch autocast does the
  same for `F.softmax`, which the reference relies on at models/gpt.py:97).

The padding mask convention is the reference's: `mask` is `[B, S]` boolean
with **True = masked**, applied key-side with the dtype's most-negative finite
value (`masked_fill(mask[:, None, None, :], finfo.min)`, models/gpt.py:93-95).

A fused Pallas flash-attention kernel (tpukit/ops/pallas_attention.py) can be
swapped in on TPU via `causal_attention(..., impl="flash")`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # twin of the reference's additive causal constant (models/gpt.py:83)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    pad_mask: jax.Array | None = None,
    impl: str = "xla",
    ring_axis: str = "seq",
    ring_layout: str = "contiguous",
) -> jax.Array:
    """Scaled dot-product causal attention.

    Args:
      q, k, v: `[B, heads, S, head_dim]`.
      scale: `1 / sqrt(head_dim)` (reference models/gpt.py:66).
      pad_mask: optional `[B, S]` bool, True = position is padding (masked).
      impl: "xla" (fused by the compiler) or "flash" (Pallas kernel on TPU).

    Returns `[B, heads, S, head_dim]` in the dtype of `v`.
    """
    if impl == "auto":
        # Measured on v5e: XLA's fused attention wins below ~512 tokens
        # (kernel grid overhead dominates tiny S x S); the flash kernel wins
        # from 512 up (+68% at S=1024, +130% at S=2048) and is the only
        # option at S >= 8k, where the materialized S x S no longer compiles.
        #
        # The kernel is safe in every sharded context: custom_partitioning
        # rules (tpukit/ops/pallas_attention.py) keep batch/head shardings
        # under GSPMD jit (DP/FSDP/TP), and pallas_call composes directly
        # with shard_map Manual regions (pipeline recipes).
        from tpukit.ops.pallas_attention import on_tpu_backend

        impl = "flash" if (on_tpu_backend() and q.shape[2] >= 512) else "xla"
    if impl == "flash":
        from tpukit.ops.pallas_attention import flash_causal_attention

        return flash_causal_attention(q, k, v, scale=scale, pad_mask=pad_mask)
    if impl == "ring":
        from tpukit.ring_attention import ring_causal_attention

        return ring_causal_attention(
            q, k, v, scale=scale, axis_name=ring_axis, pad_mask=pad_mask,
            layout=ring_layout,
        )
    if impl == "ulysses":
        from tpukit.ring_attention import ulysses_attention

        return ulysses_attention(
            q, k, v, scale=scale, axis_name=ring_axis, pad_mask=pad_mask
        )

    seq_len = q.shape[2]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale

    causal = jnp.tril(jnp.ones((seq_len, seq_len), dtype=jnp.bool_))
    logits = logits + jnp.where(causal, 0.0, NEG_INF).astype(logits.dtype)[None, None]

    if pad_mask is not None:
        logits = jnp.where(
            pad_mask[:, None, None, :],
            jnp.finfo(logits.dtype).min,
            logits,
        )

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
