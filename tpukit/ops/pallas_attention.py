"""Fused causal flash attention (Pallas TPU kernels, FlashAttention-2 style).

Replaces the O(S^2)-memory attention of the reference (`models/gpt.py:79-99`
materializes the full `[B, h, S, S]` score tensor; its own TODO at
models/gpt.py:81-82 flags the cost). These kernels stream K/V blocks through
VMEM with an online softmax, so no S x S tensor ever touches HBM — forward
writes only the output and a log-sum-exp vector; the backward is ONE fused
kernel that recomputes each score block once and emits dk/dv (VMEM-scratch
accumulated) plus per-k-block dq partials (see _bwd_kernel).

Masking semantics mirror tpukit/ops/attention.py (and therefore the
reference): causally-forbidden entries are suppressed (select to -1e9) and
the padding mask adds a float32 finfo.min bias to key columns, so a
fully-padded query row softmaxes uniformly rather than NaN-ing (see
_masked_scores for the exact-equivalence argument). One documented
divergence: for a *fully padded* query row the XLA path attends uniformly
over all S positions (the reference's masked_fill overwrites the causal
term, models/gpt.py:90-95) while the kernel attends uniformly over j <= i;
such rows carry ignore-index targets and never affect the loss.

Layout: grid (batch*heads, q_blocks, k_blocks) with the k dimension
innermost; running (m, l, acc) state lives in VMEM scratch across k steps
(TPU grids execute sequentially). Causally-skipped blocks are gated with
`pl.when` and their K/V fetches are clamped to the diagonal block so no
wasted HBM traffic occurs. Per-row vectors ride in Mosaic-friendly 2-D
layouts as LANE ROWS: the padding bias [B, 1, S_pad], log-sum-exp and the
dO.O row sums [BH, 1, S_pad] — a [BH, S_pad, 1] column would get its minor
dim padded to 128 lanes in HBM, a 128x memory/traffic expansion (same
reasoning as fused_head_ce's row vectors); rows are reshaped to (BQ, 1)
columns in VMEM where the math needs them. Every ref read/write stays
rank>=2 (rank-1 slices crash the Mosaic layout pass), and block shapes are
(8, 128)-tile aligned or span their dimension.
Sequence lengths are padded to the lane boundary in the wrapper; padded key
columns are unreachable causally and padded query rows are sliced off.

On non-TPU backends the same kernels run in Pallas interpreter mode, which
keeps the unit tests (tests/test_flash_attention.py) exercising the exact
kernel code path on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.experimental.pallas import tpu as pltpu

from tpukit.compat import def_partition as compat_def_partition

NEG_INF = -1e9  # causal additive term (twin of models/gpt.py:83)

_LANES = 128
# Score-block edge. Sweepable via env. 1024 measured fastest at S=2048 on
# v5e in round 4 (tools/ablate_r4.py, full-train-step timing: 101.5 ms vs
# 107.3 at 2048 and 126.0 at 512): at 2048 the whole sequence is ONE block,
# so the causal skip saves nothing and the kernel computes the full S^2;
# at 1024 the 2x2 grid skips one of four blocks; below that per-grid-step
# overhead outweighs the extra causal savings.
_BLOCK = max(_LANES, int(os.environ.get("TPUKIT_FLASH_BLOCK", "1024")))


def on_tpu_backend() -> bool:
    """Single source of truth for "is this a TPU-like backend" — shared with
    the auto-dispatch in tpukit/ops/attention.py so the two cannot drift."""
    return jax.default_backend() in ("tpu", "axon")


def _interpret() -> bool:
    return not on_tpu_backend()


def tpu_compiler_params(*dimension_semantics: str):
    """Shared CompilerParams for every tpukit Pallas kernel (None in
    interpreter mode): one place to tune the VMEM budget, imported by
    fused_head_ce too."""
    if _interpret():
        return None
    return pltpu.CompilerParams(
        vmem_limit_bytes=100 * 1024 * 1024,
        dimension_semantics=dimension_semantics,
    )


def online_softmax_update(m_prev, l_prev, s):
    """THE one spelling of the flash-attention running-max/renormalize
    update, shared by the training kernels here and the paged decode
    kernel (tpukit/ops/paged_attention.py) so the two cannot drift
    (lint_invariants rule `online-softmax-spelling` pins every other
    `maximum(m, max(s))` occurrence to this owner).

    `m_prev`/`l_prev`: `[rows, 1]` f32 running max / normalizer (init
    `-inf` / `0`); `s`: `[rows, cols]` f32 scores for the incoming block.
    Returns `(m_new, l_new, correction, p)` where `correction` rescales
    any accumulator built under `m_prev` and `p = exp(s - m_new)` is the
    block's unnormalized probabilities. A single call over the FULL score
    row degenerates to the plain softmax exactly: `maximum(-inf, max(s))`
    is the true max and `l_new = 0 * exp(-inf) + sum(p) = sum(p)` — the
    exactness argument the paged kernel's bit-parity bar rides."""
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, l_new, correction, p


def _plan(seq: int) -> tuple[int, int]:
    """(block, seq_pad) for a given sequence length. Mosaic requires the
    score-block edge and the padded sequence to be lane-aligned: for
    seq >= 128 both are 128-multiples (a 16-rounded block at e.g. S=520
    fails lowering with a non-128-aligned pl.ds slice); shorter sequences
    use a single 16-aligned block, which satisfies the sublane rule."""
    if seq >= _LANES:
        block = min(_BLOCK, -(-seq // _LANES) * _LANES)
    else:
        block = -(-seq // 16) * 16
    seq_pad = -(-seq // block) * block
    assert block % (16 if seq < _LANES else _LANES) == 0 and seq_pad % block == 0
    return block, seq_pad


def _masked_scores(q_blk, k_blk, bias_ref, qi, ki, block_q, block_k, has_mask):
    """[BQ, BK] float32 scores with causal + padding masks applied.

    The kernels are VPU-bound at small head_dim (the two matmuls have K or
    N = head_dim, a fraction of the MXU, while every mask/softmax op sweeps
    the full BQ x BK block), so this routine minimizes elementwise passes:

      - `scale` is folded into q by the wrappers (zero passes here);
      - the causal select compares LOCAL iotas against the block-offset
        difference (off-diagonal lower blocks reduce to an always-true
        compare the VPU predicates cheaply; a measured lax.cond variant
        that skipped them entirely was SLOWER — the conditional copies the
        4MB score block through both branches);
      - padding is one broadcast ADD of a precomputed float32 bias row
        (0 or finfo.min), not an int compare + select, and is compiled out
        entirely when the caller passed no mask (`has_mask` static).
    Ablations on v5e show the kernel is MXU-latency-bound (the matmuls'
    K or N = head_dim fills 1/4 of the array): mask/exp/reduction passes
    overlap with the MXU and cost ~nothing, so this routine optimizes for
    fewer serialized VPU passes, not minimum arithmetic.

    Numerics equivalence with the old compare/overwrite form: a bias of
    finfo.min sends exp() to exactly 0.0 in float32 (so padded columns get
    exact-zero probability AND exact-zero ds in the backward, which is why
    the backward needs no explicit pad zeroing), and finfo.min + NEG_INF
    rounds back to finfo.min (ulp at 3.4e38 is ~2e31), preserving the
    fully-padded-row uniform-softmax behavior documented above.
    """
    s = jax.lax.dot_general(
        q_blk,
        k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    # causal: global col <= global row  <=>  local c - local r <= (qi-ki)*B
    # (with square aligned blocks); for strictly-lower blocks the RHS >= B
    # makes this always-true — one compare+select, no conditionals
    assert block_q == block_k, "local-iota causal form needs square blocks"
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    s = jnp.where(cols - rows <= (qi - ki) * block_k, s, NEG_INF)
    if has_mask:
        s = s + bias_ref[0, :, pl.ds(ki * block_k, block_k)]  # (1, BK) f32
    return s


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(mask_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, block_q, block_k, num_k, has_mask):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki <= qi)
    def _():
        q_blk = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = _masked_scores(q_blk, k_blk, mask_ref, qi, ki, block_q, block_k, has_mask)

        m_prev = m_scr[:, :1]  # (BQ, 1)
        l_prev = l_scr[:, :1]
        m_new, l_new, correction, p = online_softmax_update(m_prev, l_prev, s)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v_blk.dtype),
            v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k - 1)
    def _():
        l = l_scr[:, :1]  # (BQ, 1)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, :, pl.ds(qi * block_q, block_q)] = jnp.reshape(
            m_scr[:, :1] + jnp.log(l), (1, block_q)
        )


def _flash_forward(q3, k3, v3, bias2, heads, has_mask):
    """q3 (PRESCALED)/k3/v3: [BH, S_pad, d]; bias2: [B, 1, S_pad] f32
    additive pad bias. Returns (out [BH, S_pad, d], lse [BH, S_pad, 1])."""
    bh, seq_pad, head_dim = q3.shape
    block_q = block_k = min(_BLOCK, seq_pad) if seq_pad >= _LANES else seq_pad
    num_q, num_k = seq_pad // block_q, seq_pad // block_k

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, num_k=num_k, has_mask=has_mask
    )
    # K/V fetches for causally-skipped blocks are clamped to the diagonal.
    kv_index = lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0)
    return pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, seq_pad), lambda b, qi, ki: (b // heads, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, seq_pad), lambda b, qi, ki: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        compiler_params=tpu_compiler_params("parallel", "arbitrary", "arbitrary"),
        interpret=_interpret(),
    )(bias2, q3, k3, v3)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block_q, block_k, num_q, has_mask):
    """Fused backward: ONE score/probability recomputation per (ki, qi)
    block pair yields dv and dk (accumulated in VMEM scratch over the inner
    qi sweep) AND this pair's dq contribution. dq needs accumulation across
    the OUTER ki axis, which VMEM scratch cannot provide (output blocks may
    only be revisited in consecutive grid steps), so per-ki partials go to
    a [num_k]-extended output that XLA reduces afterwards — trading a tiny
    HBM write for recomputing scores a second time (the previous dq/dkv
    split did exactly double score work).

    Note q arrives PRESCALED by `scale` (see _masked_scores): dk = ds'q
    needs no scale factor (q carries it), while dq = ds'k is a gradient
    w.r.t. the ORIGINAL q, so the chain rule through q*scale applies scale
    once here. Padded columns need no explicit zeroing: their probability
    is exp(finfo.min - lse) == 0.0 exactly, so ds is already zero there.
    """
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= ki)
    def _():
        q_blk, k_blk, v_blk = q_ref[0], k_ref[0], v_ref[0]
        do_blk = do_ref[0].astype(jnp.float32)
        s = _masked_scores(q_blk, k_blk, mask_ref, qi, ki, block_q, block_k, has_mask)
        lse_col = jnp.reshape(
            lse_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        dcap_col = jnp.reshape(
            dcap_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        p = jnp.exp(s - lse_col)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_blk.dtype),
            do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dcap_col)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_blk.dtype),
            q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dq partials stay f32 until the cross-block sum: rounding each
        # partial to bf16 first would give SHORT sequences worse dq
        # precision than the split path's single-rounding scratch
        dqp_ref[0, 0] = scale * jax.lax.dot_general(
            ds.astype(k_blk.dtype),
            k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi < ki)
    def _():
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# Fused-backward gates. The fused kernel writes an f32 dq-partials buffer
# of bh x num_k x S_pad x d (= 2*num_k times the bf16 q tensor) — measured
# ~13% faster than the split backward at S=8192/b=4 on v5e, but its size
# scales as S^2/block, so it is gated BOTH on a k-block cap and on the
# buffer's actual bytes (batch-aware): past either limit the split
# two-kernel backward — double score recompute, zero extra HBM — takes
# over. Sweepable: TPUKIT_FLASH_DQ_PARTIALS_MB.
_DQ_FUSED_MAX_NUM_K = 4
_DQ_PARTIALS_BUDGET = (
    int(os.environ.get("TPUKIT_FLASH_DQ_PARTIALS_MB", "256")) * 1024 * 1024
)


def _dq_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dq_ref, dq_scr, *, scale, block_q, block_k, num_k, has_mask):
    """Long-sequence dq: grid (bh, num_q, num_k) with ki INNER, so dq
    accumulates in VMEM scratch — no [num_k]-extended partials (see
    _flash_backward's size gate). Scores are recomputed a second time
    relative to the fused kernel; at num_k > _DQ_FUSED_MAX_NUM_K the saved
    HBM traffic pays for it."""
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(ki <= qi)
    def _():
        q_blk, k_blk, v_blk = q_ref[0], k_ref[0], v_ref[0]
        do_blk = do_ref[0].astype(jnp.float32)
        s = _masked_scores(q_blk, k_blk, mask_ref, qi, ki, block_q, block_k, has_mask)
        lse_col = jnp.reshape(
            lse_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        dcap_col = jnp.reshape(
            dcap_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        p = jnp.exp(s - lse_col)
        dp = jax.lax.dot_general(
            do_blk,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dcap_col)
        dq_scr[:] += scale * jax.lax.dot_general(
            ds.astype(k_blk.dtype),
            k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, block_q, block_k, num_q, has_mask):
    """Long-sequence dk/dv: the fused kernel minus the dq-partials output
    (same scratch accumulation over the inner qi sweep)."""
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= ki)
    def _():
        q_blk, k_blk, v_blk = q_ref[0], k_ref[0], v_ref[0]
        do_blk = do_ref[0].astype(jnp.float32)
        s = _masked_scores(q_blk, k_blk, mask_ref, qi, ki, block_q, block_k, has_mask)
        lse_col = jnp.reshape(
            lse_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        dcap_col = jnp.reshape(
            dcap_ref[0, :, pl.ds(qi * block_q, block_q)], (block_q, 1)
        )
        p = jnp.exp(s - lse_col)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_blk.dtype),
            do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dcap_col)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_blk.dtype),
            q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward_split(q3, k3, v3, bias2, lse, do3, dcap, scale, heads, has_mask, block_q, block_k):
    """Two-kernel backward for long sequences: no dq partials in HBM (the
    fused path's num_k x |q| buffer is S^2-scaled), at the cost of one
    extra score recompute per block pair."""
    bh, seq_pad, head_dim = q3.shape
    num_q, num_k = seq_pad // block_q, seq_pad // block_k

    mask_spec = pl.BlockSpec((1, 1, seq_pad), lambda b, i, j: (b // heads, 0, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, 1, seq_pad), lambda b, i, j: (b, 0, 0), memory_space=pltpu.VMEM)
    cparams = tpu_compiler_params("parallel", "arbitrary", "arbitrary")

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            num_k=num_k, has_mask=has_mask,
        ),
        grid=(bh, num_q, num_k),
        in_specs=[
            mask_spec,
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            col_spec,
            col_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=cparams,
        interpret=_interpret(),
    )(bias2, q3, k3, v3, do3, lse, dcap)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k, num_q=num_q,
            has_mask=has_mask,
        ),
        grid=(bh, num_k, num_q),
        in_specs=[
            mask_spec,
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            col_spec,
            col_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=cparams,
        interpret=_interpret(),
    )(bias2, q3, k3, v3, do3, lse, dcap)

    return dq, dk, dv


def _flash_backward(q3, k3, v3, bias2, out, lse, do3, scale, heads, has_mask):
    """q3 arrives PRESCALED. One fused kernel (see _bwd_kernel) produces
    dk/dv plus per-ki dq partials; the [num_k] partial axis is summed here
    (a cheap XLA reduction over 2-4 slices at practical block sizes).
    Past _DQ_FUSED_MAX_NUM_K k-blocks the partials would scale as S^2/block
    — the split backward takes over (no extra HBM, double score work)."""
    bh, seq_pad, head_dim = q3.shape
    block_q = block_k = min(_BLOCK, seq_pad) if seq_pad >= _LANES else seq_pad
    num_q, num_k = seq_pad // block_q, seq_pad // block_k

    # D_i = rowsum(dO * O) — cheap, computed outside the kernels. Stored
    # as a [BH, 1, S_pad] lane-row: a [BH, S_pad, 1] column would have
    # its minor dim padded to 128 lanes in HBM (a 128x memory/traffic
    # expansion — same reasoning as fused_head_ce's row vectors).
    dcap = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[:, None, :]

    dq_partials_bytes = bh * num_k * seq_pad * head_dim * 4
    if num_k > _DQ_FUSED_MAX_NUM_K or dq_partials_bytes > _DQ_PARTIALS_BUDGET:
        return _flash_backward_split(
            q3, k3, v3, bias2, lse, do3, dcap, scale, heads, has_mask,
            block_q, block_k,
        )

    mask_spec = pl.BlockSpec((1, 1, seq_pad), lambda b, i, j: (b // heads, 0, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, 1, seq_pad), lambda b, i, j: (b, 0, 0), memory_space=pltpu.VMEM)

    dq_part, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
            num_q=num_q, has_mask=has_mask,
        ),
        grid=(bh, num_k, num_q),
        in_specs=[
            mask_spec,
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            col_spec,
            col_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), lambda b, ki, qi: (b, ki, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, num_k, seq_pad, head_dim), jnp.float32),
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=tpu_compiler_params("parallel", "arbitrary", "arbitrary"),
        interpret=_interpret(),
    )(bias2, q3, k3, v3, do3, lse, dcap)

    dq = jnp.sum(dq_part, axis=1).astype(q3.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# 4-D entry points (batch and head dims kept separate so GSPMD can shard
# them), wrapped in custom_partitioning: under a DP/FSDP/TP-sharded trace the
# kernel runs on each device's local [B/n, h, S, d] shard — attention is
# independent per (batch, head), so batch/head partitioning needs no
# collectives at all. This is the capability VERDICT r1 called out: without
# it, exactly the sharded configs the baseline ladder cares about fell back
# to materialized-mask attention.
# ---------------------------------------------------------------------------


def _pad_bias(mask, seq_pad):
    """[B, S] int (1 = padding) -> [B, 1, S_pad] f32 additive bias row."""
    bias = jnp.where(
        mask != 0, jnp.finfo(jnp.float32).min, 0.0
    ).astype(jnp.float32)
    return jnp.pad(bias, ((0, 0), (0, seq_pad - mask.shape[1])))[:, None, :]


def _fwd4_impl(q, k, v, mask, scale, heads, has_mask):
    """q/k/v: [B, h, S, d]; mask: [B, S] int32 (1 = padding; ignored when
    has_mask is False). Returns (out [B, h, S, d], lse [B, h, S, 1])."""
    batch, h, seq, head_dim = q.shape
    _, seq_pad = _plan(seq)

    def prep(t):
        t = t.reshape(batch * h, seq, head_dim)
        return jnp.pad(t, ((0, 0), (0, seq_pad - seq), (0, 0)))

    bias2 = _pad_bias(mask, seq_pad)
    # scale folded into q: one cheap [B,h,S,d] multiply (usually fused into
    # the producing matmul) replaces a full [BQ,BK] pass per score block
    out, lse = _flash_forward(prep(q * scale), prep(k), prep(v), bias2, h, has_mask)
    return (
        out[:, :seq].reshape(batch, h, seq, head_dim),
        lse[:, 0, :seq].reshape(batch, h, seq, 1),
    )


def _bwd4_impl(q, k, v, mask, out, lse, do, scale, heads, has_mask):
    batch, h, seq, head_dim = q.shape
    _, seq_pad = _plan(seq)

    def prep(t):
        t = t.reshape(batch * h, seq, head_dim)
        return jnp.pad(t, ((0, 0), (0, seq_pad - seq), (0, 0)))

    bias2 = _pad_bias(mask, seq_pad)
    # padded lse rows must stay out of exp(): -inf would NaN; any finite
    # value is unused because padded query rows are sliced off below
    lse3 = jnp.pad(
        lse.reshape(batch * h, seq), ((0, 0), (0, seq_pad - seq))
    )[:, None, :]
    dq, dk, dv = _flash_backward(
        prep(q * scale), prep(k), prep(v), bias2, prep(out), lse3, prep(do),
        scale, h, has_mask,
    )

    def unprep(t):
        return t[:, :seq].reshape(batch, h, seq, head_dim)

    return unprep(dq), unprep(dk), unprep(dv)


def _batch_head_spec(sharding, ndim):
    """Partition spec keeping only batch(0)/head(1) shardings; S and
    head_dim must be whole on every device for the kernel math. Dropping a
    sequence/head_dim sharding means GSPMD will all-gather those axes per
    device — a silent memory/perf cliff for context-sharded configs, so it
    warns (seq sharding belongs on the ring-attention path, not here)."""
    from jax.sharding import PartitionSpec as P

    if sharding is None or not hasattr(sharding, "spec"):
        return P()
    spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
    if any(spec[2:ndim]):
        import warnings

        warnings.warn(
            f"flash attention: input sharded over sequence/head_dim "
            f"({sharding.spec}); the kernel keeps those axes whole per "
            f"device, so GSPMD will all-gather them (replicating S per "
            f"device). Use attention_impl='ring' (ContextParallel) for "
            f"sequence sharding.",
            stacklevel=2,
        )
    return P(*(tuple(spec[: min(2, ndim)]) + (None,) * (ndim - 2)))


def _operand_spec(info, spec, mask_spec, lse_spec):
    """Per-operand spec: [B,S] masks shard on batch only; [...,1] lse columns
    shard on batch/head; q/k/v/out/do take the full batch/head spec."""
    if len(info.shape) == 2:
        return mask_spec
    if info.shape[-1] == 1:
        return lse_spec
    return spec


def _make_partition(impl, n_out):
    """partition/infer callbacks for custom_partitioning. With static_argnums
    the callbacks receive (statics..., mesh, arg_infos, result_infos)."""

    def specs(mesh, arg_infos):
        from jax.sharding import PartitionSpec as P

        spec = _batch_head_spec(arg_infos[0].sharding, 4)
        mask_spec = P(spec[0], None)
        lse_spec = P(spec[0], spec[1], None, None)
        return spec, mask_spec, lse_spec

    def partition(scale, heads, has_mask, mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding

        spec, mask_spec, lse_spec = specs(mesh, arg_infos)
        arg_sh = tuple(
            NamedSharding(mesh, _operand_spec(a, spec, mask_spec, lse_spec))
            for a in arg_infos
        )
        outs = [spec, lse_spec] if n_out == 2 else [spec] * n_out
        out_sh = tuple(NamedSharding(mesh, s) for s in outs)

        def lower(*operands):
            return impl(*operands, scale, heads, has_mask)

        return mesh, lower, out_sh, arg_sh

    def infer(scale, heads, has_mask, mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding

        spec, _, lse_spec = specs(mesh, arg_infos)
        outs = [spec, lse_spec] if n_out == 2 else [spec] * n_out
        return tuple(NamedSharding(mesh, s) for s in outs)

    return partition, infer


_fwd4 = custom_partitioning(_fwd4_impl, static_argnums=(4, 5, 6))
_fwd4_partition, _fwd4_infer = _make_partition(_fwd4_impl, 2)
compat_def_partition(_fwd4, 
    partition=_fwd4_partition,
    infer_sharding_from_operands=_fwd4_infer,
    # b (batch) and h (heads) are shardable; s/d must stay whole per device
    sharding_rule="b h s d, b h s d, b h s d, b s -> b h s d, b h s z",
)

_bwd4 = custom_partitioning(_bwd4_impl, static_argnums=(7, 8, 9))
_bwd4_partition, _bwd4_infer = _make_partition(_bwd4_impl, 3)
compat_def_partition(_bwd4, 
    partition=_bwd4_partition,
    infer_sharding_from_operands=_bwd4_infer,
    sharding_rule=(
        "b h s d, b h s d, b h s d, b s, b h s d, b h s z, b h s d "
        "-> b h s d, b h s d, b h s d"
    ),
)


# ---------------------------------------------------------------------------
# custom_vjp wrapper (differentiation sits OUTSIDE the partitioned ops:
# custom_partitioning has no autodiff rule, so fwd and bwd are each their
# own partitioned computation)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, mask, scale, heads, has_mask):
    out, _ = _fwd4(q, k, v, mask, scale, heads, has_mask)
    return out


def _flash_fwd(q, k, v, mask, scale, heads, has_mask):
    out, lse = _fwd4(q, k, v, mask, scale, heads, has_mask)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(scale, heads, has_mask, residuals, g):
    q, k, v, mask, out, lse = residuals
    dq, dk, dv = _bwd4(q, k, v, mask, out, lse, g, scale, heads, has_mask)
    dmask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_causal_attention(q, k, v, *, scale, pad_mask=None):
    """Drop-in for the XLA path in tpukit/ops/attention.py.

    q, k, v: [B, heads, S, head_dim]; pad_mask: optional [B, S] bool
    (True = padding). Returns [B, heads, S, head_dim] in v's dtype.

    GSPMD-aware: under a sharded jit trace the custom_partitioning rules
    keep batch/head shardings and run the kernel per-shard (DP/FSDP/TP all
    shard only those dims); S and head_dim stay whole per device.
    """
    batch, heads, seq, head_dim = q.shape
    if pad_mask is None:
        # has_mask=False compiles the pad-bias pass out of the kernels; the
        # dummy mask still rides along so the operand list (and its GSPMD
        # partitioning rule) is identical in both modes
        mask = jnp.zeros((batch, seq), jnp.int32)
        return _flash(q, k, v, mask, scale, heads, False)
    return _flash(q, k, v, pad_mask.astype(jnp.int32), scale, heads, True)
