"""Fused causal flash attention (Pallas TPU kernels, FlashAttention-2 style).

Replaces the O(S^2)-memory attention of the reference (`models/gpt.py:79-99`
materializes the full `[B, h, S, S]` score tensor; its own TODO at
models/gpt.py:81-82 flags the cost). These kernels stream K/V blocks through
VMEM with an online softmax, so no S x S tensor ever touches HBM — forward
writes only the output and a log-sum-exp vector, and the backward kernels
recompute scores blockwise.

Masking semantics mirror tpukit/ops/attention.py (and therefore the
reference) exactly: the causal constraint is a -1e9 additive term and the
padding mask overwrites key columns with float32 finfo.min afterwards, so a
fully-padded query row softmaxes uniformly rather than NaN-ing. One
documented divergence: for a *fully padded* query row the XLA path attends
uniformly over all S positions (the reference's masked_fill overwrites the
causal term, models/gpt.py:90-95) while the kernel attends uniformly over
j <= i; such rows carry ignore-index targets and never affect the loss.

Layout: grid (batch*heads, q_blocks, k_blocks) with the k dimension
innermost; running (m, l, acc) state lives in VMEM scratch across k steps
(TPU grids execute sequentially). Causally-skipped blocks are gated with
`pl.when` and their K/V fetches are clamped to the diagonal block so no
wasted HBM traffic occurs. Per-row vectors ride in Mosaic-friendly 2-D
layouts: the padding mask as a [B, 1, S_pad] row, log-sum-exp and the dO.O
row sums as [BH, S_pad, 1] columns — every ref read/write stays rank>=2
(rank-1 slices crash the Mosaic layout pass), and block shapes are
(8, 128)-tile aligned or span their dimension.
Sequence lengths are padded to the lane boundary in the wrapper; padded key
columns are unreachable causally and padded query rows are sliced off.

On non-TPU backends the same kernels run in Pallas interpreter mode, which
keeps the unit tests (tests/test_flash_attention.py) exercising the exact
kernel code path on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9  # causal additive term (twin of models/gpt.py:83)

_LANES = 128
# Score-block edge. Bigger blocks amortize grid overhead at long sequence
# lengths; sweepable via env for tuning.
_BLOCK = max(_LANES, int(os.environ.get("TPUKIT_FLASH_BLOCK", "1024")))


def on_tpu_backend() -> bool:
    """Single source of truth for "is this a TPU-like backend" — shared with
    the auto-dispatch in tpukit/ops/attention.py so the two cannot drift."""
    return jax.default_backend() in ("tpu", "axon")


def _interpret() -> bool:
    return not on_tpu_backend()


def _plan(seq: int) -> tuple[int, int]:
    """(block, seq_pad) for a given sequence length. Mosaic requires the
    score-block edge and the padded sequence to be lane-aligned: for
    seq >= 128 both are 128-multiples (a 16-rounded block at e.g. S=520
    fails lowering with a non-128-aligned pl.ds slice); shorter sequences
    use a single 16-aligned block, which satisfies the sublane rule."""
    if seq >= _LANES:
        block = min(_BLOCK, -(-seq // _LANES) * _LANES)
    else:
        block = -(-seq // 16) * 16
    seq_pad = -(-seq // block) * block
    assert block % (16 if seq < _LANES else _LANES) == 0 and seq_pad % block == 0
    return block, seq_pad


def _masked_scores(q_blk, k_blk, mask_ref, scale, qi, ki, block_q, block_k):
    """[BQ, BK] float32 scores with causal + padding masks applied, matching
    the XLA path's order of operations. `mask_ref` is the [1, 1, S_pad] int32
    padding-row ref; the ki-th block is sliced at the ref level as (1, BK)."""
    s = jax.lax.dot_general(
        q_blk,
        k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    s = s + jnp.where(cols <= rows, 0.0, NEG_INF)
    pad = mask_ref[0, :, pl.ds(ki * block_k, block_k)] == 1  # (1, BK)
    return jnp.where(pad, jnp.finfo(jnp.float32).min, s), pad


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(mask_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, scale, block_q, block_k, num_k):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki <= qi)
    def _():
        q_blk = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s, _ = _masked_scores(q_blk, k_blk, mask_ref, scale, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :1]  # (BQ, 1)
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v_blk.dtype),
            v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k - 1)
    def _():
        l = l_scr[:, :1]  # (BQ, 1)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, pl.ds(qi * block_q, block_q), :] = m_scr[:, :1] + jnp.log(l)


def _flash_forward(q3, k3, v3, mask2, scale, heads):
    """q3/k3/v3: [BH, S_pad, d]; mask2: [B, 1, S_pad] int32.
    Returns (out [BH, S_pad, d], lse [BH, S_pad, 1])."""
    bh, seq_pad, head_dim = q3.shape
    block_q = block_k = min(_BLOCK, seq_pad) if seq_pad >= _LANES else seq_pad
    num_q, num_k = seq_pad // block_q, seq_pad // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, num_k=num_k
    )
    # K/V fetches for causally-skipped blocks are clamped to the diagonal.
    kv_index = lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0)
    return pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, seq_pad), lambda b, qi, ki: (b // heads, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), kv_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), kv_index, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_pad, 1), lambda b, qi, ki: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, seq_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=_interpret(),
    )(mask2, q3, k3, v3)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dq_ref, dq_scr, *, scale, block_q, block_k, num_k):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(ki <= qi)
    def _():
        q_blk, k_blk, v_blk = q_ref[0], k_ref[0], v_ref[0]
        do_blk = do_ref[0].astype(jnp.float32)
        s, pad = _masked_scores(q_blk, k_blk, mask_ref, scale, qi, ki, block_q, block_k)
        lse_col = lse_ref[0, pl.ds(qi * block_q, block_q), :]  # (BQ, 1)
        dcap_col = dcap_ref[0, pl.ds(qi * block_q, block_q), :]
        p = jnp.exp(s - lse_col)
        dp = jax.lax.dot_general(
            do_blk,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dcap_col)
        ds = jnp.where(pad, 0.0, ds)  # the where() in the fwd blocks grads
        dq_scr[:] += scale * jax.lax.dot_general(
            ds.astype(k_blk.dtype),
            k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale, block_q, block_k, num_q):
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(qi >= ki)
    def _():
        q_blk, k_blk, v_blk = q_ref[0], k_ref[0], v_ref[0]
        do_blk = do_ref[0].astype(jnp.float32)
        s, pad = _masked_scores(q_blk, k_blk, mask_ref, scale, qi, ki, block_q, block_k)
        lse_col = lse_ref[0, pl.ds(qi * block_q, block_q), :]  # (BQ, 1)
        dcap_col = dcap_ref[0, pl.ds(qi * block_q, block_q), :]
        p = jnp.exp(s - lse_col)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_blk.dtype),
            do_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk,
            v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dcap_col)
        ds = jnp.where(pad, 0.0, ds)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds.astype(q_blk.dtype),
            q_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q3, k3, v3, mask2, out, lse, do3, scale, heads):
    bh, seq_pad, head_dim = q3.shape
    block_q = block_k = min(_BLOCK, seq_pad) if seq_pad >= _LANES else seq_pad
    num_q, num_k = seq_pad // block_q, seq_pad // block_k

    # D_i = rowsum(dO * O) — cheap, computed outside the kernels.
    dcap = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True)

    mask_spec = pl.BlockSpec((1, 1, seq_pad), lambda b, i, j: (b // heads, 0, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, seq_pad, 1), lambda b, i, j: (b, 0, 0), memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_q=block_q, block_k=block_k, num_k=num_k),
        grid=(bh, num_q, num_k),
        in_specs=[
            mask_spec,
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, qi, ki: (b, jnp.minimum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            col_spec,
            col_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=_interpret(),
    )(mask2, q3, k3, v3, do3, lse, dcap)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k, num_q=num_q),
        grid=(bh, num_k, num_q),
        in_specs=[
            mask_spec,
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, head_dim), lambda b, ki, qi: (b, jnp.maximum(qi, ki), 0), memory_space=pltpu.VMEM),
            col_spec,
            col_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, head_dim), lambda b, ki, qi: (b, ki, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=_interpret(),
    )(mask2, q3, k3, v3, do3, lse, dcap)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# 4-D entry points (batch and head dims kept separate so GSPMD can shard
# them), wrapped in custom_partitioning: under a DP/FSDP/TP-sharded trace the
# kernel runs on each device's local [B/n, h, S, d] shard — attention is
# independent per (batch, head), so batch/head partitioning needs no
# collectives at all. This is the capability VERDICT r1 called out: without
# it, exactly the sharded configs the baseline ladder cares about fell back
# to materialized-mask attention.
# ---------------------------------------------------------------------------


def _fwd4_impl(q, k, v, mask, scale, heads):
    """q/k/v: [B, h, S, d]; mask: [B, S] int32 (1 = padding).
    Returns (out [B, h, S, d], lse [B, h, S, 1])."""
    batch, h, seq, head_dim = q.shape
    _, seq_pad = _plan(seq)

    def prep(t):
        t = t.reshape(batch * h, seq, head_dim)
        return jnp.pad(t, ((0, 0), (0, seq_pad - seq), (0, 0)))

    mask2 = jnp.pad(mask, ((0, 0), (0, seq_pad - seq)))[:, None, :]
    out, lse = _flash_forward(prep(q), prep(k), prep(v), mask2, scale, h)
    return (
        out[:, :seq].reshape(batch, h, seq, head_dim),
        lse[:, :seq].reshape(batch, h, seq, 1),
    )


def _bwd4_impl(q, k, v, mask, out, lse, do, scale, heads):
    batch, h, seq, head_dim = q.shape
    _, seq_pad = _plan(seq)

    def prep(t):
        t = t.reshape(batch * h, seq, head_dim)
        return jnp.pad(t, ((0, 0), (0, seq_pad - seq), (0, 0)))

    mask2 = jnp.pad(mask, ((0, 0), (0, seq_pad - seq)))[:, None, :]
    # padded lse rows must stay out of exp(): -inf would NaN; any finite
    # value is unused because padded query rows are sliced off below
    lse3 = jnp.pad(
        lse.reshape(batch * h, seq, 1), ((0, 0), (0, seq_pad - seq), (0, 0))
    )
    dq, dk, dv = _flash_backward(
        prep(q), prep(k), prep(v), mask2, prep(out), lse3, prep(do), scale, h
    )

    def unprep(t):
        return t[:, :seq].reshape(batch, h, seq, head_dim)

    return unprep(dq), unprep(dk), unprep(dv)


def _batch_head_spec(sharding, ndim):
    """Partition spec keeping only batch(0)/head(1) shardings; S and
    head_dim must be whole on every device for the kernel math. Dropping a
    sequence/head_dim sharding means GSPMD will all-gather those axes per
    device — a silent memory/perf cliff for context-sharded configs, so it
    warns (seq sharding belongs on the ring-attention path, not here)."""
    from jax.sharding import PartitionSpec as P

    if sharding is None or not hasattr(sharding, "spec"):
        return P()
    spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
    if any(spec[2:ndim]):
        import warnings

        warnings.warn(
            f"flash attention: input sharded over sequence/head_dim "
            f"({sharding.spec}); the kernel keeps those axes whole per "
            f"device, so GSPMD will all-gather them (replicating S per "
            f"device). Use attention_impl='ring' (ContextParallel) for "
            f"sequence sharding.",
            stacklevel=2,
        )
    return P(*(tuple(spec[: min(2, ndim)]) + (None,) * (ndim - 2)))


def _operand_spec(info, spec, mask_spec, lse_spec):
    """Per-operand spec: [B,S] masks shard on batch only; [...,1] lse columns
    shard on batch/head; q/k/v/out/do take the full batch/head spec."""
    if len(info.shape) == 2:
        return mask_spec
    if info.shape[-1] == 1:
        return lse_spec
    return spec


def _make_partition(impl, n_out):
    """partition/infer callbacks for custom_partitioning. With static_argnums
    the callbacks receive (statics..., mesh, arg_infos, result_infos)."""

    def specs(mesh, arg_infos):
        from jax.sharding import PartitionSpec as P

        spec = _batch_head_spec(arg_infos[0].sharding, 4)
        mask_spec = P(spec[0], None)
        lse_spec = P(spec[0], spec[1], None, None)
        return spec, mask_spec, lse_spec

    def partition(scale, heads, mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding

        spec, mask_spec, lse_spec = specs(mesh, arg_infos)
        arg_sh = tuple(
            NamedSharding(mesh, _operand_spec(a, spec, mask_spec, lse_spec))
            for a in arg_infos
        )
        outs = [spec, lse_spec] if n_out == 2 else [spec] * n_out
        out_sh = tuple(NamedSharding(mesh, s) for s in outs)

        def lower(*operands):
            return impl(*operands, scale, heads)

        return mesh, lower, out_sh, arg_sh

    def infer(scale, heads, mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding

        spec, _, lse_spec = specs(mesh, arg_infos)
        outs = [spec, lse_spec] if n_out == 2 else [spec] * n_out
        return tuple(NamedSharding(mesh, s) for s in outs)

    return partition, infer


_fwd4 = custom_partitioning(_fwd4_impl, static_argnums=(4, 5))
_fwd4_partition, _fwd4_infer = _make_partition(_fwd4_impl, 2)
_fwd4.def_partition(
    partition=_fwd4_partition,
    infer_sharding_from_operands=_fwd4_infer,
    # b (batch) and h (heads) are shardable; s/d must stay whole per device
    sharding_rule="b h s d, b h s d, b h s d, b s -> b h s d, b h s z",
)

_bwd4 = custom_partitioning(_bwd4_impl, static_argnums=(7, 8))
_bwd4_partition, _bwd4_infer = _make_partition(_bwd4_impl, 3)
_bwd4.def_partition(
    partition=_bwd4_partition,
    infer_sharding_from_operands=_bwd4_infer,
    sharding_rule=(
        "b h s d, b h s d, b h s d, b s, b h s d, b h s z, b h s d "
        "-> b h s d, b h s d, b h s d"
    ),
)


# ---------------------------------------------------------------------------
# custom_vjp wrapper (differentiation sits OUTSIDE the partitioned ops:
# custom_partitioning has no autodiff rule, so fwd and bwd are each their
# own partitioned computation)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, mask, scale, heads):
    out, _ = _fwd4(q, k, v, mask, scale, heads)
    return out


def _flash_fwd(q, k, v, mask, scale, heads):
    out, lse = _fwd4(q, k, v, mask, scale, heads)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(scale, heads, residuals, g):
    q, k, v, mask, out, lse = residuals
    dq, dk, dv = _bwd4(q, k, v, mask, out, lse, g, scale, heads)
    dmask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_causal_attention(q, k, v, *, scale, pad_mask=None):
    """Drop-in for the XLA path in tpukit/ops/attention.py.

    q, k, v: [B, heads, S, head_dim]; pad_mask: optional [B, S] bool
    (True = padding). Returns [B, heads, S, head_dim] in v's dtype.

    GSPMD-aware: under a sharded jit trace the custom_partitioning rules
    keep batch/head shardings and run the kernel per-shard (DP/FSDP/TP all
    shard only those dims); S and head_dim stay whole per device.
    """
    batch, heads, seq, head_dim = q.shape
    if pad_mask is None:
        mask = jnp.zeros((batch, seq), jnp.int32)
    else:
        mask = pad_mask.astype(jnp.int32)
    return _flash(q, k, v, mask, scale, heads)
