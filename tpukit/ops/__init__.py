from tpukit.ops.attention import causal_attention  # noqa: F401
from tpukit.ops.layers import (  # noqa: F401
    cross_entropy_loss,
    dropout,
    layer_norm,
    linear,
)
