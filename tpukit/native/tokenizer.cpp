// tpukit native tokenizer: C++ twin of the piece-splitting + vocab lookup in
// tpukit/data.py (WordTokenizer._encode_one / __call__ with padding).
//
// The reference outsources its host-side tokenization to native code inside
// its pip dependencies (HuggingFace fast tokenizers + datasets.map with
// num_proc worker processes, reference data.py:23-36); this is tpukit's
// in-tree equivalent: a multithreaded batch encoder behind a C ABI, loaded
// via ctypes (no pybind11 dependency).
//
// Piece splitting replicates the Python regex  ` ?[A-Za-z0-9']+| ?[^A-Za-z0-9\s]+|\s`
// (tpukit/data.py:_PIECE_RE) with the same alternation semantics, and
// unknown pieces degrade to per-character encoding exactly like
// WordTokenizer._encode_one. The Python test suite asserts byte-identical
// output between the two (tests/test_native.py).

#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  int32_t unk_id;
};

inline bool is_word(unsigned char c) {
  return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
         (c >= '0' && c <= '9') || c == '\'';
}

inline bool is_space(unsigned char c) {
  // Python str.isspace() over the ASCII range the corpus uses
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' || c == '\f';
}

// Next piece starting at s[i]; returns its length (>= 1).
size_t next_piece(const char* s, size_t n, size_t i) {
  size_t j = i;
  bool leading_space = s[j] == ' ';
  size_t k = j + (leading_space ? 1 : 0);
  // alt 1: " ?[A-Za-z0-9']+"
  if (k < n && is_word(s[k])) {
    size_t e = k;
    while (e < n && is_word(s[e])) e++;
    return e - i;
  }
  // alt 2: " ?[^A-Za-z0-9\s]+"
  if (k < n && !is_word(s[k]) && !is_space(s[k])) {
    size_t e = k;
    while (e < n && !is_word(s[e]) && !is_space(s[e])) e++;
    return e - i;
  }
  // alt 3: single whitespace char (covers the bare space fallthrough)
  return 1;
}

void encode_one(const Tokenizer& tok, const char* text, size_t len,
                int32_t max_len, int32_t pad_id, int32_t* ids, int32_t* mask) {
  int32_t count = 0;
  std::string piece;
  for (size_t i = 0; i < len && count < max_len;) {
    size_t plen = next_piece(text, len, i);
    piece.assign(text + i, plen);
    auto it = tok.vocab.find(piece);
    if (it != tok.vocab.end()) {
      ids[count++] = it->second;
    } else {
      // unknown piece -> per-character fallback (data.py:_encode_one).
      // UTF-8 continuation bytes are skipped so a multibyte codepoint
      // yields ONE unk, matching Python's per-codepoint loop.
      for (size_t c = 0; c < plen && count < max_len; ++c) {
        if ((static_cast<unsigned char>(piece[c]) & 0xC0) == 0x80) continue;
        auto cit = tok.vocab.find(std::string(1, piece[c]));
        ids[count++] = cit != tok.vocab.end() ? cit->second : tok.unk_id;
      }
    }
    i += plen;
  }
  for (int32_t p = 0; p < count; ++p) mask[p] = 1;
  for (int32_t p = count; p < max_len; ++p) {
    ids[p] = pad_id;
    mask[p] = 0;
  }
}

}  // namespace

extern "C" {

// vocab_blob: n_tokens pieces separated by '\0'; token id == position.
void* tpukit_tok_create(const char* vocab_blob, int64_t blob_len,
                        int32_t n_tokens, int32_t unk_id) {
  auto* tok = new Tokenizer();
  tok->unk_id = unk_id;
  tok->vocab.reserve(static_cast<size_t>(n_tokens) * 2);
  const char* p = vocab_blob;
  const char* end = vocab_blob + blob_len;
  for (int32_t id = 0; id < n_tokens && p < end; ++id) {
    size_t len = strnlen(p, end - p);
    tok->vocab.emplace(std::string(p, len), id);
    p += len + 1;
  }
  return tok;
}

void tpukit_tok_destroy(void* handle) { delete static_cast<Tokenizer*>(handle); }

// texts: concatenated UTF-8; offsets: n+1 byte offsets into it.
// out_ids/out_mask: [n, max_len] row-major int32.
void tpukit_tok_encode_batch(void* handle, const char* texts,
                             const int64_t* offsets, int32_t n,
                             int32_t max_len, int32_t pad_id,
                             int32_t* out_ids, int32_t* out_mask,
                             int32_t n_threads) {
  const auto& tok = *static_cast<Tokenizer*>(handle);
  if (n_threads < 1) n_threads = 1;
  auto work = [&](int32_t lo, int32_t hi) {
    for (int32_t r = lo; r < hi; ++r) {
      encode_one(tok, texts + offsets[r],
                 static_cast<size_t>(offsets[r + 1] - offsets[r]), max_len,
                 pad_id, out_ids + static_cast<int64_t>(r) * max_len,
                 out_mask + static_cast<int64_t>(r) * max_len);
    }
  };
  if (n_threads == 1 || n < 2 * n_threads) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int32_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int32_t lo = t * chunk;
    int32_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
