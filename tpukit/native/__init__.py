"""tpukit native runtime components (C++ behind ctypes).

The reference's host-side data path rides on native code inside its pip
dependencies — HuggingFace fast tokenizers and `datasets.map(num_proc=8)`
worker processes (reference data.py:23-36). tpukit's in-tree equivalent is
this package: a multithreaded C++ batch tokenizer (tokenizer.cpp) exactly
twinning `WordTokenizer`'s encoding, loaded through ctypes (pybind11 is
deliberately not required).

Build model: the shared library compiles lazily on first use with g++ and
is never committed — only `tokenizer.cpp` is source of truth. A sidecar
hash file records which source the cached .so was built from; any source
change (or a stale/foreign binary) triggers a rebuild, so the binary that
executes is always the one auditable from the checked-in C++ (mtime
comparison is useless after a fresh `git checkout`, which assigns equal
mtimes). Environments without a compiler simply fall back to the
pure-Python encoder — `is_available()` gates every caller. Set
TPUKIT_NATIVE=0 to force the Python path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "tokenizer.cpp"
_LIB = _DIR / "libtpukit_native.so"
_HASH = _DIR / ".libtpukit_native.srchash"

_lib = None
_build_error: str | None = None


def _src_hash() -> str:
    return hashlib.sha256(_SRC.read_bytes()).hexdigest()


def _build() -> None:
    # Compile to a per-process temp path and atomically publish: several
    # processes may race to build after a fresh checkout (the .so is not
    # committed), and a reader must never dlopen a partially-written file.
    out = _DIR / f".build-{os.getpid()}.so.tmp"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        str(_SRC), "-o", str(out),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(out, _LIB)  # lint: allow(atomic-publish): compiled .so artifact, not a JSON publish
    finally:
        out.unlink(missing_ok=True)
    _HASH.write_text(_src_hash())


def _load():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    if os.environ.get("TPUKIT_NATIVE") == "0":
        _build_error = "disabled via TPUKIT_NATIVE=0"
        return None
    try:
        recorded = _HASH.read_text().strip() if _HASH.exists() else ""
        if not _LIB.exists() or recorded != _src_hash():
            _build()
        lib = ctypes.CDLL(str(_LIB))
        lib.tpukit_tok_create.restype = ctypes.c_void_p
        lib.tpukit_tok_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.tpukit_tok_destroy.argtypes = [ctypes.c_void_p]
        lib.tpukit_tok_encode_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32,
        ]
        _lib = lib
    except Exception as exc:  # no compiler / bad toolchain -> Python path
        _build_error = f"{type(exc).__name__}: {exc}"
    return _lib


def is_available() -> bool:
    return _load() is not None


class NativeEncoder:
    """Batch encoder over a WordTokenizer-compatible vocab. Produces output
    byte-identical to `WordTokenizer.__call__(padding='max_length',
    truncation=True)` (asserted by tests/test_native.py)."""

    def __init__(self, id_to_token: list[str], unk_id: int):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native tokenizer unavailable: {_build_error}")
        self._lib = lib
        blob = b"\0".join(t.encode("utf-8") for t in id_to_token) + b"\0"
        self._handle = lib.tpukit_tok_create(
            blob, len(blob), len(id_to_token), unk_id
        )
        if not self._handle:
            raise RuntimeError("tpukit_tok_create failed")

    def encode_batch(
        self,
        texts: list[str],
        max_length: int,
        pad_id: int,
        n_threads: int | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (input_ids, attention_mask), both [N, max_length] int32."""
        encoded = [t.encode("utf-8") for t in texts]
        offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        n = len(encoded)
        ids = np.empty((n, max_length), dtype=np.int32)
        mask = np.empty((n, max_length), dtype=np.int32)
        if n_threads is None:
            n_threads = min(os.cpu_count() or 1, 16)
        self._lib.tpukit_tok_encode_batch(
            self._handle, blob, offsets, n, max_length, pad_id, ids, mask,
            n_threads,
        )
        return ids, mask

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and self._lib is not None:
            self._lib.tpukit_tok_destroy(handle)
