"""Host-side batch loading.

Twin of the reference's `torch.utils.data.DataLoader` + `DistributedSampler`
usage (main-single.py:62-75, main-ddp.py:83-100). Two pieces:

- `DataLoader`: shuffling mini-batch iterator over an `ArrayDataset`,
  reshuffling each epoch like torch's `shuffle=True` (call `set_epoch`, the
  same contract as `DistributedSampler.set_epoch`, main-ddp.py:109).
- `distributed_indices`: the `DistributedSampler` index math twinned exactly
  (pad-to-even-split by wrapping, then rank-strided) for per-host sharding in
  multi-host runs. On a single host the SPMD recipes feed the *global* batch
  and let the batch sharding split it across devices — the TPU-native
  replacement for per-rank loaders.

`num_workers`/`pin_memory` have no TPU-native meaning for a numpy-backed
in-memory dataset (there is no H2D pinning; transfers happen at the jit
boundary); the flags are accepted for CLI parity.
"""

from __future__ import annotations

import math
from typing import Iterator

import numpy as np

from tpukit import chaos as chaos_lib
from tpukit.data import ArrayDataset
from tpukit.retry import retry_io


def distributed_indices(
    dataset_len: int,
    num_replicas: int,
    rank: int,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = False,
    with_real: bool = False,
) -> np.ndarray:
    """Twin of torch `DistributedSampler.__iter__` semantics (the mechanism
    behind reference main-ddp.py:83-84): optionally shuffle with
    `seed + epoch`, pad the index list by wrapping so it divides evenly
    (unless drop_last), then take rank-strided indices.

    `with_real=True` additionally returns a bool mask marking which of this
    rank's entries are original samples (False = wrap-padding duplicates) —
    the honest-token accounting the throughput meter needs (VERDICT r2 #8)."""
    if shuffle:
        g = np.random.RandomState(seed + epoch)
        indices = g.permutation(dataset_len)
    else:
        indices = np.arange(dataset_len)
    real = np.ones(dataset_len, dtype=bool)

    if drop_last and dataset_len % num_replicas != 0:
        num_samples = dataset_len // num_replicas
        total_size = num_samples * num_replicas
        indices = indices[:total_size]
        real = real[:total_size]
    else:
        num_samples = math.ceil(dataset_len / num_replicas)
        total_size = num_samples * num_replicas
        if total_size > dataset_len:
            pad = total_size - dataset_len
            indices = np.concatenate([indices, indices[:pad]])
            real = np.concatenate([real, np.zeros(pad, dtype=bool)])

    sl = slice(rank, total_size, num_replicas)
    if with_real:
        return indices[sl], real[sl]
    return indices[sl]


class DataLoader:
    """Mini-batch iterator over an ArrayDataset.

    `shuffle=True` reshuffles every epoch (seeded by `seed + epoch`);
    `num_replicas`/`rank` apply the DistributedSampler sharding above.
    Yields dict batches of numpy arrays `{input_ids, attention_mask}`.
    Incomplete final batches are yielded (torch default drop_last=False).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        num_replicas: int = 1,
        rank: int = 0,
        drop_last: bool = False,
        pad_to_batch: bool = False,
        pad_mode: str = "wrap",
        pad_fill: int = 0,
        num_workers: int = 0,  # parity only
        pin_memory: bool = False,  # parity only
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_replicas = num_replicas
        self.rank = rank
        self.drop_last = drop_last
        # pad_to_batch keeps every batch full-shape (one static compiled step
        # shape; a batch sharded over the `data` axis always divides).
        # pad_mode "wrap" repeats rows from the front — the analogue of
        # DistributedSampler's pad-by-wrapping, right for training.
        # pad_mode "empty" appends rows of `pad_fill` tokens with a zero
        # attention mask; prepare_batch turns those into all-ignore targets,
        # so eval metrics are NOT skewed by duplicated samples.
        if pad_mode not in ("wrap", "empty"):
            raise ValueError(f"pad_mode must be 'wrap' or 'empty', got {pad_mode!r}")
        self.pad_to_batch = pad_to_batch
        self.pad_mode = pad_mode
        self.pad_fill = pad_fill
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self) -> tuple[np.ndarray, np.ndarray]:
        """Returns (indices, real): `real[i]` is False for padding entries —
        wrap-duplicates or -1 sentinels — so callers can count only original
        dataset rows (the throughput meter must not count wrap rows as real
        tokens, VERDICT r2 #8)."""
        empty_pad = self.pad_to_batch and self.pad_mode == "empty"
        if self.num_replicas > 1:
            if empty_pad and not self.drop_last:
                # Same rank-stride math as distributed_indices, but the
                # even-split padding uses -1 sentinels (-> all-ignore rows)
                # instead of wrapped duplicates, keeping eval unskewed.
                if self.shuffle:
                    g = np.random.RandomState(self.seed + self.epoch)
                    base = g.permutation(len(self.dataset))
                else:
                    base = np.arange(len(self.dataset))
                total = math.ceil(len(base) / self.num_replicas) * self.num_replicas
                base = np.concatenate(
                    [base, np.full(total - len(base), -1, base.dtype)]
                )
                indices = base[self.rank : total : self.num_replicas]
                real = indices >= 0
            else:
                indices, real = distributed_indices(
                    len(self.dataset),
                    self.num_replicas,
                    self.rank,
                    shuffle=self.shuffle,
                    seed=self.seed,
                    epoch=self.epoch,
                    drop_last=self.drop_last,
                    with_real=True,
                )
        else:
            if self.shuffle:
                g = np.random.RandomState(self.seed + self.epoch)
                indices = g.permutation(len(self.dataset))
            else:
                indices = np.arange(len(self.dataset))
            real = np.ones(len(indices), dtype=bool)
        if self.pad_to_batch and len(indices) % self.batch_size:
            pad = self.batch_size - len(indices) % self.batch_size
            if self.pad_mode == "wrap":
                # np.resize tiles, so datasets smaller than the pad still fill
                indices = np.concatenate([indices, np.resize(indices, pad)])
            else:
                indices = np.concatenate([indices, np.full(pad, -1, indices.dtype)])
            real = np.concatenate([real, np.zeros(pad, dtype=bool)])
        return indices, real

    def __len__(self) -> int:
        n = len(self._indices()[0])
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def global_real_row_counts(self) -> np.ndarray:
        """Per-batch ORIGINAL-row counts summed over all replicas.

        The wrap/sentinel pad positions depend only on (dataset_len,
        num_replicas, batch_size) — never on the shuffle values — so the
        whole global schedule is closed-form host math (ADVICE r5 #3: the
        previous implementation re-materialized every rank's shuffled
        permutation, O(num_replicas x dataset) work per epoch fleet-wide).
        This is what makes the throughput meter exact on ragged final
        batches (VERDICT r4 #6) WITHOUT a per-step cross-host reduction
        (which would re-serialize the async dispatch it is timing).

        Derivation: shuffling permutes index VALUES, never pad POSITIONS.
        Rank r's entry j sits at base position `r + j*R` (R replicas,
        rank-strided split), which is an original sample iff `r + j*R < N`
        — wrap duplicates and -1 sentinels both occupy positions >= N.
        Summed over ranks, row j therefore carries `clip(N - j*R, 0, R)`
        real rows; rows in the per-rank batch-padding tail carry none.

        Subclass safety (ADVICE r5 #3): the closed form mirrors the BASE
        `_indices` schedule, so when `type(self)` overrides `_indices` this
        falls back to enumerating the subclass's own schedule per rank
        (sweeping `self.rank` through its actual `_indices`) instead of
        silently answering with the base math.
        """
        if type(self)._indices is not DataLoader._indices:
            return self._enumerated_real_row_counts()
        n = len(self.dataset)
        reps = self.num_replicas
        if self.drop_last and n % reps:
            samples = n // reps  # even-split truncation
        else:
            samples = math.ceil(n / reps)  # pad-by-wrapping / -1 sentinels
        per_rank = samples
        if self.pad_to_batch and per_rank % self.batch_size:
            per_rank += self.batch_size - per_rank % self.batch_size
        stop = (
            (per_rank // self.batch_size) * self.batch_size
            if self.drop_last
            else per_rank
        )
        if stop == 0:
            return np.zeros(0, dtype=np.int64)
        j = np.arange(stop, dtype=np.int64)
        real = np.clip(n - j * reps, 0, reps)
        real[j >= samples] = 0  # per-rank batch padding (wrap or sentinel)
        return np.add.reduceat(real, np.arange(0, stop, self.batch_size))

    def _enumerated_real_row_counts(self) -> np.ndarray:
        """Generic fallback for subclasses with a custom `_indices`: sweep
        `self.rank` through every replica and sum each rank's actual real
        mask per batch. O(num_replicas x dataset) host work — the price of
        an arbitrary schedule; the base class uses the closed form above."""
        prev_rank = self.rank
        totals = None
        try:
            for rank in range(self.num_replicas):
                self.rank = rank
                _, real = self._indices()
                n = len(real)
                stop = (
                    (n // self.batch_size) * self.batch_size
                    if self.drop_last
                    else n
                )
                per_batch = np.array(
                    [
                        int(real[s : s + self.batch_size].sum())
                        for s in range(0, stop, self.batch_size)
                    ],
                    dtype=np.int64,
                )
                totals = per_batch if totals is None else totals + per_batch
        finally:
            self.rank = prev_rank
        return totals if totals is not None else np.zeros(0, dtype=np.int64)

    def _fetch_rows(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """One batch's dataset fetch — the retried I/O unit. For the
        in-memory fixture this is a numpy gather; for disk/remote-backed
        datasets (HF arrow on NFS/GCS-fuse) it is real I/O whose transient
        failures the round-9 backoff wrapper absorbs. The chaos hook sits
        inside so `loader_io_fail@K` exercises the actual retry path."""
        chaos_lib.maybe_io_fault("loader_fetch")
        safe = np.maximum(idx, 0)
        return self.dataset.input_ids[safe], self.dataset.attention_mask[safe]

    def __iter__(self) -> Iterator[dict]:
        indices, real = self._indices()
        n = len(indices)
        stop = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for start in range(0, stop, self.batch_size):
            idx = indices[start : start + self.batch_size]
            ids, mask = retry_io(self._fetch_rows, idx, label="loader_fetch")
            pad_rows = idx < 0  # -1 sentinels become all-ignore rows
            if pad_rows.any():
                ids = np.where(pad_rows[:, None], self.pad_fill, ids)
                mask = np.where(pad_rows[:, None], 0, mask)
            yield {
                "input_ids": ids,
                "attention_mask": mask,
                # original-sample rows in this batch (excludes wrap/sentinel
                # padding); the meter counts only these as throughput
                "real_rows": int(real[start : start + self.batch_size].sum()),
            }
