"""Shared CLI flag system.

The reference duplicates an identical 12-flag argparse block in every recipe
(main-single.py:156-167, main-ddp.py:192-203, main-fsdp.py:206-219,
main-pipe.py:225-236); here it is one dataclass + builder imported by all
five recipes (SURVEY §5 config plan). Flag names and defaults are twinned
exactly; `--cpu_offload` is the FSDP recipe's extra flag (main-fsdp.py:219).

TPU reinterpretations (documented divergences, not silent ones):
  - `--disable_amp`: flips the compute dtype from bfloat16 to float32. There
    is no GradScaler twin — bf16 needs no loss scaling (the reference's
    scaler is a no-op for bf16 anyway, main-single.py:78).
  - `--disable_compile`: runs the train/eval steps eagerly via
    `jax.disable_jit()` — the debugging analogue of skipping torch.compile
    (main-single.py:38-39).
"""

from __future__ import annotations

import argparse
import dataclasses


@dataclasses.dataclass
class TrainFlags:
    batch_size: int = 64
    epochs: int = 5
    sequence_length: int = 256
    dim: int = 256
    head_dim: int = 32
    heads: int = 8
    num_layers: int = 8
    learning_rate: float = 1e-4
    dataset_slice: str = "100%"
    num_workers: int = 4
    disable_amp: bool = False
    disable_compile: bool = False
    # FSDP recipe only (main-fsdp.py:219):
    cpu_offload: bool = False
    # tpukit extensions (absent in the reference; see SURVEY §5 plans):
    seed: int = 0
    # Dropout rate (the reference model takes it as a constructor arg but its
    # CLIs never expose it, models/gpt.py:14,50; here it is a flag). Active
    # in train steps only, seeded per step from --seed.
    dropout: float = 0.0
    checkpoint_every: int = 0  # steps; 0 = end-of-training only (reference behavior)
    # "auto" writes the sharded format exactly when the state cannot be
    # host-gathered (multi-host FSDP/pipeline), else the consolidated
    # msgpack the reference-style save produces. Force either explicitly.
    checkpoint_format: str = "auto"  # auto | consolidated | sharded
    # Non-blocking checkpoint writes (round 7): snapshot on the training
    # thread, encode/write/publish on a background thread with a join
    # barrier at the next save/exit. Same formats, same atomic-publish
    # durability; only the loop no longer stalls on disk.
    async_checkpoint: bool = False
    # Retention (round 13): after each successful checkpoint publish, prune
    # published checkpoints older than the newest K, so long elastic runs
    # don't exhaust disk. Quarantined timelines and the newest
    # integrity-verified (`latest_good`) checkpoint are never pruned.
    # 0 = keep everything (the pre-round-13 behavior). Note K also bounds
    # how far back `--on_anomaly rollback` can reach.
    keep_checkpoints: int = 0
    # Resume path (either format) or "latest". Round 13: `--resume` is
    # ELASTIC — when the checkpoint's recorded world (nprocs, device
    # count, strategy, mesh axes; written into every save's meta sidecar)
    # differs from the current run's, the state is resharded onto the
    # current `state_sharding` specs (tpukit/reshard.py) instead of
    # failing or silently misloading, and a kind="resize" JSONL record
    # names the change. Hold global batch (batch_size x data shards)
    # constant across a resize for loss-trajectory parity.
    resume: str = ""
    # Host input pipeline depth (round 7): a background thread runs
    # prepare_batch + the strategy's host transform + global-batch H2D
    # assembly this many batches ahead, overlapping the in-flight compiled
    # step. 0 = the synchronous reference path (bit-identical losses).
    prefetch: int = 2
    # If set, JAX's persistent compilation cache lives here: repeat runs of
    # the same program skip XLA recompiles, and fit logs a
    # kind="compile_cache" hit/miss record.
    compilation_cache_dir: str = ""
    profile_dir: str = ""  # if set, jax.profiler traces land here
    metrics_log: str = ""  # if set, JSONL step metrics land here
    # Metrics plane (round 22, tpukit/obs/metrics.py): mergeable
    # counters + log-bucket histograms derived from the fit() window
    # spans and the recovery observers — ON by default (pure observer,
    # window-boundary host code only). --metrics_dir points at a SHARED
    # directory where every process atomically publishes its snapshot
    # file each window and process 0 merges by bucket sum.
    no_metrics: bool = False
    metrics_dir: str = ""
    # Debug toolchain (SURVEY §5 race-detection plan): aborts with a traceback
    # at the first NaN/Inf produced inside any jitted computation.
    debug_nans: bool = False
    # Telemetry (tpukit/obs, round 6). --log_grad_norms computes global
    # grad/update/param L2 norms INSIDE the existing jitted train step and
    # logs them per window; off = the compiled step is untouched.
    log_grad_norms: bool = False
    # Loss-spike/NaN sentinel on the window-averaged loss: 0 disables; N > 0
    # fires when the loss exceeds the rolling mean by N deviations (or goes
    # non-finite). Action: "warn" logs and continues; "abort" writes a
    # checkpoint then raises (so the blow-up step is preserved for autopsy).
    spike_threshold: float = 0.0
    spike_action: str = "warn"  # warn | abort
    # Multi-host liveness: if set, every process writes a heartbeat file
    # (step + timestamp) to this SHARED directory each PRINT_FREQ window and
    # process 0 reports processes whose beats go stale past the timeout.
    heartbeat_dir: str = ""
    heartbeat_timeout: float = 120.0  # seconds
    # Failure observability (round 8, tpukit/obs). The flight recorder (a
    # bounded in-memory ring of recent step/window/sentinel records) is
    # ALWAYS on; these flags control what gets done with it when a run
    # goes wrong:
    #   --hang_timeout S > 0 starts the hang watchdog: a monitor thread
    #     armed around each step iteration that dumps a diagnostics bundle
    #     (all-thread stacks, recorder ring, HBM gauges, heartbeat
    #     snapshot, in-flight async-checkpoint/prefetch state, run config)
    #     to --debug_dir when an iteration overruns S seconds. The first
    #     step of each compiled function is exempt (compile time is not a
    #     hang); S bounds the steady-state step, not the compile.
    #   --debug_dir D is where bundles (and the anomaly trace) land; any
    #     sentinel firing (spike/NaN/straggler/divergence) also dumps a
    #     bundle there. Defaults to "debug" when a feature needing it is
    #     on; render bundles with tools/flightview.py.
    hang_timeout: float = 0.0  # seconds; 0 disables the watchdog
    debug_dir: str = ""
    # Trace-on-anomaly: K > 0 arms a jax.profiler capture of the K steps
    # following the FIRST anomaly of the run (spike/NaN/straggler/
    # divergence/hang-recovery), so the expensive trace is collected
    # exactly when it matters. Traces land under --debug_dir/anomaly_trace.
    # Ignored when --profile_dir already traces the whole run.
    trace_on_anomaly: int = 0
    # Cross-replica divergence detection: every N steps compute an in-jit
    # XOR checksum of params + opt state (a separate jitted program — the
    # train step's HLO is byte-identical on/off, the --log_grad_norms
    # discipline), publish it through the heartbeat file, and have
    # process 0 compare across processes; a mismatch at the same step
    # logs kind="divergence" and dumps a bundle. 0 disables.
    divergence_check_freq: int = 0
    # Recovery (round 9, docs/DESIGN.md "recovery"). --on_anomaly rollback
    # turns a sentinel/divergence firing from checkpoint-then-abort into an
    # in-process rollback: restore the last integrity-verified checkpoint
    # OLDER than the detection window, keep the input stream moving forward
    # (the offending batch window is never replayed), and continue — up to
    # --max_rollbacks times, then escalate to the round-8 bundle-dump-and-
    # abort path (exit code 77). "none" keeps the round-8 behavior.
    on_anomaly: str = "none"  # none | rollback
    max_rollbacks: int = 3
    # Transient host-I/O retry budget (tpukit/retry.py): checkpoint
    # reads/writes and dataset fetches retry up to N times with jittered
    # exponential backoff before failing loud. Every retry leaves a
    # kind="retry" JSONL record. 0 disables retrying.
    io_retries: int = 3
    # Deterministic fault injection (tpukit/chaos.py), e.g.
    # "nan_loss@120,sigterm@300,ckpt_io_fail@2,hang@450:2.5" — see the
    # chaos-spec grammar in docs/DESIGN.md. Empty = no harness installed;
    # the compiled train step is byte-identical either way (all injection
    # is host-side).
    chaos_spec: str = ""
    # Rematerialization policy: checkpoint each decoder layer (backward
    # recomputes the layer forward; less HBM traffic and memory — needed for
    # the larger ladder configs at long sequence).
    remat: bool = False
    # Run the layer stack as one lax.scan body instead of unrolled blocks
    # (slower on v5e at the reference depth, but keeps compile time flat for
    # very deep models).
    scan_layers: bool = False
    # Pipeline recipes: micro-batch count. 0 = 4x the stage count (shrinks
    # the GPipe bubble to ~16%); the reference ties it to the stage count
    # (chunks=num_stages, main-pipe.py:83) — pass it explicitly for that.
    microbatches: int = 0
    # main-ring.py only: sequence-parallel attention schedule — "ring"
    # (zigzag-balanced ppermute hops) or "ulysses" (all_to_all head
    # re-partitioning; needs heads % seq_shards == 0).
    cp_attention: str = "ring"
    # pipeline recipes only: "gpipe" (autodiff schedule, vocab-sharded
    # embeddings/head) or "1f1b" (explicit per-stage vjps — activation
    # memory bounded by the stage count instead of the micro count).
    pipeline_schedule: str = "gpipe"
    # pipeline recipes only (round 22, ROADMAP #5): interleaved virtual
    # stages for the 1f1b schedule — device d owns V non-contiguous layer
    # chunks (d, d+S, d+2S, ...) and the tick table interleaves their
    # forward/backward micro-steps, shrinking the warm-up/cool-down bubble
    # toward (S-1)/(M*V) at equal micro count. 1 = the existing schedules,
    # byte-identical HLO; needs --schedule 1f1b and num_layers >= V*S.
    virtual_stages: int = 1
    # main-moe.py AND (round 22) the pipeline recipes: number of routed
    # experts replacing each layer's FFN (0 = the dense reference model)
    # and how many experts each token routes to (1 = Switch, 2 =
    # GShard/Mixtral-style top-2). Under the pipeline recipes the expert
    # FFN rides INSIDE a stage chunk and only the meshless
    # --moe_dispatch pallas dataflow is legal (no a2a axis on a stage
    # mesh); xla/a2a are rejected by name at validate_config.
    num_experts: int = 0
    moe_top_k: int = 1
    # main-moe.py only: expert dispatch dataflow (round 10/11). "a2a"
    # (default) hand-places the token exchange as a shard_map
    # lax.all_to_all pair over the `expert` mesh axis — forward AND
    # backward — instead of leaving the dispatch einsums to GSPMD, whose
    # backward falls into involuntary replicate-repartition
    # (MULTICHIP_r05.json). "pallas" keeps that exchange but computes the
    # expert FFN with the fused grouped-expert GEMM (tpukit/ops/
    # moe_gemm.py; on one chip it is the dropless sorted segment GEMM —
    # the moe_e8 throughput path). "xla" restores the round-5
    # einsum-and-GSPMD behavior for comparison.
    moe_dispatch: str = "a2a"
    # Collective payload dtype (round 12, tpukit/ops/quant_comm.py —
    # EQuARX-style block-scaled quantized collectives). "f32" (default)
    # keeps the exact pre-round-12 collectives; "bf16"/"int8" compress the
    # wire payload of the strategies with hand-wired quantized collectives:
    # the DDP gradient all-reduce (two-shot: int8 reduce-scatter -> f32
    # accumulate -> int8 all-gather), the FSDP gradient reduce-scatter
    # (param all-gathers stay full precision — grads first), and the
    # ExpertParallel a2a dispatch payload. Optimizer math and master
    # params stay f32; correctness is gated by a loss-trajectory tolerance
    # (tests/test_quant_comm.py), not bit parity. Strategies without wired
    # collectives reject non-f32 values at startup.
    comm_dtype: str = "f32"
    # Stochastic rounding for the int8 quantizer (unbiased per element;
    # default off = round-to-nearest-even).
    quant_stochastic: bool = False
    # Overlap-scheduled gradient collectives (round 18, ROADMAP #5):
    # 0 (default) = the serial schedule, byte-identical HLO. N >= 1 =
    # DDP/FSDP partition the grad tree into N ~equal-byte buckets in
    # layer-reversed order and launch each bucket's collective as soon as
    # its grads are ready, overlapping wire with the remaining backward;
    # under ExpertParallel (per-layer a2a, already bucket-granular) any
    # N declares the hlolint `overlap` gate. Composes with --comm_dtype
    # (int8 wire cut + overlap win stack). Strategies without a
    # hand-placed grad wire reject the flag at startup.
    grad_buckets: int = 0


# The canonical 12 flags of every reference recipe (main-single.py:156-167).
_CORE_FLAGS = [
    ("batch_size", int),
    ("epochs", int),
    ("sequence_length", int),
    ("dim", int),
    ("head_dim", int),
    ("heads", int),
    ("num_layers", int),
    ("learning_rate", float),
    ("dataset_slice", str),
    ("num_workers", int),
]


def build_parser(
    cpu_offload: bool = False,
    cp_attention: bool = False,
    pipeline_schedule: bool = False,
    num_experts: bool = False,
    default_experts: int = 8,
) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser()
    defaults = TrainFlags()
    for name, typ in _CORE_FLAGS:
        parser.add_argument(f"--{name}", type=typ, default=getattr(defaults, name))
    parser.add_argument("--disable_amp", action="store_true")
    parser.add_argument("--disable_compile", action="store_true")
    if cpu_offload:
        parser.add_argument("--cpu_offload", action="store_true")
    if cp_attention:
        parser.add_argument(
            "--cp_attention", choices=("ring", "ulysses"), default="ring"
        )
    if pipeline_schedule:
        parser.add_argument(
            "--schedule", dest="pipeline_schedule",
            choices=("gpipe", "1f1b"), default="gpipe",
        )
        parser.add_argument(
            "--virtual_stages", type=int, default=defaults.virtual_stages
        )
    if num_experts:
        # main-moe.py keeps its 8-expert default; the pipeline recipes opt
        # in with default_experts=0 so `main-pipe.py` stays the dense
        # reference unless --num_experts is passed explicitly
        parser.add_argument("--num_experts", type=int, default=default_experts)
        parser.add_argument("--moe_top_k", type=int, default=1)
        parser.add_argument(
            "--moe_dispatch", choices=("a2a", "xla", "pallas"), default="a2a"
        )
    parser.add_argument("--seed", type=int, default=defaults.seed)
    parser.add_argument("--dropout", type=float, default=defaults.dropout)
    parser.add_argument("--checkpoint_every", type=int, default=defaults.checkpoint_every)
    parser.add_argument(
        "--checkpoint_format",
        choices=("auto", "consolidated", "sharded"),
        default=defaults.checkpoint_format,
    )
    parser.add_argument("--async_checkpoint", action="store_true")
    parser.add_argument(
        "--keep_checkpoints", type=int, default=defaults.keep_checkpoints
    )
    parser.add_argument("--resume", type=str, default=defaults.resume)
    parser.add_argument("--prefetch", type=int, default=defaults.prefetch)
    parser.add_argument(
        "--compilation_cache_dir", type=str,
        default=defaults.compilation_cache_dir,
    )
    parser.add_argument("--profile_dir", type=str, default=defaults.profile_dir)
    parser.add_argument("--metrics_log", type=str, default=defaults.metrics_log)
    parser.add_argument("--no_metrics", action="store_true",
                        default=defaults.no_metrics)
    parser.add_argument("--metrics_dir", type=str, default=defaults.metrics_dir)
    parser.add_argument("--debug_nans", action="store_true")
    parser.add_argument("--log_grad_norms", action="store_true")
    parser.add_argument(
        "--spike_threshold", type=float, default=defaults.spike_threshold
    )
    parser.add_argument(
        "--spike_action", choices=("warn", "abort"), default=defaults.spike_action
    )
    parser.add_argument("--heartbeat_dir", type=str, default=defaults.heartbeat_dir)
    parser.add_argument(
        "--heartbeat_timeout", type=float, default=defaults.heartbeat_timeout
    )
    parser.add_argument("--hang_timeout", type=float, default=defaults.hang_timeout)
    parser.add_argument("--debug_dir", type=str, default=defaults.debug_dir)
    parser.add_argument(
        "--trace_on_anomaly", type=int, default=defaults.trace_on_anomaly
    )
    parser.add_argument(
        "--divergence_check_freq", type=int,
        default=defaults.divergence_check_freq,
    )
    parser.add_argument(
        "--on_anomaly", choices=("none", "rollback"), default=defaults.on_anomaly
    )
    parser.add_argument(
        "--max_rollbacks", type=int, default=defaults.max_rollbacks
    )
    parser.add_argument("--io_retries", type=int, default=defaults.io_retries)
    parser.add_argument("--chaos_spec", type=str, default=defaults.chaos_spec)
    parser.add_argument(
        "--comm_dtype", choices=("f32", "bf16", "int8"),
        default=defaults.comm_dtype,
    )
    parser.add_argument("--quant_stochastic", action="store_true")
    parser.add_argument(
        "--grad_buckets", type=int, default=defaults.grad_buckets
    )
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--scan_layers", action="store_true")
    parser.add_argument("--microbatches", type=int, default=defaults.microbatches)
    return parser


def parse_flags(
    argv=None,
    cpu_offload: bool = False,
    cp_attention: bool = False,
    pipeline_schedule: bool = False,
    num_experts: bool = False,
    default_experts: int = 8,
) -> TrainFlags:
    ns = build_parser(
        cpu_offload=cpu_offload,
        cp_attention=cp_attention,
        pipeline_schedule=pipeline_schedule,
        num_experts=num_experts,
        default_experts=default_experts,
    ).parse_args(argv)
    kw = vars(ns)
    kw.setdefault("cpu_offload", False)
    kw.setdefault("cp_attention", "ring")
    kw.setdefault("pipeline_schedule", "gpipe")
    kw.setdefault("virtual_stages", 1)
    kw.setdefault("num_experts", 0)
    kw.setdefault("moe_top_k", 1)
    kw.setdefault("moe_dispatch", "a2a")
    return TrainFlags(**kw)


def add_serve_flags(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The serving-engine shape flags (main-serve.py, recipe 9), one
    spelling shared by the recipe and any harness that builds a
    `ServeConfig` from a CLI. Round 15 adds the paged-KV group: pages +
    block tables replace the per-slot ring when --page_size > 0, with
    shared-prefix reuse, chunked prefill, and int8 page payloads riding
    on top (tpukit/serve/paged.py; validation lives on ServeConfig and
    the engine so misconfigurations fail with named errors, not XLA
    shape errors)."""
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--buckets", type=str, default="16,32,64",
                        help="comma-separated prompt-length buckets — the "
                        "declared compile budget of the serve path")
    parser.add_argument("--max_new_tokens", type=int, default=20)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--window_steps", type=int, default=32)
    parser.add_argument("--decode_quantum", type=int, default=4,
                        help="decode steps per runtime dispatch (and per "
                        "host sync); with --fused_decode the quantum runs "
                        "as ONE on-device while_loop — raise it toward "
                        "--window_steps to amortize dispatch overhead")
    parser.add_argument("--page_size", type=int, default=0,
                        help="paged KV cache: token positions per page "
                        "(must divide every bucket); 0 = the per-slot ring")
    parser.add_argument("--num_pages", type=int, default=0,
                        help="page-pool size; 0 = ring-equivalent HBM "
                        "(slots x pages-per-slot + the null page)")
    parser.add_argument("--kv_dtype", choices=("f32", "bf16", "int8"),
                        default="f32",
                        help="page payload storage; int8 block-quantizes "
                        "page rows (quant_comm's 256-element blocks) for "
                        "~4x pages per HBM byte, gated by a token-level "
                        "tolerance test — requires --page_size")
    parser.add_argument("--prefill_chunk", type=int, default=0,
                        help="chunked-prefill tokens per dispatch (page "
                        "multiple dividing every bucket); 0 = one page")
    parser.add_argument("--fused_decode", action="store_true",
                        help="fused paged decode (round 21): T==1 "
                        "attention runs the fused Pallas paged kernel "
                        "(block tables dereferenced in-kernel, no "
                        "per-layer gather) and each quantum runs as one "
                        "on-device while_loop with early exit "
                        "(decode.decode_loop_window) — token streams "
                        "identical, host dispatch amortized across the "
                        "quantum. Requires --page_size")
    # Speculative decoding (round 17, tpukit/serve/spec.py) — the output
    # distribution is EXACT either way: greedy token-identical to vanilla
    # decode, sampled corrected by rejection sampling.
    parser.add_argument("--draft", choices=("", "ngram", "model"),
                        default="",
                        help="speculative decoding proposer: 'ngram' = "
                        "self-speculation (on-device prompt-lookup, no "
                        "second model), 'model' = a small tpukit GPT "
                        "draft (--draft_checkpoint + --draft_* shape "
                        "flags); '' = vanilla decode. Requires the ring "
                        "cache (page_size 0)")
    parser.add_argument("--spec_k", type=int, default=4,
                        help="draft tokens proposed per slot per quantum "
                        "(the verify window is spec_k + 1 wide)")
    parser.add_argument("--ngram_max", type=int, default=3,
                        help="longest n-gram the self-speculation "
                        "proposer matches (falls back through shorter "
                        "suffixes down to 1)")
    # Request-scoped tracing (round 20, tpukit/obs/trace.py): ON by
    # default — the ring is bounded and the emit cost is inside the
    # recorder's <1% budget (bench.py obs_overhead serving rung), with
    # token streams bit-identical either way (tests/test_trace.py).
    parser.add_argument("--no_trace", action="store_true",
                        help="disable request-scoped span tracing "
                        "(kind=\"trace_event\"/\"trace\" JSONL rows, "
                        "per-phase latency percentiles, traceview export)")
    parser.add_argument("--trace_capacity", type=int, default=8192,
                        help="span events retained per replica ring "
                        "(oldest evicted; evictions break the trace-"
                        "completeness invariant on long runs — grow this "
                        "before gating with --min_trace_complete)")
    # Metrics plane (round 22, tpukit/obs/metrics.py): ON by default —
    # counters/gauges/log-bucket histograms DERIVED from completions,
    # trace trees and quantum walls at window boundaries (the decode hot
    # path is untouched), token streams bit-identical either way
    # (tests/test_metrics.py) and <1% throughput (bench metrics_overhead).
    parser.add_argument("--no_metrics", action="store_true",
                        help="disable the metrics plane (mergeable "
                        "latency histograms, kind=\"metrics\"/\"slo\" "
                        "JSONL rows, snapshot files, tools/top.py feed)")
    parser.add_argument("--slo", type=str, default="",
                        help="declared service objectives, e.g. "
                        "\"ttft<=250ms@p99;tpot<=40ms@p95;e2e<=2s@p99\" "
                        "— parsed at startup (typos fail fast); each "
                        "window emits per-target compliance + error-"
                        "budget burn as kind=\"slo\" rows, gated by "
                        "report.py --min_slo_compliance")
    parser.add_argument("--metrics_dir", type=str, default="",
                        help="shared directory for atomic per-process/"
                        "per-replica metric snapshot files "
                        "(metrics-pNNNNN.json, heartbeat-file "
                        "discipline); process 0 publishes the bucket-"
                        "summed merge + OpenMetrics textfile beside them")
    return parser


def add_fleet_flags(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Fleet-router flags (round 19, tpukit/serve/fleet.py) — one spelling
    shared by main-serve.py and any harness that builds a `FleetConfig`
    from a CLI. `--replicas 0` (the default) keeps the single-engine
    round-14/15 path byte-untouched; >= 1 routes the stream through a
    FleetRouter over that many `ServeEngine` replicas, each on its own
    device subset (`--devices_per_replica`). Validation lives on
    FleetConfig, so misconfigurations fail with named errors at startup."""
    parser.add_argument("--replicas", type=int, default=0,
                        help="fleet mode: route the stream over this many "
                        "engine replicas (0 = the single-engine path)")
    parser.add_argument("--devices_per_replica", type=int, default=0,
                        help="devices per replica subset (each replica "
                        "grids its subset via pick_serve_grid); 0 = "
                        "meshless replicas on the default device")
    parser.add_argument("--min_replicas", type=int, default=1,
                        help="autoscale floor (scale-down never goes below)")
    parser.add_argument("--max_replicas", type=int, default=0,
                        help="autoscale ceiling; 0 = --replicas (no "
                        "scale-up headroom)")
    parser.add_argument("--scale_up_occupancy", type=float, default=0.0,
                        help="mean fleet slot occupancy above which a "
                        "window triggers a scale-up (0 disables)")
    parser.add_argument("--scale_down_occupancy", type=float, default=0.0,
                        help="mean fleet slot occupancy below which an "
                        "idle-queue window drains one replica (0 disables)")
    parser.add_argument("--fleet_window_steps", type=int, default=16,
                        help="fleet window cadence in dispatch rounds "
                        "(drives kind=\"fleet\" records AND the autoscale "
                        "check)")
    parser.add_argument("--disagg_prefill", action="store_true",
                        help="disaggregated prefill: a dedicated worker "
                        "runs chunked prefill and hands finished prefixes "
                        "to decode replicas as pages (requires --page_size)")
    parser.add_argument("--prefill_slots", type=int, default=0,
                        help="prefill worker lanes (0 = --slots)")
    parser.add_argument("--prefill_pages", type=int, default=0,
                        help="prefill worker pool pages (0 = the "
                        "--num_pages default)")
    parser.add_argument("--fleet_kill", type=str, default="",
                        help="deterministic serving chaos (one grammar "
                        "with --chaos_spec): replica_kill@R[:idx], "
                        "replica_sigkill@R[:idx] (real SIGKILL under "
                        "--fleet_procs), slow_replica@R:ms (heartbeat "
                        "stall), stuck_request@N (lane never finishes — "
                        "pair with --deadline_ms), ledger_io_fail@k:c "
                        "(IOError on ledger I/O occurrence k, c times)")
    parser.add_argument("--fleet_dir", type=str, default="",
                        help="durable fleet state directory: the request "
                        "ledger (write-ahead leases, exactly-once "
                        "completion records, stream replay on restart) "
                        "plus replica heartbeat files live here")
    parser.add_argument("--replica_timeout", type=float, default=0.0,
                        help="heartbeat liveness: declare a replica dead "
                        "when its beat file is older than this many "
                        "seconds — leases revoke, in-flight requests "
                        "requeue on survivors (0 disables; requires "
                        "--fleet_dir)")
    parser.add_argument("--request_retries", type=int, default=3,
                        help="per-request re-assignment budget after "
                        "replica deaths; exhaustion is a terminal NAMED "
                        "failure (reason=retry_budget), never a silent "
                        "kill/requeue loop")
    parser.add_argument("--max_queue_depth", type=int, default=0,
                        help="queue-depth backpressure: shed arrived "
                        "requests beyond this depth, lowest priority "
                        "first, as named request_rejected events "
                        "(0 = unbounded queue)")
    parser.add_argument("--deadline_ms", type=float, default=0.0,
                        help="per-request completion deadline applied to "
                        "the synthetic stream: a lane still decoding past "
                        "arrival+deadline is EVICTED with its partial "
                        "tokens (reason=\"deadline\", kind=deadline_miss "
                        "record; 0 = no deadlines)")
    parser.add_argument("--fleet_procs", action="store_true",
                        help="process fleet: run each replica as a real "
                        "worker PROCESS driven through the ledger "
                        "(requires --fleet_dir); replica_sigkill chaos "
                        "delivers a real SIGKILL and liveness comes from "
                        "process exit + heartbeat age")
    parser.add_argument("--fleet_worker", type=int, default=-1,
                        help="INTERNAL: run as ledger worker replica N "
                        "(set by the --fleet_procs supervisor when "
                        "re-execing itself; not for direct use)")
    return parser
