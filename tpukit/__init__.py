"""tpukit — a TPU-native distributed-training cookbook framework.

A ground-up JAX / XLA / pjit / Pallas re-design with the capabilities of the
reference cookbook (`vvvm23/distributed-pytorch-cookbook`): one GPT-style
decoder LM, one data pipeline, and five parallelism recipes (single-device,
data-parallel, fully-sharded, pipeline, pipeline x data-parallel) whose only
difference is the sharding strategy.

Unlike the reference — where parallelism is a model *wrapper* (DDP/FSDP/Pipe)
around an imperative torch module — tpukit expresses the model as a pure
function over a parameter pytree and expresses every parallelism strategy as a
`jax.sharding.Mesh` plus a set of `NamedSharding` rules (or, for the pipeline,
a `shard_map` + `lax.ppermute` schedule). XLA emits the collectives over ICI;
there is no NCCL, no process-group string, no RPC layer.
"""

__version__ = "0.1.0"

import os as _os

# Distributed-without-a-cluster: TPUKIT_CPU_DEVICES=N forces the CPU platform
# with N virtual devices so every mesh strategy (DP/FSDP/pipeline/2-D) can be
# driven from the recipe CLIs on one machine. Must happen before the first
# jax backend use; plain JAX_PLATFORMS env vars are not reliable on platforms
# whose PJRT plugin pins its own value, so set the config flags directly.
_cpu_devices = _os.environ.get("TPUKIT_CPU_DEVICES")
if _cpu_devices:
    # Belt and braces for jax versions without the jax_num_cpu_devices
    # config option (< 0.5): the XLA flag must be in the environment before
    # the backend initializes, and it is harmless alongside the config path.
    _flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={int(_cpu_devices)}"
        ).strip()

    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    try:
        _jax.config.update("jax_num_cpu_devices", int(_cpu_devices))
    except AttributeError:
        pass  # covered by the XLA_FLAGS fallback above

from tpukit.model import GPTConfig, TransformerDecoderLM  # noqa: F401
