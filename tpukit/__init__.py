"""tpukit — a TPU-native distributed-training cookbook framework.

A ground-up JAX / XLA / pjit / Pallas re-design with the capabilities of the
reference cookbook (`vvvm23/distributed-pytorch-cookbook`): one GPT-style
decoder LM, one data pipeline, and five parallelism recipes (single-device,
data-parallel, fully-sharded, pipeline, pipeline x data-parallel) whose only
difference is the sharding strategy.

Unlike the reference — where parallelism is a model *wrapper* (DDP/FSDP/Pipe)
around an imperative torch module — tpukit expresses the model as a pure
function over a parameter pytree and expresses every parallelism strategy as a
`jax.sharding.Mesh` plus a set of `NamedSharding` rules (or, for the pipeline,
a `shard_map` + `lax.ppermute` schedule). XLA emits the collectives over ICI;
there is no NCCL, no process-group string, no RPC layer.
"""

__version__ = "0.1.0"

import os as _os

# Distributed-without-a-cluster: TPUKIT_CPU_DEVICES=N forces the CPU platform
# with N virtual devices so every mesh strategy (DP/FSDP/pipeline/2-D) can be
# driven from the recipe CLIs on one machine. Must happen before the first
# jax backend use; plain JAX_PLATFORMS env vars are not reliable on platforms
# whose PJRT plugin pins its own value, so set the config flags directly.
_cpu_devices = _os.environ.get("TPUKIT_CPU_DEVICES")
if _cpu_devices:
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    _jax.config.update("jax_num_cpu_devices", int(_cpu_devices))

from tpukit.model import GPTConfig, TransformerDecoderLM  # noqa: F401
