"""Jittered exponential retry/backoff for transient host I/O.

At pod scale the host side of training talks to shared filesystems (NFS,
GCS-fuse) whose failure mode is the TRANSIENT error: a checkpoint write or
a dataset fetch that raises once and succeeds on the next attempt. The
reference (and tpukit before round 9) treated every such error as fatal —
one flaky `np.savez` killed a fleet-wide run that a 50 ms retry would have
saved. This module is the one retry policy every host I/O site shares:

  - `retry_io(fn, *args, label=...)` wraps one I/O operation: on a
    retryable exception it sleeps a jittered exponential backoff and tries
    again, up to the policy's budget, then FAILS LOUD by re-raising the
    last error (a retry wrapper that degrades into an infinite loop or a
    silent swallow is worse than no wrapper).
  - Retryable means host-I/O-shaped: `OSError` (IOError is its alias) and
    `TimeoutError`. Programming errors (TypeError, ValueError, KeyError)
    are never retried — retrying a bug just repeats it slower.
  - Every retry is OBSERVED: a module-level observer (installed by
    `fit()`) receives one event per failed attempt, which the trainer
    logs as a `kind="retry"` JSONL record and a flight-recorder entry, so
    "the run survived 14 transient NFS errors" is visible in the run
    summary instead of silently absorbed.

Wired sites (round 9): checkpoint blob/shard/manifest writes and reads —
sync writers AND the `AsyncCheckpointer` background half — and the
`DataLoader` batch fetch. The chaos harness (`tpukit/chaos.py`) injects
deterministic IOErrors inside these exact sites, so the retry path is
testable end to end without a flaky filesystem.

Thread-safety: `retry_io` runs on the training thread, the async
checkpoint writer thread, and the prefetch worker; the observer hook and
the event counter are lock-protected.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Any, Callable

# Exceptions worth a second attempt: transient host-I/O failures. OSError
# covers IOError (alias), filesystem errno failures, and socket errors.
RETRYABLE = (OSError, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded jittered-exponential backoff.

    `retries` is the number of RE-tries after the first attempt (so
    retries=3 means up to 4 attempts); 0 disables retrying (one attempt,
    fail loud). Delay before retry k (1-based) is
    `min(base_delay * 2**(k-1), max_delay)` scaled by a uniform jitter in
    `[1 - jitter, 1 + jitter]` — the decorrelation that keeps a pod's
    worth of processes from hammering a recovering filesystem in
    lockstep.
    """

    retries: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5

    def __post_init__(self):
        if self.retries < 0:
            raise ValueError(f"retries must be >= 0, got {self.retries}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry `attempt` (1-based)."""
        base = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        if self.jitter:
            base *= rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return base


_lock = threading.Lock()
_default_policy = RetryPolicy()
_observer: Callable[[dict], None] | None = None
# Per-PROCESS jitter stream: seeding with the pid is what decorrelates a
# pod's worth of ranks — a shared constant would have every rank draw the
# identical delay sequence and retry in lockstep, the thundering herd the
# jitter exists to prevent. (Replayability lives in the chaos harness, not
# in retry delays.)
_rng = random.Random(0x7E72 ^ os.getpid())


def set_default_policy(policy: RetryPolicy | None) -> RetryPolicy:
    """Install the process-wide default policy (fit() sets it from
    `--io_retries`); returns the previous one so callers can restore it."""
    global _default_policy
    with _lock:
        prev = _default_policy
        _default_policy = policy if policy is not None else RetryPolicy()
    return prev


def set_observer(fn: Callable[[dict], None] | None) -> None:
    """Install (or clear) the retry-event observer. Called with one dict
    per FAILED attempt: {label, attempt, retries, delay_s, error}. The
    observer must be thread-safe and must never raise (it is wrapped)."""
    global _observer
    with _lock:
        _observer = fn


def backoff_delay(attempt: int, policy: RetryPolicy | None = None) -> float:
    """One jittered-exponential delay for retry `attempt` (1-based),
    drawn from the process jitter stream under the given (or process
    default) policy — the spelling non-I/O retry loops share. Round 24's
    fleet router uses it to space request re-admissions after a replica
    death: requeues are retries of DISPATCH, not of an I/O call, so they
    can't ride retry_io, but they must not hammer the survivors in
    lockstep either."""
    with _lock:
        pol = policy if policy is not None else _default_policy
        return pol.delay(attempt, _rng)


def retry_io(
    fn: Callable[..., Any],
    *args,
    label: str = "io",
    policy: RetryPolicy | None = None,
    retryable: tuple = RETRYABLE,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
):
    """Run `fn(*args, **kwargs)`, retrying transient failures per `policy`
    (default: the process-wide policy). Re-raises the final error once the
    budget is spent — never returns a sentinel, never loops forever."""
    with _lock:
        pol = policy if policy is not None else _default_policy
        obs = _observer
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retryable as exc:
            attempt += 1
            if attempt > pol.retries:
                raise  # budget spent: fail loud with the real error
            with _lock:
                delay = pol.delay(attempt, _rng)
            if obs is not None:
                try:
                    obs(
                        {
                            "label": label,
                            "attempt": attempt,
                            "retries": pol.retries,
                            "delay_s": round(delay, 4),
                            "error": f"{type(exc).__name__}: {exc}",
                        }
                    )
                except Exception:
                    pass  # observability must never break the I/O path
            if delay > 0:
                sleep(delay)


class RetryLog:
    """Thread-safe collector of retry events — the observer `fit()`
    installs. The training thread drains it at window boundaries into the
    JSONL/flight-recorder; `total` survives draining for the run metrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self.total = 0

    def __call__(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)
            self.total += 1

    def drain(self) -> list[dict]:
        with self._lock:
            out, self._events = self._events, []
        return out
